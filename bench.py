"""Benchmark: CIFAR-10-class AutoML trial throughput on one chip.

Prints ONE JSON line:
  {"metric": "cifar10_automl_trials_per_hour", "value": N,
   "unit": "trials/hour/chip", "vs_baseline": R}

Method: measure steady-state bf16 training throughput (images/sec) and
evaluation throughput of the canonical workload — VGG16 (width 1.0,
batch 128) on CIFAR-shaped data (32x32x3) — on this chip, plus the
measured fixed per-trial overhead (advisor propose/feedback + params
dump). From those, compute the wall-clock of one canonical AutoML
trial (1 epoch over the 50,000-image CIFAR-10 train split + eval over
the 10,000-image test split) and report trials/hour.

vs_baseline: the 8xV100 reference baseline from BASELINE.md — the
reference publishes no numbers (BASELINE.json "published": {}), so the
documented estimate there is 120 trials/hour/GPU for this canonical
trial (V100 mixed-precision VGG16 CIFAR-10 ≈ 1.8k img/s → ~28s/epoch
+ eval + AutoML overhead ≈ 30s/trial). vs_baseline = value / 120,
i.e. the per-chip ratio; the v5e-8 vs 8xV100 pod ratio is the same
number. The north-star target is vs_baseline ≥ 8.
"""

from __future__ import annotations

import json
import time

import numpy as np

CANON_TRAIN = 50_000
CANON_EVAL = 10_000
BASELINE_TRIALS_PER_HOUR_PER_GPU = 120.0


def main() -> None:
    import jax
    import optax
    import jax.numpy as jnp

    from rafiki_tpu.models.vgg import _Vgg
    from rafiki_tpu.ops.train import TrainLoop, cross_entropy_loss

    batch = 128
    module = _Vgg(depth=16, width_mult=1.0, num_classes=10, dropout=0.1)

    def apply_fn(params, b, train=False, rng=None):
        kwargs = {"rngs": {"dropout": rng}} if rng is not None else {}
        return module.apply({"params": params}, b["x"], train=train, **kwargs)

    def init_fn(rng):
        return module.init(rng, jnp.zeros((1, 32, 32, 3)), train=False)["params"]

    def loss_fn(params, b, rng):
        logits = apply_fn(params, b, train=True, rng=rng)
        loss, acc = cross_entropy_loss(logits, b["y"])
        return loss, {"acc": acc}

    loop = TrainLoop(init_fn, apply_fn, loss_fn, optax.adam(1e-3), seed=0)

    rng = np.random.default_rng(0)
    b = {
        "x": rng.uniform(0, 1, size=(batch, 32, 32, 3)).astype(np.float32),
        "y": rng.integers(0, 10, size=(batch,)).astype(np.int32),
    }
    dev_b = loop.plan.put_batch(b)

    # -- train throughput (compile, warm up, then time) ---------------------
    # NOTE: hard-sync with device_get, not block_until_ready — on the
    # axon-tunnelled TPU the latter returns before execution finishes,
    # inflating throughput ~10x.
    t_compile0 = time.monotonic()
    loop.state, m = loop._train_step(loop.state, dev_b)
    float(jax.device_get(m["loss"]))
    compile_s = time.monotonic() - t_compile0
    for _ in range(5):
        loop.state, m = loop._train_step(loop.state, dev_b)
    float(jax.device_get(m["loss"]))
    steps = 100
    t0 = time.monotonic()
    for _ in range(steps):
        loop.state, m = loop._train_step(loop.state, dev_b)
    float(jax.device_get(m["loss"]))
    train_img_s = steps * batch / (time.monotonic() - t0)

    # -- eval throughput -----------------------------------------------------
    c, n = loop._eval_step(loop.state[0], dev_b)
    int(jax.device_get(c))
    t0 = time.monotonic()
    for _ in range(30):
        c, n = loop._eval_step(loop.state[0], dev_b)
    int(jax.device_get(c))
    eval_img_s = 30 * batch / (time.monotonic() - t0)

    # -- fixed per-trial overhead: advisor round + params dump --------------
    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.models.vgg import Vgg
    from flax import serialization

    adv = make_advisor(Vgg.get_knob_config(), kind="gp", seed=0)
    t0 = time.monotonic()
    for _ in range(3):
        knobs = adv.propose()
        adv.feedback(0.5, knobs)
    advisor_s = (time.monotonic() - t0) / 3
    t0 = time.monotonic()
    blob = serialization.to_bytes(jax.device_get(loop.params))
    dump_s = time.monotonic() - t0

    # The worker persists parameters on a background saver thread
    # (rafiki_tpu/worker/train.py _AsyncSaver), so in steady state a
    # trial's wall clock is max(compute, persist) — the dump overlaps
    # the NEXT trial's train+eval, not its own.
    compute_s = (CANON_TRAIN / train_img_s) + (CANON_EVAL / eval_img_s) + advisor_s
    trial_s = max(compute_s, dump_s)
    trials_per_hour = 3600.0 / trial_s
    out = {
        "metric": "cifar10_automl_trials_per_hour",
        "value": round(trials_per_hour, 2),
        "unit": "trials/hour/chip",
        "vs_baseline": round(trials_per_hour / BASELINE_TRIALS_PER_HOUR_PER_GPU, 3),
        "detail": {
            "train_img_per_s": round(train_img_s, 1),
            "eval_img_per_s": round(eval_img_s, 1),
            "canonical_trial_s": round(trial_s, 2),
            "compile_s": round(compile_s, 1),
            "advisor_s_per_trial": round(advisor_s, 3),
            "params_dump_s": round(dump_s, 3),
            "device": str(jax.devices()[0]),
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
