"""Benchmark: CIFAR-10-class AutoML trial throughput on one chip.

Prints ONE JSON line on stdout (always — a watchdog guarantees it even
on hangs; failures carry an "error" field with whatever was measured):

  {"metric": "cifar10_automl_trials_per_hour", "value": N,
   "unit": "trials/hour/chip", "vs_baseline": R, "detail": {...}}

Method — MEASURED, not extrapolated: the headline number comes from
running a real N-trial AutoML job end to end through LocalScheduler on
this chip — GP advisor proposing knobs, trials trained/evaluated/
persisted by the actual worker loop — and dividing trials by total
wall-clock. That wall-clock INCLUDES every XLA compile, advisor call,
dataset load and parameter dump the job performed (the round-2 bench
excluded a measured 12.8s/trial compile the framework then couldn't
amortize; the program cache + persistent compilation cache now
amortize it for real, and the number says so honestly).

Canonical workload (mirrors BASELINE.md acceptance configs 2-3): VGG16
width 1.0 on CIFAR-shaped synthetic data (50k train / 10k eval,
32x32x3, 10 classes), one epoch per trial; the GP sweeps lr, dropout
and batch size — the compile-relevant axis (batch) exercises the
program cache across its 3 shape buckets.

The task is calibrated to be NON-saturating so the accuracy clause is
falsifiable (scripts/calibrate_bench_task.py): 20% of labels are
flipped uniformly, capping a perfect classifier at (1-0.2)+0.2/10 =
0.82 top-1 regardless of scale, and pixel noise sigma=0.35 makes
1-epoch accuracy measurably lr/dropout-sensitive (smoke-scale
calibration 2026-07-30: good configs 0.71-0.77, bad configs at ~0.08
chance, spread ~0.7). ``best_top1 < top1_target`` flips the bench to
an error exit — a learning regression or an advisor steering into bad
regions turns the bench red instead of shaving the headline silently.
The canonical-scale target (0.70) is provisional pending a TPU
calibration run (`scripts/calibrate_bench_task.py --canonical`).

Also reported (detail): steady-state trials/hour (median over trials
that STARTED after the last program-cache miss — stragglers included;
null when no trial ran fully warm), wall_s_to_top1_target (first
wall-clock moment any trial crossed the accuracy target — the north
star's time-to-accuracy clause), cold (first-completed) and slowest
trial durations, per-step training throughput, TWO MFU figures vs the
v5e's 197 TFLOP/s bf16 peak (XLA whole-program flops AND analytic
conv+dense model flops; both null off-TPU), advisor cost measured
POST-GP-fit (>=30 observations), a GP-vs-random ``advisor_lift`` over
>=3 seeds with its dispersion, params dump time, program/compile-cache
statistics, and acceptance config 5 served BOTH ways: the
reference-shaped one-worker-per-trial ensemble and ServicesManager's
stacked top-k path (one vmapped XLA program). The artifact also embeds
``detail.telemetry`` — the unified telemetry snapshot
(rafiki_tpu/telemetry/): per-phase trial spans (advisor-propose /
build / train / evaluate / persist), program-cache hit/miss/eviction,
host-feed vs step time, and serving-path counters — so every headline
number decomposes into attributable spans.

vs_baseline: the 120 trials/hour/GPU denominator is an ESTIMATE
(BASELINE.md §Baseline derivation: V100 mixed-precision VGG16
CIFAR-10 ~1.8k img/s => ~28s epoch + eval + AutoML overhead ~30s per
canonical trial; the reference publishes no numbers). The per-chip
ratio equals the v5e-8 vs 8xV100 pod ratio. North star: >= 8.

``detail.trial_pack`` reports the packed-vs-serial microbench: k
same-program trials trained as one vmapped pack vs back-to-back serial
(docs/trial_packing.md), with the per-trial score parity delta. When
the TPU tunnel is down past the probe retries, the bench no longer
exits rc=1 with a zero artifact: it falls back to CPU, runs the
program-cache + packing microbench only, records ``detail.degraded``
with ``value``/``vs_baseline`` null, and exits 0 — the perf trajectory
keeps its honest, reduced data point.

``detail.goodput`` embeds the goodput/cost ledger (rafiki_tpu/obs/):
per-trial and per-pack wall split into compile / step / feed /
checkpoint / downtime buckets plus the job-level
``goodput = productive_step_s / wall_s`` ratio — present on BOTH the
full and the degraded artifact. ``detail.health`` (also on both
shapes) carries the numerics health totals — divergences, capsules,
evictions, contained trials, badput charged (docs/health.md) — so a
NaN epidemic is named in the artifact instead of surfacing only as a
throughput dip. The accuracy gate is calibrated for
the canonical TPU scale; on plain CPU runs a miss is recorded as
``detail.top1_note`` but stays advisory (rc 0) unless the target was
explicitly forced.

Env knobs: RAFIKI_BENCH_TRIALS (default 30), RAFIKI_BENCH_DEADLINE_S
(default 1500), RAFIKI_BENCH_PLATFORM=cpu (tiny smoke-scale run for
tests), RAFIKI_BENCH_SELFTEST_FAIL=1 (forced failure, tests the error
path), RAFIKI_BENCH_SELFTEST_DEGRADED=1 (forced CPU-fallback degraded
artifact, skips the probe retries).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import threading
import time

BASELINE_TRIALS_PER_HOUR_PER_GPU = 120.0  # estimate — BASELINE.md §Baseline derivation
V5E_BF16_PEAK_FLOPS = 197e12
CANON_TRAIN, CANON_EVAL = 50_000, 10_000

#: Artifact schema: 1 = the historical BENCH_r* shape (no marker);
#: 2 adds this field plus the ``headline`` block. Bump when a consumer
#: (scripts/bench_report.py) would need to branch on the shape.
BENCH_SCHEMA_VERSION = 2

_OUT = {
    "metric": "cifar10_automl_trials_per_hour",
    "value": 0.0,
    "unit": "trials/hour/chip",
    "vs_baseline": 0.0,
    "detail": {"baseline_basis": "120 trials/hour/GPU — ESTIMATE, derivation in BASELINE.md"},
}
_EMIT_LOCK = threading.Lock()
_emitted = False


def _emit(error: str | None = None) -> None:
    """Print the single JSON result line exactly once. The lock makes
    the watchdog wait out an in-flight normal emit instead of racing it
    (two lines / a truncated line would break the driver's parse).
    _emitted flips only AFTER a successful print: the watchdog can fire
    while the main thread is mutating detail, and a serialization error
    here must not eat the one emission the driver parses."""
    global _emitted
    with _EMIT_LOCK:
        if _emitted:
            return
        if error is not None:
            _OUT["error"] = error
        # Stamped here, not at detail-build time, so every artifact
        # shape (full, degraded, watchdog-partial, error) carries the
        # same headline block for scripts/bench_report.py to trend.
        # Older rounds spelled some keys differently — .get fallbacks,
        # absent keys trend as no-data rather than KeyError.
        d = _OUT.get("detail") or {}
        _OUT["schema_version"] = BENCH_SCHEMA_VERSION
        _OUT["headline"] = {
            "trials_per_hour": _OUT.get("value"),
            "canonical_trial_s": d.get("canonical_trial_s",
                                       d.get("canonical_compute_s")),
            "compile_s": d.get("compile_s", d.get("cold_trial_s")),
            "train_img_per_s": d.get("train_img_per_s"),
        }
        line = None
        for _ in range(3):
            try:
                line = json.dumps(_OUT)
                break
            except RuntimeError:  # detail mutated mid-serialize; retry
                time.sleep(0.05)
        if line is None:  # last resort: drop the racing detail dict
            line = json.dumps({k: v for k, v in _OUT.items() if k != "detail"})
        print(line, flush=True)
        _emitted = True


def _watchdog(deadline_s: float):
    def fire():
        try:
            _emit(error=f"deadline exceeded ({deadline_s:.0f}s); partial detail included")
        finally:
            # stdout is delivered; nothing graceful left to do.
            os._exit(3)

    t = threading.Timer(deadline_s, fire)
    t.daemon = True
    t.start()
    return t


# -- backend ----------------------------------------------------------------


def _probe_backend_subprocess(timeout_s: float) -> tuple[bool, str]:
    """Check device availability in a THROWAWAY subprocess: jax backend
    init has no timeout and hangs indefinitely when the TPU tunnel is
    down (BENCH_r01's failure mode), and a hung thread can't be
    cancelled — a subprocess can."""
    code = ("import jax; d = jax.devices(); "
            "print(d[0].platform, len(d))")
    try:
        r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                           text=True, timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, "backend probe timed out (TPU tunnel down?)"
    if r.returncode != 0:
        return False, f"backend probe rc={r.returncode}: {r.stderr.strip()[-400:]}"
    return True, r.stdout.strip()


def _init_backend() -> "tuple[str, str | None]":
    """Retry-with-backoff backend init. Returns (platform, degraded):
    ``degraded`` is None on the requested backend, or the reason string
    when the TPU probe exhausted its retries and the bench fell back to
    CPU — the caller then runs the reduced (microbench-only) artifact
    instead of exiting rc=1 with zero values (BENCH_r01–r05's gap)."""
    if os.environ.get("RAFIKI_BENCH_SELFTEST_FAIL"):
        raise RuntimeError("selftest: forced backend failure")
    from rafiki_tpu.utils.backend import force_cpu_backend, honor_env_platform

    if os.environ.get("RAFIKI_BENCH_SELFTEST_DEGRADED"):
        # Test hook: exercise the degraded CPU-fallback artifact without
        # waiting out the real probe's ~460s retry budget.
        force_cpu_backend()
        import jax

        return (jax.devices()[0].platform,
                "selftest: forced degraded fallback")
    if os.environ.get("RAFIKI_BENCH_PLATFORM", "").lower() == "cpu":
        force_cpu_backend()
        import jax

        return jax.devices()[0].platform, None
    if honor_env_platform():  # JAX_PLATFORMS=cpu: skip the TPU probe
        import jax

        return jax.devices()[0].platform, None
    # ~460s worst-case probe budget: leaves ~1000s of the default
    # 1500s deadline for the measured run if the tunnel recovers late.
    delays = [0, 10, 30, 60]
    last = ""
    for d in delays:
        if d:
            time.sleep(d)
        ok, msg = _probe_backend_subprocess(timeout_s=90)
        last = msg
        if ok:
            import jax

            return jax.devices()[0].platform, None
    force_cpu_backend()
    import jax

    return (jax.devices()[0].platform,
            f"backend unavailable after {len(delays)} attempts: {last}; "
            f"CPU fallback — headline unmeasured, microbench only")


# -- canonical bench model ---------------------------------------------------
#
# The canonical trial fixes the architecture (VGG16 width 1.0, 1 epoch
# — the unit the 120/hour baseline estimate prices) and sweeps the
# tuning axes: lr (log), dropout, batch size. Source form because the
# scheduler loads model templates from uploaded bytes, same as users do.

BENCH_MODEL_SRC = b'''
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob
from rafiki_tpu.models.vgg import Vgg, _Vgg


class BenchVgg(Vgg):
    """Canonical-trial VGG16: fixed arch, tunable lr/dropout/batch."""

    @staticmethod
    def get_knob_config():
        return {
            "depth": FixedKnob(16),
            "width_mult": FixedKnob(1.0),
            "dropout": FloatKnob(0.0, 0.5),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([64, 128, 256], affects_shape=True),
            "epochs": FixedKnob(1),
            "seed": FixedKnob(0),
        }
'''

BENCH_MODEL_SRC_SMOKE = b'''
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob
from rafiki_tpu.models.vgg import Vgg, _Vgg


class BenchVgg(Vgg):
    """Smoke-scale canonical trial for CPU test runs."""

    @staticmethod
    def get_knob_config():
        return {
            "depth": FixedKnob(11),
            "width_mult": FixedKnob(0.25),
            "dropout": FloatKnob(0.0, 0.5),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([64, 128], affects_shape=True),
            "epochs": FixedKnob(1),
            "seed": FixedKnob(0),
        }
'''


def _scale(platform: str) -> dict:
    # noise/flip and the per-scale top1 targets come from
    # scripts/calibrate_bench_task.py (see module docstring): flip=0.2
    # puts the accuracy ceiling at 0.82; targets sit below the measured
    # good-config scores and well above the ~0.1 chance floor.
    common = dict(noise=0.35, flip=0.2, lift_trials=12, lift_warmup=4,
                  lift_seeds=3, platform=platform)
    # One knob read, mode-specific fallbacks: RAFIKI_BENCH_TRIALS set
    # overrides both scales; unset, cpu smokes at 3 and tpu runs 30.
    env_trials = os.environ.get("RAFIKI_BENCH_TRIALS")
    if platform == "cpu":  # smoke run for tests: seconds, not minutes
        return dict(src=BENCH_MODEL_SRC_SMOKE, train_n=2048, eval_n=512,
                    w=8, trials=int(env_trials) if env_trials else 3,
                    micro_steps=5, canon_train=2048, canon_eval=512,
                    micro=dict(depth=11, width=0.25, batch=64),
                    top1_target=0.30, **common)
    return dict(src=BENCH_MODEL_SRC, train_n=CANON_TRAIN, eval_n=CANON_EVAL,
                w=32, trials=int(env_trials) if env_trials else 30,
                micro_steps=100, canon_train=CANON_TRAIN, canon_eval=CANON_EVAL,
                micro=dict(depth=16, width=1.0, batch=128),
                top1_target=0.70, **common)


# -- the real AutoML loop (headline) ----------------------------------------


def run_real_loop(sc: dict, detail: dict) -> None:
    from rafiki_tpu.scheduler import LocalScheduler
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.ops.train import program_cache_stats

    train_uri = (f"synthetic://images?classes=10&n={sc['train_n']}"
                 f"&w={sc['w']}&h={sc['w']}&c=3&seed=0"
                 f"&noise={sc['noise']}&flip={sc['flip']}")
    val_uri = (f"synthetic://images?classes=10&n={sc['eval_n']}"
               f"&w={sc['w']}&h={sc['w']}&c=3&seed=1"
               f"&noise={sc['noise']}&flip={sc['flip']}")
    import shutil

    tmp = tempfile.mkdtemp(prefix="rafiki-bench-")
    try:
        store = MetaStore(os.path.join(tmp, "meta.sqlite3"))
        params = ParamsStore(os.path.join(tmp, "params"))
        model = store.create_model("bench-vgg", "IMAGE_CLASSIFICATION", None,
                                   sc["src"], "BenchVgg")
        job = store.create_train_job("bench", "IMAGE_CLASSIFICATION", None,
                                     train_uri, val_uri,
                                     {"MODEL_TRIAL_COUNT": sc["trials"]})
        store.create_sub_train_job(job["id"], model["id"])

        cache0 = program_cache_stats()
        wall0 = time.time()  # epoch clock, comparable to trial rows
        t0 = time.monotonic()
        result = LocalScheduler(store, params).run_train_job(
            job["id"], n_workers=1, advisor_kind="gp")
        # lint: disable=RF007 — headline wall-clock, reported in the artifact
        wall = time.monotonic() - t0
        cache1 = program_cache_stats()
        if result.best_trials:
            # Acceptance config 5 (BASELINE.md): serve the top-k trials
            # behind the predictor/bus and measure query throughput —
            # both the per-trial-worker path and the stacked path.
            try:
                _measure_serving(store, params, result, sc, detail)
            except Exception as e:  # serving metrics are additive, not fatal
                detail["serving_error"] = f"{type(e).__name__}: {e}"
    finally:
        shutil.rmtree(tmp, ignore_errors=True)

    done = [t for t in result.trials if t["status"] == "COMPLETED"]
    # In completion order: the first trial to finish paid the cold
    # compiles; later "slow" trials are stragglers, a different fact.
    timed = sorted((t for t in done
                    if t.get("stopped_at") and t.get("started_at")),
                   key=lambda t: t["stopped_at"])
    durations = [t["stopped_at"] - t["started_at"] for t in timed]
    per_trial = sorted(durations)
    # Steady state = trials that ran ENTIRELY after the last cold
    # compile (started after the final program-cache miss), stragglers
    # included — the r4 "median of the fastest half" definition
    # excluded stragglers by construction and flattered the claim.
    # None when no trial ran fully warm (honest: no steady evidence).
    last_miss = cache1.get("last_miss_ts", 0.0)
    warm = sorted(t["stopped_at"] - t["started_at"] for t in timed
                  if t["started_at"] > last_miss)
    steady_s = warm[len(warm) // 2] if warm else None

    best_top1 = max((t["score"] for t in done), default=None)
    # North-star clause 2 analog: first wall-clock moment any trial's
    # score crossed the target, measured from job submission.
    hits = [t["stopped_at"] for t in done
            if t.get("score") is not None and t.get("stopped_at")
            and t["score"] >= sc["top1_target"]]
    wall_to_target = round(min(hits) - wall0, 2) if hits else None
    detail.update({
        "measured_trials": len(done),
        "errored_trials": len(result.trials) - len(done),
        "n_workers": 1,
        "job_wall_s": round(wall, 2),
        "measured_trials_per_hour": round(3600.0 * len(done) / wall, 2),
        "cold_trial_s": round(durations[0], 2) if durations else None,
        "slowest_trial_s": round(per_trial[-1], 2) if per_trial else None,
        "steady_trial_s": round(steady_s, 3) if steady_s is not None else None,
        "steady_trials_n": len(warm),
        "steady_trials_per_hour": (round(3600.0 / steady_s, 2)
                                   if steady_s else None),
        "wall_s_to_top1_target": wall_to_target,
        "best_top1": best_top1,
        "top1_target": sc["top1_target"],
        "top1_ceiling": round((1 - sc["flip"]) + sc["flip"] / 10, 3),
        "top1_miss": best_top1 is None or best_top1 < sc["top1_target"],
        "programs_compiled": cache1["misses"] - cache0["misses"],
        "program_cache_hits": cache1["hits"] - cache0["hits"],
        "job_status": result.status,
    })
    if result.status != "COMPLETED":
        raise RuntimeError(f"bench job ended {result.status}: {result.errors[:2]}")
    _OUT["value"] = detail["measured_trials_per_hour"]
    _OUT["vs_baseline"] = round(_OUT["value"] / BASELINE_TRIALS_PER_HOUR_PER_GPU, 3)


def _predict_ok(out) -> bool:
    return not any(isinstance(o, dict) and "error" in o for o in out)


def _measure_qps(pred, queries, rounds: int = 5,
                 warm_deadline_s: float = 120) -> tuple:
    """(qps, batch_latency_ms) through a live Predictor. Warm until the
    predict program has actually compiled: the first forward can exceed
    the predictor's timeout, which surfaces as {"error": ...} entries
    rather than an exception — those must never count as served."""
    deadline = time.monotonic() + warm_deadline_s
    while not _predict_ok(pred.predict(queries[:8])):
        if time.monotonic() > deadline:
            raise RuntimeError("predict never warmed (timeouts only)")
        time.sleep(1)
    t0 = time.monotonic()
    for _ in range(rounds):
        out = pred.predict(queries)
        if not _predict_ok(out):
            raise RuntimeError("timeout/error response during timed rounds")
    # lint: disable=RF007 — QPS denominator, reported in the artifact
    dt = time.monotonic() - t0
    assert len(out) == len(queries)
    return (round(rounds * len(queries) / dt, 1), round(1000.0 * dt / rounds, 1))


def _measure_serving(store, params, result, sc: dict, detail: dict) -> None:
    """Acceptance config 5 (BASELINE.md): predictor ensemble over the
    top-k trained models. The REAL top-2 trials are served both ways
    and both throughputs reported: (a) the reference-shaped fallback —
    one InferenceWorker per trial, the predictor scatter/gathers and
    mean-ensembles — and (b) through ServicesManager's stacked
    selection (admin/services_manager.py), where same-architecture
    trials fuse into ONE vmapped XLA program (parallel/serving.py).
    ``serving_path`` records which path the services manager actually
    engaged; ``serving_k`` the ensemble width."""
    import threading

    import numpy as np

    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.predictor.predictor import Predictor
    from rafiki_tpu.worker.inference import InferenceWorker

    best = result.best_trials[:2]
    detail["serving_k"] = len(best)
    cls = load_model_class(sc["src"], "BenchVgg")
    rng = np.random.default_rng(0)
    queries = list(rng.uniform(0, 1, size=(64, sc["w"], sc["w"], 3))
                   .astype(np.float32))

    # (a) one worker per trial: predictor fans out to k workers and
    # ensembles — the reference's serving shape.
    bus = InProcBus()
    models = []
    for t in best:
        m = cls(**t["knobs"])
        m.load_parameters(params.load(t["params_id"]))
        models.append(m)
    workers = [InferenceWorker(bus, "bench-fb", f"iw-{i}", m)
               for i, m in enumerate(models)]
    threads = [threading.Thread(target=w.run, daemon=True) for w in workers]
    for th in threads:
        th.start()
    try:
        deadline = time.monotonic() + 60
        while len(bus.get_workers("bench-fb")) < len(workers):
            if time.monotonic() > deadline:
                raise RuntimeError("inference workers never registered")
            time.sleep(0.05)
        qps, lat = _measure_qps(Predictor(bus, "bench-fb"), queries)
        detail["serving_qps_per_worker"] = qps
        detail["serving_batch_latency_ms"] = lat
    finally:
        for w in workers:
            w.stop()
        for th in threads:
            th.join(timeout=10)
        for m in models:
            m.destroy()

    if len(best) < 2:
        detail["serving_path"] = "per-trial (k=1)"
        return
    # (b) the stacked path, through the real services manager: it
    # re-loads the trial models itself and fuses them when stackable.
    from rafiki_tpu.admin.services_manager import ServicesManager

    inf = store.create_inference_job(result.job_id, None)
    sm = ServicesManager(store, params)
    pred = sm.create_inference_services(inf["id"], best, serve_http=False)
    try:
        handle = sm._inference_jobs[inf["id"]]
        path = ("stacked" if len(handle.workers) < len(best)
                else "per-trial-fallback")
        detail["serving_path"] = path
        qps, lat = _measure_qps(pred, queries)
        if path == "stacked":
            detail["serving_qps_stacked"] = qps
            detail["serving_batch_latency_stacked_ms"] = lat
        else:  # heterogeneous top-k: record it honestly, don't relabel
            detail["serving_qps_fallback_via_services_manager"] = qps
            detail["serving_batch_latency_fallback_ms"] = lat
    finally:
        sm.stop_inference_services(inf["id"])


# -- trial packing: packed-vs-serial microbench ------------------------------

PACK_MODEL_SRC = b'''
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.ff import _Mlp


class PackFF(JaxModel):
    """Fixed-shape FF for the trial-pack microbench: every lr shares
    one program key, so k trials always bucket into one pack."""

    @staticmethod
    def get_knob_config():
        return {
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
            "batch_size": FixedKnob(64),
            "epochs": FixedKnob(2),
            "seed": FixedKnob(0),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=2, hidden_units=128, num_classes=num_classes)
'''


def run_trial_pack_micro(sc: dict, detail: dict) -> None:
    """Packed-vs-serial trial throughput (docs/trial_packing.md): k
    same-program trials trained once back-to-back serially and once as
    a single vmapped pack, both WARM (each path's programs compiled by
    a throwaway round first — this measures the steady state the
    packing lever targets, not compile amortization, which is the
    program cache's own detail block). ``max_score_delta`` doubles as
    a parity check: packed per-trial scores must match serial ones."""
    from rafiki_tpu.model.base import load_model_class

    cls = load_model_class(PACK_MODEL_SRC, "PackFF")
    train = (f"synthetic://images?classes=10&n=2048&w=8&h=8&c=3&seed=0"
             f"&noise={sc['noise']}&flip={sc['flip']}")
    val = (f"synthetic://images?classes=10&n=512&w=8&h=8&c=3&seed=1"
           f"&noise={sc['noise']}&flip={sc['flip']}")
    k, epochs = 4, 2
    lrs = [3e-3, 1e-2, 3e-2, 1e-3]

    def serial_once() -> list:
        scores = []
        for lr in lrs:
            m = cls(learning_rate=lr)
            m.train(train)
            scores.append(float(m.evaluate(val)))
            m.destroy()
        return scores

    def packed_once() -> list:
        models = [cls(learning_rate=lr) for lr in lrs]
        cls.train_packed(models, train)
        scores = cls.evaluate_packed(models, val)
        for m in models:
            m.destroy()
        return scores

    serial_once()
    packed_once()  # both compiled programs now warm
    t0 = time.monotonic()
    s_serial = serial_once()
    # lint: disable=RF007 — packed-vs-serial A/B wall, reported in detail
    serial_s = time.monotonic() - t0
    t0 = time.monotonic()
    s_packed = packed_once()
    # lint: disable=RF007 — packed-vs-serial A/B wall, reported in detail
    packed_s = time.monotonic() - t0
    detail["trial_pack"] = {
        "k": k,
        "epochs": epochs,
        "serial_s": round(serial_s, 3),
        "serial_s_per_trial": round(serial_s / k, 3),
        "packed_s": round(packed_s, 3),
        "packed_s_per_trial": round(packed_s / k, 3),
        "speedup_vs_serial": round(serial_s / packed_s, 2),
        "max_score_delta": round(max(abs(a - b)
                                     for a, b in zip(s_serial, s_packed)), 4),
    }


# -- advisor lift: GP vs random on tiny real trials --------------------------

LIFT_MODEL_SRC = b'''
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.vgg import Vgg


class LiftVgg(Vgg):
    """Tiny real-training probe for GP-vs-random lift: one shape
    bucket (fixed batch), wide log-lr axis where quality varies."""

    @staticmethod
    def get_knob_config():
        return {
            "depth": FixedKnob(11),
            "width_mult": FixedKnob(0.25),
            "dropout": FloatKnob(0.0, 0.5),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": FixedKnob(64),
            "epochs": FixedKnob(1),
            "seed": FixedKnob(0),
        }
'''


def run_advisor_lift(sc: dict, detail: dict) -> None:
    """GP-vs-random lift from tiny-but-real trials on the calibrated
    task (the knob space is where 1-epoch top-1 demonstrably varies —
    see scripts/calibrate_bench_task.py). Both advisors run the same
    trial count with fixed seeds; ``advisor_lift`` = mean post-warmup
    GP score minus the random advisor's mean over the same positions —
    the exploitation the GP buys once it has observations. Kept tiny
    (VGG11 w=0.25 on 8x8) so it costs seconds, not the headline's
    minutes; the full-size advisor quality signal is the headline
    job's gated best_top1."""
    from rafiki_tpu.advisor.gp import GpAdvisor
    from rafiki_tpu.advisor.random_advisor import RandomAdvisor
    from rafiki_tpu.model.base import load_model_class

    cls = load_model_class(LIFT_MODEL_SRC, "LiftVgg")
    train = (f"synthetic://images?classes=10&n=2048&w=8&h=8&c=3&seed=0"
             f"&noise={sc['noise']}&flip={sc['flip']}")
    val = (f"synthetic://images?classes=10&n=512&w=8&h=8&c=3&seed=1"
           f"&noise={sc['noise']}&flip={sc['flip']}")
    n, warmup = sc["lift_trials"], sc["lift_warmup"]

    def sweep(advisor) -> list:
        scores = []
        for _ in range(n):
            knobs = advisor.propose()
            m = cls(**knobs)
            m.train(train)
            s = float(m.evaluate(val))
            m.destroy()
            advisor.feedback(s, knobs)
            scores.append(round(s, 4))
        return scores

    kc = cls.get_knob_config()
    mean = lambda xs: sum(xs) / len(xs)
    # >=3 seeds with dispersion (r4 directive 8): a one-seed lift at
    # smoke scale is within noise; the claim must carry its spread.
    lifts, best_lifts = [], []
    diffs, gp_scores = [], []
    t0 = time.monotonic()
    for s in range(sc["lift_seeds"]):
        s_gp = sweep(GpAdvisor(kc, seed=s, n_initial=warmup))
        s_rnd = sweep(RandomAdvisor(kc, seed=100 + s))
        lifts.append(round(mean(s_gp[warmup:]) - mean(s_rnd[warmup:]), 4))
        best_lifts.append(round(max(s_gp) - max(s_rnd), 4))
        # position-paired post-warmup diffs, pooled across seeds: the
        # bootstrap resamples these, so the CI reflects both seed and
        # position noise (docs/search_anatomy.md).
        diffs.extend(g - r for g, r in zip(s_gp[warmup:], s_rnd[warmup:]))
        gp_scores.extend(s_gp)
    # lint: disable=RF007 — sweep A/B wall, reported in detail.search
    sweep_wall_s = time.monotonic() - t0
    m_lift = mean(lifts)
    spread = max(abs(l - m_lift) for l in lifts)
    detail["advisor_lift"] = round(m_lift, 4)
    detail["advisor_lift_spread"] = round(spread, 4)
    detail["advisor_lift_per_seed"] = lifts
    # significant only when the whole dispersion band clears zero
    detail["advisor_lift_significant"] = (m_lift - spread) > 0
    detail["advisor_lift_best"] = round(mean(best_lifts), 4)
    detail["advisor_lift_trials"] = n * sc["lift_seeds"]
    # Search-anatomy block: the same lift claim with a bootstrap CI
    # (fixed seed — byte-reproducible across runs on the same scores),
    # plus the probe sweep's regret curve and effective throughput so
    # bench_report --sweep can trend them from SWEEP_r*.json siblings.
    from rafiki_tpu.obs.search import stats as search_stats

    ci = search_stats.bootstrap_ci(diffs, seed=0)
    curve = search_stats.regret_curve(gp_scores)
    n_scored = 2 * n * sc["lift_seeds"]
    detail["search"] = {
        "advisor_lift": round(ci["mean"], 4),
        "lift_ci_low": round(ci["lo"], 4),
        "lift_ci_high": round(ci["hi"], 4),
        "lift_significant": ci["lo"] > 0,
        "n_diffs": ci["n"],
        "n_boot": ci["n_boot"],
        "boot_seed": ci["seed"],
        "best_score": curve["best_score"],
        "regret": curve["mean_regret"],
        "n_scored": n_scored,
        "sweep_wall_s": round(sweep_wall_s, 3),
        "effective_trials_per_hour": round(
            n_scored / sweep_wall_s * 3600.0, 2) if sweep_wall_s else 0.0,
    }
    # Curve-advisor plane (docs/early_kill.md): the probe sweep above
    # never kills (no epoch loop), so these are 0 here — but headline
    # runs under RAFIKI_CURVE_KILL pick up the session's counters, and
    # bench_report --sweep trends them alongside the throughput claim.
    from rafiki_tpu.obs.search.ledger import search_ledger

    snap = search_ledger.snapshot()
    for k in ("n_killed", "n_false_kills", "n_speculations",
              "n_corrections"):
        detail["search"][k] = snap.get(k, 0)


# -- microbench: step throughput, MFU, advisor, dump ------------------------


def _vgg_train_flops_per_image(depth: int, width_mult: float, w: int,
                               num_classes: int = 10) -> float:
    """Analytic conv+dense flops (2*MACs) for one image's forward pass
    through ``models/vgg._Vgg``, tripled for the train step (backward
    ~= 2x forward for conv/dense — the conventional model-flops MFU
    numerator, vs XLA's whole-program count which also bills norms,
    pooling, optimizer update and padding)."""
    from rafiki_tpu.models.vgg import _CFGS

    h = wd = w
    cin, fwd = 3, 0.0
    for v in _CFGS[depth]:
        if v == "M":
            if min(h, wd) >= 2:
                h, wd = h // 2, wd // 2
            continue
        cout = max(8, int(v * width_mult))
        fwd += 2.0 * h * wd * cout * cin * 9  # 3x3 SAME conv
        cin = cout
    d1 = max(64, int(512 * width_mult))
    fwd += 2.0 * (h * wd * cin) * d1
    fwd += 2.0 * d1 * num_classes
    return 3.0 * fwd


def run_micro(sc: dict, detail: dict) -> None:
    import jax
    import numpy as np

    from rafiki_tpu.models.vgg import Vgg

    m = sc["micro"]
    batch = m["batch"]
    model = Vgg(depth=m["depth"], width_mult=m["width"], dropout=0.1,
                learning_rate=1e-3, batch_size=batch, epochs=1, seed=0)
    tiny = (f"synthetic://images?classes=10&n={max(batch * 2, 256)}"
            f"&w={sc['w']}&h={sc['w']}&c=3&seed=0")
    # NOTE: run_micro executes AFTER run_real_loop on purpose — the
    # other order would pre-warm the persistent XLA cache with the
    # canonical HLO and the "compile-inclusive" headline would never
    # pay the real cold compile. Here the caches are fair game: micro
    # numbers are steady-state throughputs.
    model.train(tiny)

    loop = model._loop
    rng = np.random.default_rng(0)
    b = {"x": rng.uniform(0, 1, size=(batch, sc["w"], sc["w"], 3)).astype(np.float32),
         "y": rng.integers(0, 10, size=(batch,)).astype(np.int32)}
    dev_b = loop.plan.put_batch(b)
    # hard-sync with device_get, not block_until_ready — on the
    # axon-tunnelled TPU the latter returns before execution finishes.
    loop.state, mt = loop._train_step(loop.state, dev_b)
    float(jax.device_get(mt["loss"]))
    steps = sc["micro_steps"]
    t0 = time.monotonic()
    for _ in range(steps):
        loop.state, mt = loop._train_step(loop.state, dev_b)
    float(jax.device_get(mt["loss"]))
    # lint: disable=RF007 — steady-state step timing, the microbench output
    step_s = (time.monotonic() - t0) / steps
    train_img_s = batch / step_s

    c, n = loop._eval_step(loop.state[0], dev_b)
    int(jax.device_get(c))
    t0 = time.monotonic()
    for _ in range(max(10, steps // 3)):
        c, n = loop._eval_step(loop.state[0], dev_b)
    int(jax.device_get(c))
    # lint: disable=RF007 — steady-state eval timing, the microbench output
    eval_img_s = max(10, steps // 3) * batch / (time.monotonic() - t0)

    # MFU only means something on the hardware whose peak is the
    # denominator: off-TPU both fields are null, not a rounded 0.0
    # (r4 verdict, Weak #2). Gate on != "cpu", not == "tpu": this
    # image's PJRT plugin registers the TPU as platform "axon", and the
    # == "tpu" form silently nulled MFU on every green-window run.
    on_tpu = sc["platform"] != "cpu"
    mfu = mfu_model = None
    if on_tpu:
        try:  # whole-program flops from XLA's own cost model
            compiled = loop._train_step.lower(loop.state, dev_b).compile()
            ca = compiled.cost_analysis()
            ca = ca[0] if isinstance(ca, (list, tuple)) else ca
            flops = float(ca.get("flops", 0.0))
            if flops > 0:
                mfu = flops / step_s / V5E_BF16_PEAK_FLOPS
        except Exception:
            pass
        step_model_flops = _vgg_train_flops_per_image(
            m["depth"], m["width"], sc["w"]) * batch
        mfu_model = step_model_flops / step_s / V5E_BF16_PEAK_FLOPS

    t0 = time.monotonic()
    blob = model.dump_parameters()
    # lint: disable=RF007 — params dump timing, reported in detail
    dump_s = time.monotonic() - t0

    detail.update({
        "train_img_per_s": round(train_img_s, 1),
        "eval_img_per_s": round(eval_img_s, 1),
        "params_dump_s": round(dump_s, 3),
        "params_blob_mb": round(len(blob) / 1e6, 1),
        "mfu_vs_v5e_bf16_peak": round(mfu, 4) if mfu is not None else None,
        "mfu_model_flops": round(mfu_model, 4) if mfu_model is not None else None,
        "mfu_basis": ("mfu_vs_v5e_bf16_peak: XLA whole-program flops — "
                      "overstates vs model-flops MFU; mfu_model_flops: "
                      "analytic conv+dense fwd+bwd; both null off-TPU"),
        "canonical_compute_s": round(
            sc["canon_train"] / train_img_s + sc["canon_eval"] / eval_img_s, 2),
    })
    model.destroy()

    # Advisor cost in steady state: measured AFTER the GP has real fits
    # (>=30 observations) — the random warmup phase costs ~0 and would
    # understate it.
    from rafiki_tpu.advisor import make_advisor
    from rafiki_tpu.model.base import load_model_class

    cls = load_model_class(sc["src"], "BenchVgg")
    adv = make_advisor(cls.get_knob_config(), kind="gp", seed=0)
    obs_rng = np.random.default_rng(1)
    for _ in range(32):
        knobs = adv.propose()
        adv.feedback(float(obs_rng.uniform(0.3, 0.9)), knobs)
    t0 = time.monotonic()
    rounds = 5
    for _ in range(rounds):
        knobs = adv.propose()
        adv.feedback(0.5, knobs)
    # lint: disable=RF007 — advisor cost measurement, reported in detail
    detail["advisor_s_per_trial_at_30obs"] = round((time.monotonic() - t0) / rounds, 4)


def _goodput_snapshot() -> dict:
    """The goodput ledger's per-entity split (compile/step/feed/
    checkpoint/downtime + goodput ratio), rounded for the artifact."""
    from rafiki_tpu.obs.ledger import ledger

    snap = ledger.snapshot()

    def _round(d):
        return {k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in d.items()}

    return {
        "entities": {name: _round(e)
                     for name, e in snap.get("entities", {}).items()},
        "total": _round(snap.get("total", {})),
        "goodput": (round(snap["goodput"], 4)
                    if snap.get("goodput") is not None else None),
    }


def _health_snapshot() -> dict:
    """Numerics health totals for the artifact: divergences caught,
    capsules banked, pack evictions, contained trials, and the
    wall-clock those divergences burned (already inside badput_s)."""
    from rafiki_tpu.obs import health

    return dict(health.stats())


def main() -> None:
    deadline = float(os.environ.get("RAFIKI_BENCH_DEADLINE_S", "1500"))
    wd = _watchdog(deadline)
    detail = _OUT["detail"]
    try:
        platform, degraded = _init_backend()
        # Always recorded, even on failure paths below: a green-window
        # artifact with mfu null must say WHICH platform produced it.
        detail["platform"] = platform
        from rafiki_tpu.utils.backend import enable_compilation_cache

        detail["xla_cache_dir"] = enable_compilation_cache()
        import jax

        detail["device"] = str(jax.devices()[0])
        detail["device_kind"] = getattr(jax.devices()[0], "device_kind", "")
        # Test hook: deterministic stall for the watchdog test (the
        # real run's duration depends on cache warmth).
        stall = float(os.environ.get("RAFIKI_BENCH_SELFTEST_SLEEP_S", "0"))
        if stall:
            time.sleep(stall)
        sc = _scale(platform)
        if os.environ.get("RAFIKI_BENCH_TOP1_TARGET"):  # tests force the red path
            sc["top1_target"] = float(os.environ["RAFIKI_BENCH_TOP1_TARGET"])
        detail["n_trials_requested"] = sc["trials"]
        from rafiki_tpu import telemetry

        if degraded:
            # TPU tunnel down: the headline is unmeasurable, but a
            # zero-value rc=1 artifact leaves the perf trajectory empty
            # (BENCH_r01–r05). Measure what a CPU honestly can — the
            # program-cache + trial-packing microbench — mark the
            # artifact degraded, null the baseline ratio, exit green.
            detail["degraded"] = degraded
            try:
                # The reduced microbench must not turn the degraded
                # artifact back into an rc=1 zero (BENCH_r03–r05's
                # regression shape): a CPU-side failure here is recorded
                # and the artifact still ships green.
                from rafiki_tpu.obs.ledger import ledger

                with ledger.entity("bench:micro"):
                    run_trial_pack_micro(sc, detail)
            except Exception as micro_e:
                detail["degraded_micro_error"] = (
                    f"{type(micro_e).__name__}: {micro_e}")
            from rafiki_tpu.ops.train import program_cache_stats

            detail["program_cache"] = program_cache_stats()
            detail["goodput"] = _goodput_snapshot()
            detail["health"] = _health_snapshot()
            detail["telemetry"] = telemetry.snapshot()
            _OUT["value"] = None
            _OUT["vs_baseline"] = None
            _emit()
            wd.cancel()
            return

        from rafiki_tpu.obs.ledger import ledger

        run_real_loop(sc, detail)  # first: its compiles must be COLD
        # Embed the span/metric snapshot NOW, while it holds exactly the
        # headline job's trials — per-phase spans (advisor-propose /
        # build / train / evaluate / persist), program-cache hit/miss,
        # host-feed vs step time — so the BENCH artifact decomposes its
        # own wall-clock. Refreshed after the remaining sections so the
        # final artifact also covers serving/micro/lift activity.
        detail["telemetry"] = telemetry.snapshot()
        run_micro(sc, detail)
        with ledger.entity("bench:micro"):
            run_trial_pack_micro(sc, detail)
        run_advisor_lift(sc, detail)
        # Goodput ledger: the job's wall decomposed into compile / step /
        # feed / checkpoint / downtime per trial (acceptance criterion:
        # present on BOTH the full and the degraded artifact).
        detail["goodput"] = _goodput_snapshot()
        # Numerics health (docs/health.md): present on BOTH artifact
        # shapes so bench_report.py can trend divergences/evictions and
        # the badput they charged — a silent NaN epidemic shows up as a
        # throughput regression; this names it.
        detail["health"] = _health_snapshot()
        detail["telemetry"] = telemetry.snapshot()
        if detail.get("top1_miss"):
            # The accuracy clause is a GATE, not a footnote: a learning
            # regression (or an advisor steering into bad regions) must
            # turn the bench red, not quietly shave the headline. A
            # None best_top1 is a job failure, not a regression — label
            # it so triage starts at the right subsystem. On a plain
            # CPU run the gate is ADVISORY (recorded, rc stays 0): the
            # targets are calibrated for the canonical TPU scale, and a
            # 3-trial smoke sweep misses them by seed noise — which is
            # exactly how BENCH_r03–r05 turned CPU artifacts into rc=1
            # zeros. An explicitly forced target keeps the red path
            # testable on CPU.
            best = detail.get("best_top1")
            forced = bool(os.environ.get("RAFIKI_BENCH_TOP1_TARGET"))
            if best is None or platform != "cpu" or forced:
                _emit(error=("no completed trials scored — job/infra "
                             "failure, see errored_trials" if best is None
                             else
                             f"best_top1 {best} below target "
                             f"{sc['top1_target']} "
                             f"(ceiling {detail.get('top1_ceiling')}) — "
                             "learning regression"))
                wd.cancel()
                sys.exit(1)
            detail["top1_note"] = (
                f"best_top1 {best} below smoke target {sc['top1_target']}: "
                "advisory on CPU — the gate is calibrated for the "
                "canonical TPU run")
        _emit()
    except BaseException as e:  # noqa: BLE001 — the JSON line must go out
        _emit(error=f"{type(e).__name__}: {e}")
        wd.cancel()
        sys.exit(1)
    wd.cancel()


if __name__ == "__main__":
    main()
