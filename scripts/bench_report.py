#!/usr/bin/env python
"""Bench regression gate: trend the BENCH_r*.json history, verdict it.

Each bench round leaves an artifact — either the driver wrapper
``{"n": ..., "cmd": ..., "rc": ..., "tail": [...], "parsed": {...}}``
or a raw ``bench.py`` result line. This report joins them into one
trajectory per headline metric and renders a verdict:

  regressed     latest measurable value is worse than the best prior
                measurable value by more than ``--tolerance``
  improved      better than the best prior value by more than tolerance
  flat          within tolerance of the best prior value
  single-point  only one round ever measured this metric (no trend)
  no-data       no round measured it at all

"Measurable" is deliberately strict: a round whose payload carries an
``error`` (TPU tunnel down, watchdog fired) or a null/zero value is
**no data**, not a zero — r03–r05's backend-unavailable artifacts must
not read as a 100% throughput regression against r02's real number.

Schema tolerance runs both directions: schema>=2 artifacts carry a
``headline`` block (bench.py stamps it); older rounds are backfilled
from ``value`` + ``detail`` with the same key fallbacks bench.py uses.

Output: one JSON document on stdout (schema_versioned, machine-first —
scripts/perf_smoke.py subprocesses this as a CI gate); the exit code is
the verdict: 0 clean, 1 any metric regressed, 2 unreadable history.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Any, Dict, List, Optional

REPORT_SCHEMA_VERSION = 1
DEFAULT_TOLERANCE = 0.10

#: Headline metrics and which direction is good. Keys match the
#: bench.py ``headline`` block.
METRICS = {
    "trials_per_hour": "higher",
    "train_img_per_s": "higher",
    "canonical_trial_s": "lower",
    "compile_s": "lower",
}

#: Serving-round metrics (``--serving``): bench_serving.py v2 artifact
#: keys with their polarities, so SERVING_r*.json rounds gate the
#: trajectory exactly like training rounds do.
SERVING_METRICS = {
    "qps": "higher",
    "p50_ms": "lower",
    "p99_ms": "lower",
    "shed_rate": "lower",
    "ensemble_fanout_cost_ms": "lower",
}

#: Twin-validation rounds (``--twin``): TWIN_r*.json artifacts from
#: ``python -m rafiki_tpu.obs twin validate --out`` (docs/twin.md).
#: Both errors are relative |predicted-measured|/measured — lower is a
#: better-calibrated twin; a creeping error trend means the simulator
#: has drifted from the serving code it predicts.
TWIN_METRICS = {
    "p50_err": "lower",
    "p99_err": "lower",
}

#: Train-twin-validation rounds (``--train-twin``): TRAINTWIN_r*.json
#: artifacts from ``python -m rafiki_tpu.obs twin train validate --out``
#: (docs/twin.md). Relative |predicted-measured|/measured on the sweep's
#: trials/hour and wall clock — a creeping error trend means the sweep
#: simulator has drifted from the scheduler it predicts.
TRAIN_TWIN_METRICS = {
    "tph_err": "lower",
    "wall_err": "lower",
}

#: Sweep-anatomy rounds (``--sweep``): SWEEP_r*.json artifacts from
#: ``python -m rafiki_tpu.obs sweep --out`` (docs/search_anatomy.md).
#: Reconciliation-failed rounds stamp ``error`` and read as no-data —
#: a sweep whose audit trail leaked is not a zero-regret sweep.
SWEEP_METRICS = {
    "effective_trials_per_hour": "higher",
    "best_score": "higher",
    "regret": "lower",
    "advisor_lift": "higher",
}

#: Elasticity rounds (``--scale``): SCALE_r*.json artifacts from
#: scripts/autoscale_smoke.py (docs/autoscale.md). Recovery-time-to-SLO
#: is the loop's headline — how long a load spike burns before the
#: scale-up lands and the breach clears; actuations is the flap bill
#: the damping machinery keeps bounded.
SCALE_METRICS = {
    "recovery_s": "lower",
    "actuations": "lower",
}

#: Params-store rounds (``--store``): STORE_r*.json artifacts from
#: scripts/measure_store_throughput.py. ``second_write_frac`` is the
#: CAS dedup acceptance number — the byte fraction a near-identical
#: second checkpoint actually writes (ISSUE 14 gate: < 0.20).
STORE_METRICS = {
    "write_txn_per_s": "higher",
    "dedup_ratio": "higher",
    "second_write_frac": "lower",
    "cas_dump_s": "lower",
}

#: Crash-recovery rounds (``--resume``): RESUME_r*.json artifacts from
#: scripts/resume_smoke.py (docs/recovery.md). Recovery wall-clock is
#: the headline — how long a SIGKILLed sweep takes to be adopted and
#: driven to completion by a fresh process; duplicate_claims is the
#: WAL-reconcile acceptance number and must stay at zero.
RESUME_METRICS = {
    "recovery_wall_s": "lower",
    "trials_salvaged": "higher",
    "trials_restarted": "lower",
    "duplicate_claims": "lower",
}

#: Sharded-lane rounds (``--shard``): SHARD_r*.json artifacts from
#: scripts/shard_smoke.py (docs/sharding.md). restore_s is the
#: reshard-on-restore wall — how long resuming a group trial at a new
#: width takes; group_trials_per_hour is the lane's throughput
#: headline. Error rounds (a group that never completed) stamp
#: ``error`` and yield no data — a dead lane is not a fast one.
SHARD_METRICS = {
    "restore_s": "lower",
    "group_trials_per_hour": "higher",
}

#: Multi-tenant serving rounds (``--tenants``): TENANT_r*.json
#: artifacts from ``bench_serving.py --tenants`` (docs/multitenancy.md).
#: The gold tenant's tail and shed rate are the isolation headline —
#: the protected tenant must not regress when the batch aggressor's
#: skewed load grows — while batch_qps guards the other direction:
#: proportional share means the aggressor still progresses, so a
#: "fix" that simply starves batch also fails the gate.
TENANT_METRICS = {
    "gold_p99_ms": "lower",
    "gold_shed_rate": "lower",
    "batch_qps": "higher",
    "qps": "higher",
}

#: Metrics where 0 is a legitimate measurement, not "did not run" —
#: a clean serving round genuinely sheds nothing, a 1-worker round
#: has zero fan-out cost, a perfectly calibrated twin has zero
#: prediction error, and a sweep that found the optimum early has
#: zero regret. (Throughput-style metrics keep the strict v > 0
#: rule: their zeros mean a dead backend.)
ZERO_OK = {"shed_rate", "ensemble_fanout_cost_ms", "p50_err", "p99_err",
           "tph_err", "wall_err",
           "regret", "advisor_lift", "dedup_ratio",
           "trials_salvaged", "trials_restarted", "duplicate_claims",
           "gold_shed_rate"}

#: Metrics that are legitimately signed: a GP that *hurt* the sweep
#: has negative lift, and that is a measurement the trend must carry,
#: not a dead-backend null.
NEG_OK = {"advisor_lift"}


def _payload_from_tail(tail: Any) -> Optional[Dict[str, Any]]:
    """Backfill path: no ``parsed`` block, so scan the captured stdout
    tail from the end for the single bench result line. Tail chunks are
    arbitrary splits, so join first and walk whole lines."""
    if not tail:
        return None
    text = "".join(str(t) for t in tail)
    for line in reversed(text.splitlines()):
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        if isinstance(obj, dict) and ("value" in obj or "metric" in obj):
            return obj
    return None


def load_round(path: str) -> Dict[str, Any]:
    """One artifact file -> {round, path, rc, payload}. Never raises on
    a malformed file: it becomes a payload-less round (= no data)."""
    name = os.path.basename(path)
    out: Dict[str, Any] = {"path": name, "round": name, "rc": None,
                           "payload": None, "source": None}
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        out["error"] = f"{type(e).__name__}: {e}"
        return out
    if not isinstance(doc, dict):
        out["error"] = "artifact is not a JSON object"
        return out
    if ("metric" in doc or "headline" in doc or "qps" in doc
            or "schema_version" in doc or "twin_schema_version" in doc
            or "train_twin_schema_version" in doc
            or "sweep_schema_version" in doc
            or "scale_schema_version" in doc
            or "store_schema_version" in doc
            or "resume_schema_version" in doc
            or "shard_schema_version" in doc):
        # A raw bench.py / bench_serving.py result saved directly, no
        # driver wrapper.
        out["payload"], out["source"] = doc, "raw"
        return out
    out["round"] = doc.get("n", name)
    out["rc"] = doc.get("rc")
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        out["payload"], out["source"] = parsed, "parsed"
    else:
        out["payload"] = _payload_from_tail(doc.get("tail"))
        out["source"] = "tail" if out["payload"] else None
    return out


def headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The metric block to trend. An ``error``-bearing payload yields
    nothing: its zeros mean "did not run", not "ran this slow"."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    h = payload.get("headline")
    if isinstance(h, dict):
        return h
    d = payload.get("detail") or {}
    return {  # pre-schema_version backfill — mirrors bench.py._emit
        "trials_per_hour": payload.get("value"),
        "canonical_trial_s": d.get("canonical_trial_s",
                                   d.get("canonical_compute_s")),
        "compile_s": d.get("compile_s", d.get("cold_trial_s")),
        "train_img_per_s": d.get("train_img_per_s"),
    }


def serving_headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The serving metric block: bench_serving.py v2 artifacts carry
    the headline keys at top level."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in SERVING_METRICS
            if payload.get(k) is not None}


def twin_headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The twin-error block: validate artifacts carry p50_err/p99_err
    at top level. Error rounds (journals missing, too few requests)
    yield nothing — a round that never validated is no-data, not a
    perfect score."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in TWIN_METRICS
            if payload.get(k) is not None}


def train_twin_headline_of(payload: Optional[Dict[str, Any]]
                           ) -> Dict[str, Any]:
    """The train-twin-error block: ``twin train validate`` artifacts
    carry tph_err/wall_err at top level. Error rounds (journals
    missing, too few trials captured) yield nothing — a round that
    never validated is no-data, not a perfect score."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in TRAIN_TWIN_METRICS
            if payload.get(k) is not None}


def sweep_headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The sweep-anatomy block: ``obs sweep --out`` artifacts carry the
    headline keys at top level. A reconciliation-failed artifact stamps
    ``error`` and yields nothing — no-data, not a perfect sweep."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in SWEEP_METRICS
            if payload.get(k) is not None}


def scale_headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The elasticity block: autoscale_smoke artifacts carry the
    headline keys at top level. A round whose scenario failed stamps
    ``error`` and yields nothing — a loop that never closed is
    no-data, not an instant recovery."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in SCALE_METRICS
            if payload.get(k) is not None}


def store_headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The params-store block: measure_store_throughput artifacts
    carry the headline keys at top level."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in STORE_METRICS
            if payload.get(k) is not None}


def resume_headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The crash-recovery block: resume_smoke artifacts carry the
    headline keys at top level. A round whose resume never completed
    stamps ``error`` and yields nothing — a job still down is no-data,
    not an instant recovery."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in RESUME_METRICS
            if payload.get(k) is not None}


def shard_headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The sharded-lane block: shard_smoke artifacts carry restore_s
    and group_trials_per_hour at top level. Error rounds yield nothing
    — a group that never resumed is no-data, not an instant restore."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in SHARD_METRICS
            if payload.get(k) is not None}


def tenant_headline_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The multi-tenant block: ``bench_serving.py --tenants`` artifacts
    carry the flat gold_*/batch_* headline keys at top level. Error
    rounds yield nothing — a run that never isolated anyone is no-data,
    not a zero-shed round."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    return {k: payload.get(k) for k in TENANT_METRICS
            if payload.get(k) is not None}


def health_of(payload: Optional[Dict[str, Any]]) -> Dict[str, Any]:
    """The ``detail.health`` numerics block (docs/health.md), when the
    artifact carries one. Trended as ADVISORY context — a round with
    divergences explains a throughput dip, it is not itself a
    regression verdict (the badput is already in the goodput split)."""
    if not isinstance(payload, dict) or payload.get("error"):
        return {}
    h = (payload.get("detail") or {}).get("health")
    return h if isinstance(h, dict) else {}


def _measurable(v: Any, zero_ok: bool = False,
                neg_ok: bool = False) -> bool:
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return False
    return v > 0 or (zero_ok and v == 0) or (neg_ok and v < 0)


def trend(rounds: List[Dict[str, Any]], tolerance: float,
          metrics: Optional[Dict[str, str]] = None,
          headline_fn=headline_of) -> Dict[str, Dict[str, Any]]:
    """Per-metric trajectory + verdict. Latest measurable point vs the
    best prior measurable point, with a relative tolerance band."""
    out: Dict[str, Dict[str, Any]] = {}
    for metric, direction in (metrics or METRICS).items():
        zero_ok = metric in ZERO_OK
        neg_ok = metric in NEG_OK
        points = []
        for r in rounds:
            v = headline_fn(r["payload"]).get(metric)
            points.append({
                "round": r["round"],
                "value": v if _measurable(v, zero_ok, neg_ok) else None})
        measured = [p for p in points if p["value"] is not None]
        entry: Dict[str, Any] = {"direction": direction,
                                 "trajectory": points,
                                 "n_measured": len(measured)}
        if not measured:
            entry["verdict"] = "no-data"
        elif len(measured) == 1:
            entry["verdict"] = "single-point"
            entry["latest"] = measured[-1]["value"]
        else:
            latest = measured[-1]["value"]
            prior = [p["value"] for p in measured[:-1]]
            best = max(prior) if direction == "higher" else min(prior)
            # Signed fraction, positive = worse, in units of the best
            # prior value — one tolerance knob works for both signs.
            # ZERO_OK metrics can have best == 0 (a clean round shed
            # nothing) and NEG_OK ones a negative best (a GP that hurt):
            # fall back to an absolute delta so going from 0 to anything
            # still registers instead of dividing by 0 (or flipping sign).
            denom = best if best > 0 else 1.0
            delta = ((best - latest) if direction == "higher"
                     else (latest - best)) / denom
            entry.update({"latest": latest, "best_prior": best,
                          "delta_frac": round(delta, 4)})
            if delta > tolerance:
                entry["verdict"] = "regressed"
            elif delta < -tolerance:
                entry["verdict"] = "improved"
            else:
                entry["verdict"] = "flat"
        out[metric] = entry
    return out


def main(argv: Optional[List[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="scripts/bench_report.py",
        description="trend BENCH_r*.json artifacts, exit 1 on regression")
    p.add_argument("artifacts", nargs="*",
                   help="artifact files in round order "
                        "(default: BENCH_r*.json next to bench.py)")
    p.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                   help="relative regression band (default 0.10)")
    p.add_argument("--serving", action="store_true",
                   help="trend bench_serving.py rounds (SERVING_r*.json "
                        "default glob, qps/p50/p99/shed/fanout polarities)")
    p.add_argument("--twin", action="store_true",
                   help="trend twin-validation rounds (TWIN_r*.json "
                        "default glob, p50_err/p99_err lower-better)")
    p.add_argument("--train-twin", action="store_true",
                   help="trend train-twin-validation rounds "
                        "(TRAINTWIN_r*.json default glob, "
                        "tph_err/wall_err lower-better)")
    p.add_argument("--sweep", action="store_true",
                   help="trend sweep-anatomy rounds (SWEEP_r*.json "
                        "default glob, trials-per-hour/best-score higher, "
                        "regret lower, advisor_lift signed)")
    p.add_argument("--scale", action="store_true",
                   help="trend elasticity rounds (SCALE_r*.json default "
                        "glob, recovery_s/actuations lower-better)")
    p.add_argument("--store", action="store_true",
                   help="trend params-store rounds (STORE_r*.json default "
                        "glob, txn/s + dedup higher, write frac lower)")
    p.add_argument("--resume", action="store_true",
                   help="trend crash-recovery rounds (RESUME_r*.json "
                        "default glob, recovery_wall_s/restarts/duplicate "
                        "claims lower, salvaged trials higher)")
    p.add_argument("--shard", action="store_true",
                   help="trend sharded-lane rounds (SHARD_r*.json "
                        "default glob, reshard restore_s lower, group "
                        "trials-per-hour higher)")
    p.add_argument("--tenants", action="store_true",
                   help="trend multi-tenant serving rounds "
                        "(TENANT_r*.json default glob, gold tail/shed "
                        "lower-better, batch qps higher-better)")
    args = p.parse_args(argv)

    if sum((args.serving, args.twin, args.train_twin, args.sweep,
            args.scale, args.store, args.resume, args.tenants,
            args.shard)) > 1:
        print(json.dumps(
            {"error": "--serving, --twin, --train-twin, --sweep, --scale, "
                      "--store, --resume, --tenants and --shard are "
                      "exclusive"}))
        return 2
    if args.shard:
        metric_set, headline_fn = SHARD_METRICS, shard_headline_of
        pattern = "SHARD_r*.json"
    elif args.tenants:
        metric_set, headline_fn = TENANT_METRICS, tenant_headline_of
        pattern = "TENANT_r*.json"
    elif args.resume:
        metric_set, headline_fn = RESUME_METRICS, resume_headline_of
        pattern = "RESUME_r*.json"
    elif args.scale:
        metric_set, headline_fn = SCALE_METRICS, scale_headline_of
        pattern = "SCALE_r*.json"
    elif args.store:
        metric_set, headline_fn = STORE_METRICS, store_headline_of
        pattern = "STORE_r*.json"
    elif args.sweep:
        metric_set, headline_fn = SWEEP_METRICS, sweep_headline_of
        pattern = "SWEEP_r*.json"
    elif args.train_twin:
        metric_set, headline_fn = TRAIN_TWIN_METRICS, train_twin_headline_of
        pattern = "TRAINTWIN_r*.json"
    elif args.twin:
        metric_set, headline_fn = TWIN_METRICS, twin_headline_of
        pattern = "TWIN_r*.json"
    elif args.serving:
        metric_set, headline_fn = SERVING_METRICS, serving_headline_of
        pattern = "SERVING_r*.json"
    else:
        metric_set, headline_fn = METRICS, headline_of
        pattern = "BENCH_r*.json"

    paths = args.artifacts
    if not paths:
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        paths = sorted(glob.glob(os.path.join(root, pattern)))
    if not paths:
        print(json.dumps({"error": "no bench artifacts found"}))
        return 2

    rounds = [load_round(pth) for pth in paths]
    metrics = trend(rounds, args.tolerance,
                    metrics=metric_set, headline_fn=headline_fn)
    regressed = sorted(m for m, e in metrics.items()
                       if e["verdict"] == "regressed")
    health_points = [dict(round=r["round"], **health_of(r["payload"]))
                     for r in rounds if health_of(r["payload"])]
    report = {
        "schema_version": REPORT_SCHEMA_VERSION,
        "tolerance": args.tolerance,
        "n_rounds": len(rounds),
        "mode": ("tenants" if args.tenants
                 else "resume" if args.resume
                 else "scale" if args.scale
                 else "store" if args.store
                 else "sweep" if args.sweep
                 else "train-twin" if args.train_twin
                 else "twin" if args.twin
                 else "serving" if args.serving else "training"),
        "rounds": [{"round": r["round"], "rc": r["rc"],
                    "source": r["source"],
                    "has_data": bool(headline_fn(r["payload"]))}
                   for r in rounds],
        "metrics": metrics,
        "health": {
            "trajectory": health_points,
            "latest_divergences": (health_points[-1].get("divergences")
                                   if health_points else None),
        },
        "regressed": regressed,
        "verdict": "regressed" if regressed else "ok",
    }
    print(json.dumps(report))
    return 1 if regressed else 0


if __name__ == "__main__":
    sys.exit(main())
