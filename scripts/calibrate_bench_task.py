"""Calibrate the bench's canonical synthetic task (see bench.py).

The north star's "matched final top-1" clause is only falsifiable if
the bench task does NOT saturate: on the old flip=0 task every
non-broken config converged to ~1.0 and the `best_top1 >= target` gate
constrained nothing (round-3 verdict, Weak #3). This script measures
1-epoch top-1 across the lr grid x dropout for candidate (noise, flip)
pairs so the task parameters and `top1_target` in bench.py can be set
from evidence:

  * ceiling: a perfect classifier on a flip-relabeled task scores
    (1-flip) + flip/classes regardless of model/scale/epochs;
  * target: chosen below the measured good-config score and above the
    measured bad-config scores, so a learning regression (or a broken
    advisor steering into bad regions) turns the bench red.

Usage:
  JAX_PLATFORMS=cpu python scripts/calibrate_bench_task.py          # smoke scale
  python scripts/calibrate_bench_task.py --canonical               # TPU scale

Prints one row per (noise, flip, lr, dropout): top-1 after 1 epoch.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--canonical", action="store_true",
                    help="VGG16/50k canonical scale (TPU); default smoke scale")
    ap.add_argument("--noise", type=float, nargs="*", default=[0.35, 0.6])
    ap.add_argument("--flip", type=float, nargs="*", default=[0.2])
    args = ap.parse_args()

    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    from rafiki_tpu.models.vgg import Vgg

    if args.canonical:
        depth, width, w, n_train, n_eval = 16, 1.0, 32, 50_000, 10_000
        lrs = [1e-4, 1e-3, 1e-2, 3e-2]
    else:
        # MUST mirror bench.py _scale()'s smoke task (train_n/eval_n/w/
        # model) — these measurements justify that task's top1_target.
        depth, width, w, n_train, n_eval = 11, 0.25, 8, 2048, 512
        lrs = [1e-4, 1e-3, 1e-2, 3e-2]
    dropouts = [0.0, 0.4]

    rows = []
    for noise, flip in itertools.product(args.noise, args.flip):
        train = (f"synthetic://images?classes=10&n={n_train}&w={w}&h={w}&c=3"
                 f"&seed=0&noise={noise}&flip={flip}")
        val = (f"synthetic://images?classes=10&n={n_eval}&w={w}&h={w}&c=3"
               f"&seed=1&noise={noise}&flip={flip}")
        ceiling = (1 - flip) + flip / 10
        for lr, do in itertools.product(lrs, dropouts):
            m = Vgg(depth=depth, width_mult=width, dropout=do,
                    learning_rate=lr, batch_size=64, epochs=1, seed=0)
            m.train(train)
            top1 = float(m.evaluate(val))
            m.destroy()
            row = dict(noise=noise, flip=flip, lr=lr, dropout=do,
                       top1=round(top1, 4), ceiling=round(ceiling, 3))
            rows.append(row)
            print(json.dumps(row), flush=True)

    # Summary per task variant: best/worst over the knob grid.
    for (noise, flip), grp in itertools.groupby(
            rows, key=lambda r: (r["noise"], r["flip"])):
        grp = list(grp)
        tops = [r["top1"] for r in grp]
        print(f"# noise={noise} flip={flip}: best={max(tops):.3f} "
              f"worst={min(tops):.3f} spread={max(tops)-min(tops):.3f} "
              f"ceiling={grp[0]['ceiling']}")


if __name__ == "__main__":
    main()
