#!/usr/bin/env python
"""Request-anatomy CI smoke: waterfalls, tail attribution, both polarities.

Three phases, each in a fresh subprocess + journal dir
(docs/serving_anatomy.md):

  1. **Clean mp run** — ``bench_serving --smoke --mp`` with REAL
     spawned stub workers on the multiprocess bus and one pinned trace
     id. The artifact must be schema v2 with a populated ``hops``
     block, and the real ``obs waterfall <pin>`` CLI must reconstruct
     the pinned trace with >=4 hops spanning >=3 distinct pids, every
     chain's hop sums reconciling with its end-to-end span within 10%
     (``obs tails --check`` enforces the same fleet-wide). The
     serving time-series must have journaled rows (``obs serving``)
     and the ``serving_forward_p99`` SLO must NOT have breached — the
     no-false-positive control for phase 2.

  2. **Stacked mp run** — ``bench_serving --smoke --mp --route
     stacked``: ONE spawned worker stands in for the whole top-k
     ensemble and the gateway microbatches into it (docs/serving.md).
     The pinned trace must stitch ACROSS the microbatch — >=5 hops
     including a ``gateway_batch_wait`` segment, >=2 pids, hop sums
     reconciling within 10% — the microbatch counters must have
     populated, and the collapsed route's ``ensemble_fanout_cost_ms``
     must stay under 15ms — a fraction of the tens of ms the
     replicated k=3 mp fan-out pays in wire tax alone.

  3. **Injected mp run** — same stack, chaos plane now delaying
     ``inference.forward`` by 250ms on ~20% of batches, with a tight
     custom ``serving_forward_p99`` budget (150ms) ticking every
     100ms. ``obs tails`` must attribute the tail to the ``forward``
     hop (dominant segment), and the journals must carry the
     ``slo/breach`` record for ``serving_forward_p99`` — the injected
     delay is both *localised* and *alarmed*. The load is shaped so
     attribution is crisp, not smeared: one closed-loop client with
     one query per request makes every micro-batch a single query, so
     both replicas' chaos RNG streams (seeded, advanced once per hit)
     stay aligned and a delayed request delays BOTH replicas — the
     partner chain never mirrors the delay into its gather_decide
     wait, and p=0.2 keeps the delay out of the forward p50.

  4. **Report gate, both polarities** — ``bench_report --serving``
     over synthetic SERVING_r*.json rounds: an improved round must
     exit 0, a regressed round must exit 1. Serving rounds gate the
     trajectory exactly like training rounds.

Output: one JSON object on stdout. Exit code: 0 when every assertion
holds; 1 otherwise — this is a CI gate (scripts/check_tier1.sh).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PIN = "cafe0bet4p5"  # pinned trace id: the smoke's evidence, not a sample
CHAOS = "seed=7;inference.forward:delay:delay=0.25:p=0.2"
TIGHT_SLO = json.dumps([{
    "name": "serving_forward_p99",
    "source": "hist_p99:serving.hop.forward_s",
    "threshold": 0.15,
    "windows": [0.4, 1.0],
    "description": "smoke: forward p99 budget tightened to 150ms",
}])


def _run(cmd, env=None, timeout=300):
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.run(cmd, capture_output=True, text=True,
                          env=full_env, timeout=timeout, cwd=REPO)


def _bench(log_dir, extra_env=None, pin=None, extra_args=()):
    cmd = [sys.executable, "scripts/bench_serving.py", "--smoke", "--mp",
           "--min-replies", "2", *extra_args]
    if pin:
        cmd += ["--pin-trace", pin]
    env = {"RAFIKI_LOG_DIR": log_dir}
    if extra_env:
        env.update(extra_env)
    r = _run(cmd, env=env)
    try:
        report = json.loads(r.stdout)
    except ValueError:
        report = {"unparseable_stdout": r.stdout[-500:]}
    return r.returncode, report, r.stderr[-500:]


def _obs(log_dir, *verb_args):
    return _run([sys.executable, "-m", "rafiki_tpu.obs",
                 "--dir", log_dir, "--json", *verb_args])


def _journal_records(log_dir):
    out = []
    for name in os.listdir(log_dir):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(log_dir, name)) as f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    pass
    return out


def phase_clean(results):
    log_dir = tempfile.mkdtemp(prefix="serving_smoke_clean_")
    rc, report, err = _bench(log_dir, pin=PIN,
                             extra_args=("--requests-per-client", "12"))
    ph = {"bench_rc": rc, "bench_stderr": err,
          "schema_version": report.get("schema_version"),
          "pinned_status": report.get("pinned_status"),
          "hops_segments": sorted(report.get("hops") or {}),
          "ensemble_fanout_cost_ms": report.get("ensemble_fanout_cost_ms")}
    ok = (rc == 0 and report.get("schema_version") == 2
          and report.get("pinned_status") == 200
          and bool(report.get("hops")))

    # The pinned trace through the REAL CLI: >=4 hops, >=3 pids, and
    # hop sums reconciling with the chain span within 10%.
    wf = _obs(log_dir, "waterfall", PIN)
    ph["waterfall_rc"] = wf.returncode
    queries = []
    if wf.returncode == 0:
        try:
            queries = json.loads(wf.stdout).get("queries", [])
        except ValueError:
            pass
    if queries:
        ph["waterfall"] = {
            "queries": len(queries),
            "min_hops": min(q.get("n_hops", 0) for q in queries),
            "pids": sorted({p for q in queries for p in q.get("pids", [])}),
            "max_reconcile_err": max(q.get("max_reconcile_err", 1.0)
                                     for q in queries),
        }
        w = ph["waterfall"]
        ok = (ok and w["min_hops"] >= 4 and len(w["pids"]) >= 3
              and w["max_reconcile_err"] <= 0.10)
    else:
        ok = False

    tails = _obs(log_dir, "tails", "--check", "--tolerance", "0.10")
    ph["tails_check_rc"] = tails.returncode
    ok = ok and tails.returncode == 0

    serving = _obs(log_dir, "serving")
    rows = [ln for ln in serving.stdout.splitlines() if ln.strip()]
    ph["serving_rc"], ph["serving_rows"] = serving.returncode, len(rows)
    ok = ok and serving.returncode == 0 and rows

    # No-false-positive control: the default 1s forward budget must
    # not breach on ~millisecond stub forwards.
    breaches = [r for r in _journal_records(log_dir)
                if r.get("kind") == "slo" and r.get("name") == "breach"
                and r.get("slo") == "serving_forward_p99"]
    ph["forward_breaches"] = len(breaches)
    ok = ok and not breaches

    ph["ok"] = bool(ok)
    results["clean"] = ph
    return ok


def phase_stacked(results):
    log_dir = tempfile.mkdtemp(prefix="serving_smoke_stacked_")
    pin = PIN + "st"
    rc, report, err = _bench(log_dir, pin=pin,
                             extra_args=("--route", "stacked",
                                         "--requests-per-client", "12"))
    ph = {"bench_rc": rc, "bench_stderr": err,
          "route": report.get("route"),
          "pinned_status": report.get("pinned_status"),
          "ensemble_fanout_cost_ms": report.get("ensemble_fanout_cost_ms")}
    ok = (rc == 0 and report.get("schema_version") == 2
          and report.get("route") == "stacked"
          and report.get("pinned_status") == 200)

    # The collapsed fan-out is the whole point: one worker, one
    # envelope per microbatch — the fan-out overhead must sit in
    # single-digit ms where the replicated k=3 mp run pays tens.
    fan = report.get("ensemble_fanout_cost_ms")
    ok = ok and fan is not None and fan < 15.0

    # Microbatching actually engaged: the size/fill/flush counters the
    # gateway stamps per flush (docs/telemetry.md) rode the journals.
    hops = report.get("hops") or {}
    ph["has_batch_wait_hop"] = "gateway_batch_wait" in hops
    ok = ok and "gateway_batch_wait" in hops

    # The pinned trace must stitch ACROSS the microbatch: member
    # prefix + shared batch leg + worker leg + decide, >=2 pids, and
    # a named gateway_batch_wait segment, reconciling within 10%.
    wf = _obs(log_dir, "waterfall", pin)
    ph["waterfall_rc"] = wf.returncode
    queries = []
    if wf.returncode == 0:
        try:
            queries = json.loads(wf.stdout).get("queries", [])
        except ValueError:
            pass
    if queries:
        segs = {s["segment"] for q in queries
                for v in q.get("chains", {}).values()
                for s in v.get("segments", [])}
        ph["waterfall"] = {
            "queries": len(queries),
            "min_hops": min(q.get("n_hops", 0) for q in queries),
            "pids": sorted({p for q in queries for p in q.get("pids", [])}),
            "max_reconcile_err": max(q.get("max_reconcile_err", 1.0)
                                     for q in queries),
            "segments": sorted(segs),
        }
        w = ph["waterfall"]
        ok = (ok and w["min_hops"] >= 5 and len(w["pids"]) >= 2
              and "gateway_batch_wait" in segs
              and w["max_reconcile_err"] <= 0.10)
    else:
        ok = False

    ph["ok"] = bool(ok)
    results["stacked"] = ph
    return ok


def phase_injected(results):
    log_dir = tempfile.mkdtemp(prefix="serving_smoke_chaos_")
    rc, report, err = _bench(
        log_dir,
        extra_args=("--clients", "1", "--queries-per-request", "1",
                    "--requests-per-client", "80"),
        extra_env={
            "RAFIKI_CHAOS": CHAOS,
            "RAFIKI_SLO": TIGHT_SLO,
            "RAFIKI_SLO_TICK_S": "0.1",
        })
    ph = {"bench_rc": rc, "bench_stderr": err,
          "p99_ms": report.get("p99_ms")}
    ok = rc == 0

    # Attribution: the injected delay must surface as the forward hop
    # dominating the p99-over-p50 excess.
    tails = _obs(log_dir, "tails")
    ph["tails_rc"] = tails.returncode
    dominant = None
    if tails.returncode == 0:
        try:
            doc = json.loads(tails.stdout)
            dominant = doc.get("dominant")
            ph["dominant"] = dominant
            ph["forward_excess_ms"] = next(
                (s.get("excess_ms") for s in doc.get("segments", [])
                 if s.get("segment") == "forward"), None)
        except ValueError:
            pass
    ok = ok and bool(dominant) and dominant.startswith("forward")

    # Alarm: the tightened 150ms budget must have breached and left a
    # slo/breach record behind.
    breaches = [r for r in _journal_records(log_dir)
                if r.get("kind") == "slo" and r.get("name") == "breach"
                and r.get("slo") == "serving_forward_p99"]
    ph["forward_breaches"] = len(breaches)
    ok = ok and bool(breaches)

    ph["ok"] = bool(ok)
    results["injected"] = ph
    return ok


def phase_report_gate(results):
    d = tempfile.mkdtemp(prefix="serving_smoke_report_")

    def _round(n, **kv):
        path = os.path.join(d, f"SERVING_r{n:02d}.json")
        with open(path, "w") as f:
            json.dump(dict(schema_version=2, **kv), f)
        return path

    base = _round(1, qps=100.0, p50_ms=10.0, p99_ms=30.0, shed_rate=0.0,
                  ensemble_fanout_cost_ms=5.0)
    better = _round(2, qps=130.0, p50_ms=7.0, p99_ms=22.0, shed_rate=0.0,
                    ensemble_fanout_cost_ms=3.0)
    worse = _round(3, qps=60.0, p50_ms=18.0, p99_ms=80.0, shed_rate=0.2,
                   ensemble_fanout_cost_ms=15.0)

    good = _run([sys.executable, "scripts/bench_report.py", "--serving",
                 base, better])
    bad = _run([sys.executable, "scripts/bench_report.py", "--serving",
                base, better, worse])
    try:
        regressed = json.loads(bad.stdout).get("regressed")
    except ValueError:
        regressed = None
    ph = {"improved_rc": good.returncode, "regressed_rc": bad.returncode,
          "regressed_metrics": regressed}
    ok = (good.returncode == 0 and bad.returncode == 1
          and bool(regressed))
    ph["ok"] = bool(ok)
    results["report_gate"] = ph
    return ok


def main():
    results = {}
    ok = phase_clean(results)
    ok = phase_stacked(results) and ok
    ok = phase_injected(results) and ok
    ok = phase_report_gate(results) and ok
    results["ok"] = bool(ok)
    print(json.dumps(results, indent=2))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
