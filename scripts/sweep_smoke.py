#!/usr/bin/env python
"""Search-anatomy CI smoke: a seeded sweep must reconstruct from its
journals alone, and a doctored journal must fail reconciliation loudly
(docs/search_anatomy.md).

Three phases, ~10s total:

  1. **Sweep + reconstruct** — a 12-trial GpAdvisor sweep and a
     12-trial RandomAdvisor baseline over a synthetic quadratic
     objective, journaled to a fresh dir; then ``python -m
     rafiki_tpu.obs sweep --out SWEEP_r01.json`` as a real subprocess
     reading ONLY the journals. Every proposal must carry its
     acquisition breakdown, the regret curve must be non-increasing,
     and the GP-vs-random lift must come with its bootstrap CI.
  2. **Doctored journal** — the same dir minus one ``advisor/propose``
     line must exit non-zero with a reconciliation failure naming the
     escaped decision on stderr: feedback for a proposal that was
     never journaled means the audit trail leaked, and the sweep plane
     must refuse to pretend otherwise.
  3. **Report gate, both polarities** — ``bench_report --sweep`` over
     synthetic SWEEP_r*.json rounds: an improving trend exits 0, a
     collapsed round (regret up, trials/hour down) exits 1, and a
     reconciliation-failed round reads as no-data, not a
     zero-regret sweep.

Output: one JSON object on stdout. Exit 0 when every assertion holds;
1 otherwise — this is a CI gate (scripts/check_tier1.sh).
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_TRIALS = 12


def _run(cmd, timeout=120):
    return subprocess.run(cmd, capture_output=True, text=True,
                          env=dict(os.environ), timeout=timeout, cwd=REPO)


def _objective(knobs) -> float:
    """Smooth quadratic with one optimum inside the box — a GP can
    exploit it within 12 trials, so the reconstruction has a real
    regret curve to check."""
    lr_term = (math.log10(knobs["lr"]) + 2.5) ** 2 * 0.2
    unit_term = abs(knobs["units"] - 32) / 64 * 0.2
    return round(1.0 - lr_term - unit_term, 6)


def _journaled_sweep(log_dir):
    from rafiki_tpu.advisor.gp import GpAdvisor
    from rafiki_tpu.advisor.random_advisor import RandomAdvisor
    from rafiki_tpu.model.knobs import FixedKnob, FloatKnob, IntegerKnob
    from rafiki_tpu.obs.journal import journal

    kc = {"lr": FloatKnob(1e-4, 3e-2, is_exp=True),
          "units": IntegerKnob(4, 64),
          "b": FixedKnob(8)}
    journal.configure(log_dir, role="sweep")
    try:
        for adv in (GpAdvisor(kc, seed=5, n_initial=4),
                    RandomAdvisor(kc, seed=105)):
            for _ in range(N_TRIALS):
                knobs = adv.propose()
                adv.feedback(_objective(knobs), knobs)
    finally:
        journal.close()


def phase_reconstruct(results):
    log_dir = tempfile.mkdtemp(prefix="sweep_smoke_")
    _journaled_sweep(log_dir)
    out = os.path.join(log_dir, "SWEEP_r01.json")
    # The reader is a real subprocess with NOTHING but the journal dir:
    # the whole sweep must reconstruct from records alone.
    r = _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", log_dir,
              "--json", "sweep", "--out", out])
    try:
        doc = json.loads(r.stdout)
    except ValueError:
        doc = {}
    proposals = doc.get("proposals") or []
    regret = (doc.get("curve") or {}).get("regret") or []
    ci = doc.get("lift") or {}
    ph = {
        "rc": r.returncode,
        "n_proposals": len(proposals),
        "every_proposal_audited": bool(proposals) and all(
            p.get("acquisition", {}).get("phase") for p in proposals),
        "regret_nonincreasing": bool(regret) and all(
            a >= b for a, b in zip(regret, regret[1:])),
        "final_regret": regret[-1] if regret else None,
        "lift_ci": [ci.get("lo"), ci.get("hi")],
        "reconciliation_ok": (doc.get("reconciliation") or {}).get("ok"),
        "artifact_written": os.path.exists(out),
        "ok": False,
    }
    ph["ok"] = (ph["rc"] == 0 and ph["n_proposals"] == N_TRIALS
                and ph["every_proposal_audited"]
                and ph["regret_nonincreasing"]
                and ph["reconciliation_ok"] is True
                and ph["artifact_written"]
                and None not in ph["lift_ci"])
    if not ph["ok"]:
        ph["stderr"] = r.stderr[-400:]
    results["reconstruct"] = ph
    return log_dir if ph["ok"] else None


def phase_doctored(results, log_dir):
    """Strip ONE advisor/propose line: the remaining feedback is now a
    decision with no journaled origin, and reconciliation must fail
    loudly instead of rendering a plausible-looking sweep."""
    doctored = tempfile.mkdtemp(prefix="sweep_smoke_doctored_")
    cut = 0
    for name in os.listdir(log_dir):
        if not name.endswith(".jsonl"):
            continue
        kept = []
        for line in open(os.path.join(log_dir, name)):
            try:
                rec = json.loads(line)
            except ValueError:
                rec = {}
            if (not cut and rec.get("kind") == "advisor"
                    and rec.get("name") == "propose"
                    and rec.get("engine") == "gp"):
                cut += 1
                continue
            kept.append(line)
        with open(os.path.join(doctored, name), "w") as f:
            f.writelines(kept)
    r = _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", doctored,
              "--json", "sweep"])
    ph = {
        "lines_cut": cut,
        "rc": r.returncode,
        "fails_loudly": "RECONCILIATION FAILED" in r.stderr,
        "names_escape": "feedback_without_propose" in r.stderr,
        "ok": (cut == 1 and r.returncode != 0
               and "RECONCILIATION FAILED" in r.stderr
               and "feedback_without_propose" in r.stderr),
    }
    if not ph["ok"]:
        ph["stderr"] = r.stderr[-400:]
    results["doctored"] = ph
    return ph["ok"]


def phase_report_gate(results, log_dir):
    """bench_report --sweep over synthetic rounds, both polarities,
    seeded from the real r01 artifact so the trend exercises the
    actual SWEEP schema."""
    td = tempfile.mkdtemp(prefix="sweep_rounds_")
    base = json.load(open(os.path.join(log_dir, "SWEEP_r01.json")))

    def _round(n, doc):
        path = os.path.join(td, f"SWEEP_r{n:02d}.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    improving = [
        _round(1, dict(base, effective_trials_per_hour=400.0, regret=0.08)),
        _round(2, dict(base, effective_trials_per_hour=440.0, regret=0.05)),
        _round(3, {"sweep_schema_version": base.get("sweep_schema_version"),
                   "error": "sweep reconciliation failed"}),
        _round(4, dict(base, effective_trials_per_hour=450.0, regret=0.04)),
    ]
    ok_run = _run([sys.executable, "scripts/bench_report.py", "--sweep",
                   *improving])
    regressed = improving + [
        _round(5, dict(base, effective_trials_per_hour=200.0, regret=0.30))]
    bad_run = _run([sys.executable, "scripts/bench_report.py", "--sweep",
                    *regressed])
    try:
        ok_doc = json.loads(ok_run.stdout)
        bad_doc = json.loads(bad_run.stdout)
    except ValueError:
        ok_doc, bad_doc = {}, {}
    error_round_has_data = any(
        r.get("has_data") for r in ok_doc.get("rounds", [])
        if str(r.get("round", "")).endswith("r03.json"))
    ph = {
        "ok_rc": ok_run.returncode,
        "ok_verdict": ok_doc.get("verdict"),
        "regressed_rc": bad_run.returncode,
        "regressed_metrics": bad_doc.get("regressed"),
        "error_round_counted": error_round_has_data,
        "ok": (ok_run.returncode == 0 and ok_doc.get("verdict") == "ok"
               and bad_run.returncode == 1
               and "effective_trials_per_hour" in (bad_doc.get("regressed")
                                                   or [])
               and "regret" in (bad_doc.get("regressed") or [])
               and not error_round_has_data),
    }
    if not ph["ok"]:
        ph["ok_stderr"] = ok_run.stderr[-300:]
        ph["regressed_stderr"] = bad_run.stderr[-300:]
    results["report_gate"] = ph
    return ph["ok"]


def main() -> int:
    results = {}
    log_dir = phase_reconstruct(results)
    ok = log_dir is not None
    if ok:
        ok = phase_doctored(results, log_dir) and ok
    if ok:
        ok = phase_report_gate(results, log_dir) and ok
    results["ok"] = ok
    print(json.dumps(results))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
