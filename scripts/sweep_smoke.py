#!/usr/bin/env python
"""Search-anatomy CI smoke: a seeded sweep must reconstruct from its
journals alone, and a doctored journal must fail reconciliation loudly
(docs/search_anatomy.md).

Five phases, ~15s total:

  1. **Sweep + reconstruct** — a 12-trial GpAdvisor sweep and a
     12-trial RandomAdvisor baseline over a synthetic quadratic
     objective, journaled to a fresh dir; then ``python -m
     rafiki_tpu.obs sweep --out SWEEP_r01.json`` as a real subprocess
     reading ONLY the journals. Every proposal must carry its
     acquisition breakdown, the regret curve must be non-increasing,
     and the GP-vs-random lift must come with its bootstrap CI.
  2. **Doctored journal** — the same dir minus one ``advisor/propose``
     line must exit non-zero with a reconciliation failure naming the
     escaped decision on stderr: feedback for a proposal that was
     never journaled means the audit trail leaked, and the sweep plane
     must refuse to pretend otherwise.
  3. **Early-kill A/B, both polarities** (docs/early_kill.md) — the
     same seeded proposal stream trained twice over a synthetic
     epoch-curve objective with real per-epoch sleeps: kill-off runs
     every trial (doomed ones diverge at the end, charged to the
     doomed bucket), kill-on condemns them off the curve fit after
     ``min_obs`` epochs. Both journal dirs reconstruct through the
     real ``obs sweep`` subprocess; the gate is the ISSUE's claim —
     kill-on ``effective_trials_per_hour`` >= 1.3x kill-off at a
     byte-equal final best, zero false kills (each killed trial's
     sibling re-run to completion stays below best-so-far).
  4. **Doctored killer** — the same stream under an over-aggressive
     config (margin=0, warmup=0, min_obs=2) must be CAUGHT: at least
     one hindsight false kill journaled, kill_precision < 1 in the
     reconstruction. A killer the false-kill gate cannot catch would
     let a "faster" sweep quietly discard its best trials.
  5. **Report gate, both polarities** — ``bench_report --sweep`` over
     synthetic SWEEP_r*.json rounds: an improving trend exits 0, a
     collapsed round (regret up, trials/hour down) exits 1, and a
     reconciliation-failed round reads as no-data, not a
     zero-regret sweep. The committed repo-root ``SWEEP_r01.json``
     (regenerate with ``--emit-artifact``) must carry the A/B verdict
     and pass the same report gate.

Output: one JSON object on stdout. Exit 0 when every assertion holds;
1 otherwise — this is a CI gate (scripts/check_tier1.sh).
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
N_TRIALS = 12


def _run(cmd, timeout=120):
    return subprocess.run(cmd, capture_output=True, text=True,
                          env=dict(os.environ), timeout=timeout, cwd=REPO)


def _objective(knobs) -> float:
    """Smooth quadratic with one optimum inside the box — a GP can
    exploit it within 12 trials, so the reconstruction has a real
    regret curve to check."""
    lr_term = (math.log10(knobs["lr"]) + 2.5) ** 2 * 0.2
    unit_term = abs(knobs["units"] - 32) / 64 * 0.2
    return round(1.0 - lr_term - unit_term, 6)


def _journaled_sweep(log_dir):
    from rafiki_tpu.advisor.gp import GpAdvisor
    from rafiki_tpu.advisor.random_advisor import RandomAdvisor
    from rafiki_tpu.model.knobs import FixedKnob, FloatKnob, IntegerKnob
    from rafiki_tpu.obs.journal import journal

    kc = {"lr": FloatKnob(1e-4, 3e-2, is_exp=True),
          "units": IntegerKnob(4, 64),
          "b": FixedKnob(8)}
    journal.configure(log_dir, role="sweep")
    try:
        for adv in (GpAdvisor(kc, seed=5, n_initial=4),
                    RandomAdvisor(kc, seed=105)):
            for _ in range(N_TRIALS):
                knobs = adv.propose()
                adv.feedback(_objective(knobs), knobs)
    finally:
        journal.close()


# -- early-kill A/B (docs/early_kill.md) -------------------------------------
#
# One RandomAdvisor proposal stream (CURVE_SEED) trained twice over a
# synthetic epoch-curve objective. Half the knob box is doomed: the
# curve saturates low and the trial diverges on its final epoch —
# consolation feedback, doomed bucket — in BOTH polarities, so the
# scored set (and therefore final best) is identical by construction
# and the only difference the ledger can see is wall: kill-off sinks
# CURVE_EPOCHS sleeps into every doomed trial, kill-on only min_obs.

N_CURVE_TRIALS = 8
CURVE_EPOCHS = 10
EPOCH_S = 0.03
CURVE_SEED = 10
EFF_RATIO_FLOOR = 1.3
KILL_CFG = {"warmup_epochs": 2, "margin": 0.35, "min_obs": 3}
DOCTORED_KILL_CFG = {"warmup_epochs": 0, "margin": 0.0, "min_obs": 2}
ROOT_ARTIFACT = os.path.join(REPO, "SWEEP_r01.json")


def _curve_profile(knobs):
    """Deterministic trial destiny from the knob assignment itself —
    the 'sibling re-run' ground truth is just this function again.
    Finals are bimodal (doomed plateau 0.10-0.18 vs healthy 0.70-0.90)
    so a sane margin separates the bands."""
    from rafiki_tpu.obs.search import audit as search_audit

    h = int(search_audit.knobs_hash(knobs), 16)
    doomed = (h >> 8) % 2 == 1
    final = (0.10 + (h % 97) / 97.0 * 0.08) if doomed \
        else (0.70 + (h % 89) / 89.0 * 0.20)
    return round(final, 6), doomed, h


def _epoch_score(h_int, final, e):
    """Saturating curve with a deterministic per-trial wiggle — enough
    noise that a 2-observation fit can be badly wrong (the doctored
    killer's trap) while a min_obs=3 fit still lands inside the band."""
    wiggle = 1.0 + 0.06 * math.sin((h_int % 7) + 1.7 * e)
    return round(final * (1.0 - math.exp(-(e + 1) / 2.0)) * wiggle, 6)


def _curved_sweep(log_dir, kill_cfg):
    """Run the seeded stream once; ``kill_cfg=None`` is the kill-off
    polarity (no coordinator at all — the off path must not even
    consult the extrapolator). Returns run counters."""
    import time

    from rafiki_tpu.advisor.curve import KillConfig
    from rafiki_tpu.advisor.random_advisor import RandomAdvisor
    from rafiki_tpu.advisor.speculative import CurveCoordinator
    from rafiki_tpu.model.knobs import FixedKnob, FloatKnob, IntegerKnob
    from rafiki_tpu.obs.journal import journal
    from rafiki_tpu.obs.search import audit as search_audit
    from rafiki_tpu.obs.search.ledger import search_ledger

    kc = {"lr": FloatKnob(1e-4, 3e-2, is_exp=True),
          "units": IntegerKnob(4, 64),
          "b": FixedKnob(8)}
    search_ledger.reset()
    journal.configure(log_dir, role="sweep")
    counts = {"killed": 0, "diverged": 0, "scored": 0, "false_kills": 0}
    killed = []  # (knobs, predicted_final, best_at_kill)
    try:
        adv = RandomAdvisor(kc, seed=CURVE_SEED)
        coord = (CurveCoordinator(KillConfig(enabled=True, **kill_cfg))
                 if kill_cfg else None)
        for t in range(N_CURVE_TRIALS):
            knobs = adv.propose()
            final, doomed, h_int = _curve_profile(knobs)
            was_killed = False
            score = 0.0
            for e in range(CURVE_EPOCHS):
                time.sleep(EPOCH_S)
                score = _epoch_score(h_int, final, e)
                if coord is None:
                    continue
                coord.observe(knobs, e, score, trial_id=f"t{t:02d}",
                              horizon=CURVE_EPOCHS)
                fit = coord.kill_verdict(knobs, e, trial_id=f"t{t:02d}")
                if fit is not None:
                    killed.append((knobs, fit.predicted_final,
                                   coord.best_so_far))
                    search_audit.note_doomed(knobs)
                    adv.feedback(0.0, knobs)
                    was_killed = True
                    break
            if was_killed:
                counts["killed"] += 1
            elif doomed:
                # The trial diverges at the end — the same consolation
                # path the workers take, identical in both polarities.
                search_audit.note_doomed(knobs)
                adv.feedback(0.0, knobs)
                if coord is not None:
                    coord.note_done(knobs)
                counts["diverged"] += 1
            else:
                adv.feedback(score, knobs)
                if coord is not None:
                    coord.note_scored(knobs, score)
                counts["scored"] += 1
        # Hindsight pass: re-run every killed trial's knobs to
        # completion (the analytic profile IS the sibling) and journal
        # a false-kill verdict when the sibling beats best-so-far.
        for knobs, predicted, best_at in killed:
            sibling, _, h_int = _curve_profile(knobs)
            sibling_score = _epoch_score(h_int, sibling, CURVE_EPOCHS - 1)
            if best_at is not None and sibling_score > best_at:
                search_audit.record_false_kill(
                    knobs, killed_predicted=predicted,
                    sibling_score=sibling_score, best_so_far=best_at)
                counts["false_kills"] += 1
    finally:
        journal.close()
    return counts


def _reconstruct_artifact(log_dir, name):
    """The real `obs sweep` subprocess over one polarity's journals."""
    out = os.path.join(log_dir, name)
    r = _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", log_dir,
              "--json", "sweep", "--out", out])
    art = json.load(open(out)) if os.path.exists(out) else {}
    return r, art


def _root_artifact_doc(art_on, art_off):
    """The committed SWEEP_r01.json: the kill-on artifact with the
    kill-off polarity side by side and the A/B verdict explicit."""
    eff_on = art_on.get("effective_trials_per_hour") or 0.0
    eff_off = art_off.get("effective_trials_per_hour") or 0.0
    doc = dict(art_on)
    doc["kill_off"] = {k: art_off.get(k) for k in (
        "effective_trials_per_hour", "span_s", "n_scored", "n_doomed",
        "best_score", "regret")}
    doc["kill_on_vs_off"] = {
        "eff_ratio": round(eff_on / eff_off, 4) if eff_off else None,
        "best_delta": round((art_on.get("best_score") or 0.0)
                            - (art_off.get("best_score") or 0.0), 9),
        "eff_ratio_floor": EFF_RATIO_FLOOR,
    }
    return doc


def phase_early_kill(results):
    on_dir = tempfile.mkdtemp(prefix="sweep_smoke_killon_")
    off_dir = tempfile.mkdtemp(prefix="sweep_smoke_killoff_")
    c_off = _curved_sweep(off_dir, None)
    c_on = _curved_sweep(on_dir, KILL_CFG)
    r_off, art_off = _reconstruct_artifact(off_dir, "SWEEP_off.json")
    r_on, art_on = _reconstruct_artifact(on_dir, "SWEEP_on.json")
    eff_on = art_on.get("effective_trials_per_hour")
    eff_off = art_off.get("effective_trials_per_hour")
    ph = {
        "counts_on": c_on,
        "counts_off": c_off,
        "rc": [r_off.returncode, r_on.returncode],
        "eff_on": eff_on,
        "eff_off": eff_off,
        "eff_ratio": (round(eff_on / eff_off, 4)
                      if eff_on and eff_off else None),
        "best_on": art_on.get("best_score"),
        "best_off": art_off.get("best_score"),
        "n_kills": art_on.get("n_kills"),
        "n_false_kills": art_on.get("n_false_kills"),
        "kill_precision": art_on.get("kill_precision"),
        "ok": False,
    }
    ph["ok"] = (
        r_off.returncode == 0 and r_on.returncode == 0
        and c_on["false_kills"] == 0
        and c_on["killed"] >= 2
        and c_on["scored"] == c_off["scored"] >= 3
        and ph["eff_ratio"] is not None
        and ph["eff_ratio"] >= EFF_RATIO_FLOOR
        and ph["best_on"] is not None
        and ph["best_on"] == ph["best_off"]
        and art_on.get("n_kills") == c_on["killed"]
        and art_on.get("n_false_kills") == 0
        and art_on.get("kill_precision") == 1.0
        and (art_off.get("n_kills") or 0) == 0)
    if not ph["ok"]:
        ph["stderr"] = (r_on.stderr or r_off.stderr)[-400:]
    results["early_kill"] = ph
    return (art_on, art_off) if ph["ok"] else None


def phase_doctored_killer(results):
    """An over-aggressive config must be CAUGHT by the false-kill
    gate, not rewarded for its trials/hour."""
    d_dir = tempfile.mkdtemp(prefix="sweep_smoke_killdoc_")
    c = _curved_sweep(d_dir, DOCTORED_KILL_CFG)
    r, art = _reconstruct_artifact(d_dir, "SWEEP_doctored.json")
    ph = {
        "counts": c,
        "rc": r.returncode,
        "n_kills": art.get("n_kills"),
        "n_false_kills": art.get("n_false_kills"),
        "kill_precision": art.get("kill_precision"),
        "ok": False,
    }
    ph["ok"] = (r.returncode == 0
                and c["false_kills"] >= 1
                and art.get("n_false_kills") == c["false_kills"]
                and (art.get("kill_precision") or 1.0) < 1.0)
    if not ph["ok"]:
        ph["stderr"] = r.stderr[-400:]
    results["doctored_killer"] = ph
    return ph["ok"]


def phase_reconstruct(results):
    log_dir = tempfile.mkdtemp(prefix="sweep_smoke_")
    _journaled_sweep(log_dir)
    out = os.path.join(log_dir, "SWEEP_r01.json")
    # The reader is a real subprocess with NOTHING but the journal dir:
    # the whole sweep must reconstruct from records alone.
    r = _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", log_dir,
              "--json", "sweep", "--out", out])
    try:
        doc = json.loads(r.stdout)
    except ValueError:
        doc = {}
    proposals = doc.get("proposals") or []
    regret = (doc.get("curve") or {}).get("regret") or []
    ci = doc.get("lift") or {}
    ph = {
        "rc": r.returncode,
        "n_proposals": len(proposals),
        "every_proposal_audited": bool(proposals) and all(
            p.get("acquisition", {}).get("phase") for p in proposals),
        "regret_nonincreasing": bool(regret) and all(
            a >= b for a, b in zip(regret, regret[1:])),
        "final_regret": regret[-1] if regret else None,
        "lift_ci": [ci.get("lo"), ci.get("hi")],
        "reconciliation_ok": (doc.get("reconciliation") or {}).get("ok"),
        "artifact_written": os.path.exists(out),
        "ok": False,
    }
    ph["ok"] = (ph["rc"] == 0 and ph["n_proposals"] == N_TRIALS
                and ph["every_proposal_audited"]
                and ph["regret_nonincreasing"]
                and ph["reconciliation_ok"] is True
                and ph["artifact_written"]
                and None not in ph["lift_ci"])
    if not ph["ok"]:
        ph["stderr"] = r.stderr[-400:]
    results["reconstruct"] = ph
    return log_dir if ph["ok"] else None


def phase_doctored(results, log_dir):
    """Strip ONE advisor/propose line: the remaining feedback is now a
    decision with no journaled origin, and reconciliation must fail
    loudly instead of rendering a plausible-looking sweep."""
    doctored = tempfile.mkdtemp(prefix="sweep_smoke_doctored_")
    cut = 0
    for name in os.listdir(log_dir):
        if not name.endswith(".jsonl"):
            continue
        kept = []
        for line in open(os.path.join(log_dir, name)):
            try:
                rec = json.loads(line)
            except ValueError:
                rec = {}
            if (not cut and rec.get("kind") == "advisor"
                    and rec.get("name") == "propose"
                    and rec.get("engine") == "gp"):
                cut += 1
                continue
            kept.append(line)
        with open(os.path.join(doctored, name), "w") as f:
            f.writelines(kept)
    r = _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", doctored,
              "--json", "sweep"])
    ph = {
        "lines_cut": cut,
        "rc": r.returncode,
        "fails_loudly": "RECONCILIATION FAILED" in r.stderr,
        "names_escape": "feedback_without_propose" in r.stderr,
        "ok": (cut == 1 and r.returncode != 0
               and "RECONCILIATION FAILED" in r.stderr
               and "feedback_without_propose" in r.stderr),
    }
    if not ph["ok"]:
        ph["stderr"] = r.stderr[-400:]
    results["doctored"] = ph
    return ph["ok"]


def phase_report_gate(results, log_dir):
    """bench_report --sweep over synthetic rounds, both polarities,
    seeded from the real r01 artifact so the trend exercises the
    actual SWEEP schema."""
    td = tempfile.mkdtemp(prefix="sweep_rounds_")
    base = json.load(open(os.path.join(log_dir, "SWEEP_r01.json")))

    def _round(n, doc):
        path = os.path.join(td, f"SWEEP_r{n:02d}.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    improving = [
        _round(1, dict(base, effective_trials_per_hour=400.0, regret=0.08)),
        _round(2, dict(base, effective_trials_per_hour=440.0, regret=0.05)),
        _round(3, {"sweep_schema_version": base.get("sweep_schema_version"),
                   "error": "sweep reconciliation failed"}),
        _round(4, dict(base, effective_trials_per_hour=450.0, regret=0.04)),
    ]
    ok_run = _run([sys.executable, "scripts/bench_report.py", "--sweep",
                   *improving])
    regressed = improving + [
        _round(5, dict(base, effective_trials_per_hour=200.0, regret=0.30))]
    bad_run = _run([sys.executable, "scripts/bench_report.py", "--sweep",
                    *regressed])
    try:
        ok_doc = json.loads(ok_run.stdout)
        bad_doc = json.loads(bad_run.stdout)
    except ValueError:
        ok_doc, bad_doc = {}, {}
    error_round_has_data = any(
        r.get("has_data") for r in ok_doc.get("rounds", [])
        if str(r.get("round", "")).endswith("r03.json"))
    ph = {
        "ok_rc": ok_run.returncode,
        "ok_verdict": ok_doc.get("verdict"),
        "regressed_rc": bad_run.returncode,
        "regressed_metrics": bad_doc.get("regressed"),
        "error_round_counted": error_round_has_data,
        "ok": (ok_run.returncode == 0 and ok_doc.get("verdict") == "ok"
               and bad_run.returncode == 1
               and "effective_trials_per_hour" in (bad_doc.get("regressed")
                                                   or [])
               and "regret" in (bad_doc.get("regressed") or [])
               and not error_round_has_data),
    }
    if not ph["ok"]:
        ph["ok_stderr"] = ok_run.stderr[-300:]
        ph["regressed_stderr"] = bad_run.stderr[-300:]
    results["report_gate"] = ph
    return ph["ok"]


def phase_root_artifact(results):
    """The committed repo-root SWEEP_r01.json must be the real thing:
    carries the A/B verdict above the floor, zero false kills, and
    passes the same ``bench_report --sweep`` gate CI trends."""
    try:
        doc = json.load(open(ROOT_ARTIFACT))
    except (OSError, ValueError):
        doc = {}
    verdict = doc.get("kill_on_vs_off") or {}
    r = _run([sys.executable, "scripts/bench_report.py", "--sweep",
              ROOT_ARTIFACT])
    try:
        rep = json.loads(r.stdout)
    except ValueError:
        rep = {}
    has_data = any(x.get("has_data") for x in rep.get("rounds", []))
    ph = {
        "exists": os.path.exists(ROOT_ARTIFACT),
        "eff_ratio": verdict.get("eff_ratio"),
        "best_delta": verdict.get("best_delta"),
        "n_kills": doc.get("n_kills"),
        "report_rc": r.returncode,
        "report_has_data": has_data,
        "ok": False,
    }
    ph["ok"] = (ph["exists"]
                and (verdict.get("eff_ratio") or 0.0) >= EFF_RATIO_FLOOR
                and verdict.get("best_delta") == 0.0
                and (doc.get("n_kills") or 0) >= 1
                and doc.get("n_false_kills") == 0
                and r.returncode == 0 and has_data)
    if not ph["ok"]:
        ph["stderr"] = r.stderr[-300:]
    results["root_artifact"] = ph
    return ph["ok"]


def emit_artifact() -> int:
    """Regenerate the committed repo-root SWEEP_r01.json from a fresh
    A/B run (``sweep_smoke.py --emit-artifact``)."""
    results = {}
    ab = phase_early_kill(results)
    if ab is None:
        print(json.dumps(results, indent=2))
        return 1
    doc = _root_artifact_doc(*ab)
    with open(ROOT_ARTIFACT, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    print(json.dumps({"written": ROOT_ARTIFACT,
                      "kill_on_vs_off": doc["kill_on_vs_off"]}))
    return 0


def main() -> int:
    if "--emit-artifact" in sys.argv[1:]:
        return emit_artifact()
    results = {}
    log_dir = phase_reconstruct(results)
    ok = log_dir is not None
    if ok:
        ok = phase_doctored(results, log_dir) and ok
    if ok:
        ok = phase_early_kill(results) is not None and ok
    if ok:
        ok = phase_doctored_killer(results) and ok
    if ok:
        ok = phase_root_artifact(results) and ok
    if ok:
        ok = phase_report_gate(results, log_dir) and ok
    results["ok"] = ok
    print(json.dumps(results))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
