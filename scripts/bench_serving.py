#!/usr/bin/env python
"""Closed-loop serving load generator for the predict path (schema v2).

Three modes:

  * ``--url http://host:port`` — drive a LIVE predictor endpoint
    (``predictor_host`` from the inference-job row) with N closed-loop
    clients for a fixed request count, measuring end-to-end latency
    through the serving gateway.
  * ``--smoke`` (default when no --url) — fully in-process and
    deterministic: stub-model workers on the in-proc bus behind a real
    Gateway + PredictorApp WSGI stack, exercised through the werkzeug
    test client. No sockets, no sleeps beyond the stub service time —
    the tier-1 wiring in scripts/check_tier1.sh runs this variant.
  * ``--smoke --mp`` — same stack, but the stub workers are REAL
    spawned processes on the multiprocess bus, so the hop waterfall
    crosses >=3 pids (scripts/serving_obs_smoke.py drives this).

``--tenants`` runs a skewed two-tenant closed loop (a gold tenant vs a
``--skew``x batch aggressor) against a tenant-aware gateway and emits
per-tenant p50/p99/shed plus the TENANT_r*.json headline keys
(docs/multitenancy.md).

``--route`` picks the serving shape (docs/serving.md): ``replicated``
(default) is the k-replica fan-out — one stub worker per trial, every
request fanned to all of them; ``stacked`` is the collapsed route —
ONE worker holds the whole ensemble, the gateway microbatches into it
(``--max-batch``, default 8 on this route); ``both`` runs the two
back to back with a telemetry reset in between and emits a combined
artifact: the stacked headline at top level (that is the route the PR
ships) plus a ``routes`` block carrying each per-route report, so one
SERVING_r*.json shows the before/after of the fan-out collapse.

Output: one JSON object on stdout (``schema_version: 2``):

  {"schema_version": 2, "qps": ..., "p50_ms": ..., "p99_ms": ...,
   "shed_rate": ..., "requests": ..., "ok": ..., "shed": ...,
   "errors": ..., "hops": {"forward": {"count": ..., "p50_ms": ...,
   "p99_ms": ...}, ...}, "ensemble_fanout_cost_ms": ...}

The ``hops`` block is the per-segment anatomy from the request-anatomy
plane (docs/serving_anatomy.md) and ``ensemble_fanout_cost_ms`` is the
chain total minus the slowest device forward — the overhead the
k-replica fan-out adds on top of the model, i.e. the number the
vmapped-ensemble bet must shrink. ``--pin-trace ID`` sends one extra
traced request after the load so a known trace id has a full
waterfall (``obs waterfall ID``).

Closed-loop means each client fires its next request only after the
previous one answered (or was shed) — offered load adapts to service
rate, the standard arrangement for latency benchmarking. Shed (429)
responses count toward shed_rate, not latency percentiles.

Exit code: 0 on a sane run; 1 when the run itself misbehaved (5xx
responses, zero completed requests) — that makes the smoke variant a
CI gate, not just a number printer.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCHEMA_VERSION = 2


def percentile(sorted_xs, p):
    if not sorted_xs:
        return None
    last = len(sorted_xs) - 1
    return sorted_xs[min(last, int(last * p / 100))]


class _StubModel:
    """Fixed service time, fixed output — no jax, no compile. Module
    level so multiprocessing spawn targets can pickle it."""

    def __init__(self, service_ms):
        self.service_ms = service_ms

    def predict(self, queries):
        time.sleep(self.service_ms / 1000.0)
        return [[0.6, 0.4] for _ in queries]


def _mp_stub_worker(bus, worker_id, service_ms):
    """Spawn target: one stub inference worker as its OWN process, the
    same dance run_inference_worker_process does (platform pin first,
    then the obs plane) minus the model store."""
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    from rafiki_tpu import obs

    obs.configure_from_env(role="infer")
    from rafiki_tpu.worker.inference import InferenceWorker

    InferenceWorker(bus, "bench", worker_id,
                    _StubModel(service_ms)).run()


class ClosedLoopClient:
    """One closed-loop worker: POST, record, repeat."""

    def __init__(self, post, n_requests, payload, record):
        self._post = post          # (payload) -> status_code
        self._n = n_requests
        self._payload = payload
        self._record = record

    def run(self):
        for _ in range(self._n):
            t0 = time.monotonic()
            try:
                status = self._post(self._payload)
            except Exception:
                status = -1
            # lint: disable=RF007 — the delta IS the datum: the client-observed request latency this bench reports
            self._record(status, time.monotonic() - t0)


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.latencies_s = []
        self.ok = 0
        self.shed = 0
        self.errors = 0

    def record(self, status, latency_s):
        with self._lock:
            if status == 200:
                self.ok += 1
                self.latencies_s.append(latency_s)
            elif status == 429:
                self.shed += 1
            else:
                self.errors += 1

    def report(self, elapsed_s):
        with self._lock:
            xs = sorted(self.latencies_s)
            total = self.ok + self.shed + self.errors
            return {
                "requests": total,
                "ok": self.ok,
                "shed": self.shed,
                "errors": self.errors,
                "qps": round(total / elapsed_s, 2) if elapsed_s else None,
                "p50_ms": (None if not xs
                           else round(percentile(xs, 50) * 1000, 3)),
                "p99_ms": (None if not xs
                           else round(percentile(xs, 99) * 1000, 3)),
                "shed_rate": round(self.shed / total, 4) if total else None,
            }


def run_load(post, n_clients, requests_per_client, payload):
    recorder = Recorder()
    clients = [ClosedLoopClient(post, requests_per_client, payload,
                                recorder.record)
               for _ in range(n_clients)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    # lint: disable=RF007 — the delta IS the datum: total load-generation wall used as the qps denominator
    return recorder.report(time.monotonic() - t0)


def _hops_block():
    """The per-segment anatomy block from this process's telemetry
    registry (the predictor absorbs chains in-process, so the
    histograms live here)."""
    from rafiki_tpu import telemetry
    from rafiki_tpu.obs.anatomy import hops as _hops

    hists = telemetry.snapshot().get("histograms", {})
    prefix = "serving.hop."
    hops = {}
    for name in sorted(hists):
        if not name.startswith(prefix):
            continue
        h = hists[name]
        seg = name[len(prefix):-2]  # strip prefix and the "_s" unit
        hops[seg] = {"count": h.get("count"),
                     "p50_ms": (None if h.get("p50") is None
                                else round(h["p50"] * 1000, 3)),
                     "p99_ms": (None if h.get("p99") is None
                                else round(h["p99"] * 1000, 3))}
    fan = hists.get(_hops.FANOUT_METRIC)
    fanout_ms = (None if not fan or fan.get("p50") is None
                 else round(fan["p50"] * 1000, 3))
    return hops or None, fanout_ms


def run_url_mode(args):
    import requests

    url = args.url.rstrip("/") + "/predict"
    session = requests.Session()

    def post(payload):
        resp = session.post(url, json=payload, timeout=args.deadline_s + 5)
        return resp.status_code

    payload = {"queries": [[1.0]] * args.queries_per_request,
               "deadline_s": args.deadline_s}
    return run_load(post, args.clients, args.requests_per_client, payload)


def run_smoke_mode(args, route="replicated"):
    from werkzeug.test import Client

    from rafiki_tpu.gateway import Gateway, GatewayConfig
    from rafiki_tpu.predictor import Predictor
    from rafiki_tpu.predictor.app import PredictorApp
    from rafiki_tpu.worker.inference import InferenceWorker

    # The stacked route collapses the fan-out: ONE worker stands in for
    # the whole top-k ensemble (the stub's fixed service time is paid
    # once per forward either way — exactly the vmap bet), quorum is 1,
    # and the gateway microbatches into it.
    stacked = route == "stacked"
    n_workers = 1 if stacked else args.workers
    wprefix = "sbw" if stacked else "bw"
    max_batch = (args.max_batch if args.max_batch is not None
                 else (8 if stacked else 1))
    min_replies = 1 if stacked else args.min_replies

    stop = threading.Event()
    threads = []
    procs = []
    manager = None
    if args.mp:
        import multiprocessing as mp

        from rafiki_tpu.bus.queues import make_mp_bus

        ctx = mp.get_context("spawn")
        manager = ctx.Manager()
        bus = make_mp_bus(manager)
        for i in range(n_workers):
            pr = ctx.Process(target=_mp_stub_worker,
                             args=(bus, f"{wprefix}{i}", args.service_ms),
                             daemon=True)
            procs.append(pr)
            pr.start()
    else:
        from rafiki_tpu.bus import InProcBus

        bus = InProcBus()
        for i in range(n_workers):
            w = InferenceWorker(bus, "bench", f"{wprefix}{i}",
                                _StubModel(args.service_ms), stop_event=stop)
            th = threading.Thread(target=w.run, daemon=True)
            threads.append(th)
            th.start()
    deadline = time.monotonic() + (30 if args.mp else 10)
    while len(bus.get_workers("bench")) < n_workers:
        if time.monotonic() > deadline:
            raise RuntimeError("bench workers never registered")
        time.sleep(0.005)

    predictor = Predictor(bus, "bench", timeout_s=args.deadline_s)
    gateway = Gateway(predictor, GatewayConfig(
        max_inflight=args.max_inflight, max_queue=args.max_queue,
        min_replies=min_replies, hedge_grace_s=0.02,
        max_batch=max_batch, max_batch_wait_ms=args.max_batch_wait_ms))
    wsgi = Client(PredictorApp(gateway))

    def post(payload):
        return wsgi.post("/predict", json=payload).status_code

    payload = {"queries": [[1.0]] * args.queries_per_request,
               "deadline_s": args.deadline_s}
    try:
        report = run_load(post, args.clients, args.requests_per_client,
                          payload)
        if args.pin_trace:
            # One traced request AFTER the load: a known trace id with
            # a full waterfall for `obs waterfall <id>` (retried — the
            # pinned trace is the smoke's evidence, not a sample).
            status = None
            for _ in range(20):
                status = wsgi.post(
                    "/predict", json=payload,
                    headers={"X-Rafiki-Trace-Id": args.pin_trace},
                ).status_code
                if status == 200:
                    break
                time.sleep(0.05)
            report["pinned_trace"] = args.pin_trace
            report["pinned_status"] = status
        # Short runs would otherwise journal nothing: force the
        # time-series bucket and the exemplar window closed.
        gateway.rollup.flush()
        from rafiki_tpu.obs.anatomy import exemplars

        exemplars.ring.flush()
        return report
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=2)
        for pr in procs:
            pr.terminate()
            pr.join(timeout=5)
        if manager is not None:
            manager.shutdown()


def run_tenants_mode(args):
    """Skewed two-tenant closed loop against a tenant-aware gateway.

    A gold tenant at 1x clients and a batch tenant at ``--skew``x
    clients share one gateway built over a TenantFabric — weighted
    admission, per-tenant quotas, per-tenant accounting. The artifact
    carries a per-tenant latency/shed report plus flat headline keys
    (``gold_p99_ms``, ``gold_shed_rate``, ``batch_qps``) for the
    TENANT_r*.json trend gate in bench_report --tenants: the number
    that must not regress is the PROTECTED tenant's tail while the
    aggressor keeps making proportional progress.
    """
    from werkzeug.test import Client

    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.gateway import Gateway, GatewayConfig
    from rafiki_tpu.predictor import Predictor
    from rafiki_tpu.predictor.app import PredictorApp
    from rafiki_tpu.tenancy import TenantDirectory, TenantFabric
    from rafiki_tpu.worker.inference import InferenceWorker

    GOLD, BATCH = "gold_t", "batch_t"
    stop = threading.Event()
    bus = InProcBus()
    threads = []
    for i in range(args.workers):
        w = InferenceWorker(bus, "bench", f"tw{i}",
                            _StubModel(args.service_ms), stop_event=stop)
        th = threading.Thread(target=w.run, daemon=True)
        threads.append(th)
        th.start()
    deadline = time.monotonic() + 10
    while len(bus.get_workers("bench")) < args.workers:
        if time.monotonic() > deadline:
            raise RuntimeError("bench workers never registered")
        time.sleep(0.005)

    fabric = TenantFabric(TenantDirectory(
        tiers={GOLD: "gold", BATCH: "batch"}))
    predictor = Predictor(bus, "bench", timeout_s=args.deadline_s)
    gateway = Gateway(predictor, GatewayConfig(
        max_inflight=args.max_inflight, max_queue=args.max_queue,
        min_replies=1, hedge_grace_s=0.02), tenancy=fabric)
    wsgi = Client(PredictorApp(gateway))
    payload = {"queries": [[1.0]] * args.queries_per_request,
               "deadline_s": args.deadline_s}

    recorders = {GOLD: Recorder(), BATCH: Recorder()}

    def _post_as(tenant):
        def post(p):
            return wsgi.post("/predict", json=p,
                             headers={"X-Rafiki-Tenant": tenant}
                             ).status_code
        return post

    clients = (
        [ClosedLoopClient(_post_as(GOLD), args.requests_per_client,
                          payload, recorders[GOLD].record)
         for _ in range(args.clients)]
        + [ClosedLoopClient(_post_as(BATCH), args.requests_per_client,
                            payload, recorders[BATCH].record)
           for _ in range(args.clients * args.skew)])
    pool = [threading.Thread(target=c.run, daemon=True) for c in clients]
    t0 = time.monotonic()
    try:
        for th in pool:
            th.start()
        for th in pool:
            th.join()
        # lint: disable=RF007 — the delta IS the datum: load wall-clock, the per-tenant qps denominator
        elapsed = time.monotonic() - t0
        gateway.drain(timeout=5.0)  # flushes the tenant/summary journal
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=2)

    tiers = {GOLD: "gold", BATCH: "batch"}
    tenants = {t: dict(recorders[t].report(elapsed), tier=tiers[t])
               for t in (GOLD, BATCH)}
    total = sum(tenants[t]["requests"] for t in tenants)
    report = {
        "mode": "smoke-tenants",
        "skew": args.skew,
        "tenants": tenants,
        "requests": total,
        "ok": sum(tenants[t]["ok"] for t in tenants),
        "shed": sum(tenants[t]["shed"] for t in tenants),
        "errors": sum(tenants[t]["errors"] for t in tenants),
        "qps": round(total / elapsed, 2) if elapsed else None,
        # Flat headline keys for the TENANT_r*.json polarity gate.
        "gold_p50_ms": tenants[GOLD]["p50_ms"],
        "gold_p99_ms": tenants[GOLD]["p99_ms"],
        "gold_shed_rate": tenants[GOLD]["shed_rate"],
        "batch_p99_ms": tenants[BATCH]["p99_ms"],
        "batch_qps": tenants[BATCH]["qps"],
    }
    return report


def main(argv=None):
    # Platform pin FIRST: this process may import jax transitively via
    # the worker/model stack, and the image's sitecustomize would
    # otherwise hang backend init with the TPU tunnel down.
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="live predictor base URL; omit for the "
                                  "in-process smoke run")
    ap.add_argument("--smoke", action="store_true",
                    help="force the in-process deterministic run")
    ap.add_argument("--mp", action="store_true",
                    help="smoke mode with REAL spawned worker processes "
                         "on the mp bus (cross-process waterfalls)")
    ap.add_argument("--route", choices=("replicated", "stacked", "both"),
                    default="replicated",
                    help="serving shape: k-replica fan-out, collapsed "
                         "stacked worker + gateway microbatching, or "
                         "both back to back (combined artifact)")
    ap.add_argument("--max-batch", type=int, default=None,
                    help="gateway microbatch size (default: 1 on the "
                         "replicated route, 8 on the stacked route)")
    ap.add_argument("--max-batch-wait-ms", type=float, default=5.0,
                    help="gateway microbatch deadline-bounded wait")
    ap.add_argument("--pin-trace", default=None,
                    help="send one extra request under this trace id "
                         "after the load (obs waterfall target)")
    ap.add_argument("--tenants", action="store_true",
                    help="skewed two-tenant run against a tenant-aware "
                         "gateway: per-tenant p50/p99/shed plus the "
                         "TENANT_r*.json headline keys "
                         "(docs/multitenancy.md)")
    ap.add_argument("--skew", type=int, default=3,
                    help="batch-tenant client multiple in --tenants "
                         "mode (gold gets --clients, batch gets "
                         "--clients * skew)")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests-per-client", type=int, default=25)
    ap.add_argument("--queries-per-request", type=int, default=4)
    ap.add_argument("--deadline-s", type=float, default=2.0)
    ap.add_argument("--workers", type=int, default=2,
                    help="stub inference workers (smoke mode)")
    ap.add_argument("--service-ms", type=float, default=1.0,
                    help="stub model service time (smoke mode)")
    ap.add_argument("--max-inflight", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=8)
    ap.add_argument("--min-replies", type=int, default=None,
                    help="gather quorum override (default ceil(k/2))")
    args = ap.parse_args(argv)

    # Journal under RAFIKI_LOG_DIR when set: the serving/ts, serving/
    # hops and slo records are this bench's durable side channel.
    from rafiki_tpu import obs

    obs.configure_from_env(role="gateway")

    def _run_route(route):
        rep = run_smoke_mode(args, route=route)
        rep["mode"] = "smoke-mp" if args.mp else "smoke"
        rep["route"] = route
        hops, fanout_ms = _hops_block()
        rep["hops"] = hops
        rep["ensemble_fanout_cost_ms"] = fanout_ms
        return rep

    if args.tenants:
        report = run_tenants_mode(args)
        unhealthy = [report]
    elif args.url and not args.smoke:
        report = run_url_mode(args)
        report["mode"] = "url"
        hops, fanout_ms = _hops_block()
        report["hops"] = hops
        report["ensemble_fanout_cost_ms"] = fanout_ms
        unhealthy = [report]
    elif args.route == "both":
        from rafiki_tpu import telemetry

        replicated = _run_route("replicated")
        telemetry.reset()  # per-route hops/fanout, not a blended view
        stacked = _run_route("stacked")
        # Stacked headline at top level (the route the PR ships), the
        # per-route before/after under ``routes`` for the trend gate.
        report = dict(stacked)
        report["route"] = "both"
        report["routes"] = {"replicated": replicated, "stacked": stacked}
        unhealthy = [replicated, stacked]
    else:
        report = _run_route(args.route)
        unhealthy = [report]

    report["schema_version"] = SCHEMA_VERSION

    print(json.dumps(report, indent=2))

    bad = [r for r in unhealthy if r["errors"] or not r["ok"]]
    if bad:
        for r in bad:
            print(f"bench_serving: unhealthy {r.get('route', 'url')} run "
                  f"({r['errors']} errors, {r['ok']} ok)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
