#!/usr/bin/env python
"""Closed-loop serving load generator for the predict path.

Two modes:

  * ``--url http://host:port`` — drive a LIVE predictor endpoint
    (``predictor_host`` from the inference-job row) with N closed-loop
    clients for a fixed request count, measuring end-to-end latency
    through the serving gateway.
  * ``--smoke`` (default when no --url) — fully in-process and
    deterministic: stub-model workers on the in-proc bus behind a real
    Gateway + PredictorApp WSGI stack, exercised through the werkzeug
    test client. No sockets, no sleeps beyond the stub service time —
    the tier-1 wiring in scripts/check_tier1.sh runs this variant.

Output: one JSON object on stdout:

  {"qps": ..., "p50_ms": ..., "p99_ms": ..., "shed_rate": ...,
   "requests": ..., "ok": ..., "shed": ..., "errors": ...}

Closed-loop means each client fires its next request only after the
previous one answered (or was shed) — offered load adapts to service
rate, the standard arrangement for latency benchmarking. Shed (429)
responses count toward shed_rate, not latency percentiles.

Exit code: 0 on a sane run; 1 when the run itself misbehaved (5xx
responses, zero completed requests) — that makes the smoke variant a
CI gate, not just a number printer.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def percentile(sorted_xs, p):
    if not sorted_xs:
        return None
    last = len(sorted_xs) - 1
    return sorted_xs[min(last, int(last * p / 100))]


class ClosedLoopClient:
    """One closed-loop worker: POST, record, repeat."""

    def __init__(self, post, n_requests, payload, record):
        self._post = post          # (payload) -> (status_code, latency_s)
        self._n = n_requests
        self._payload = payload
        self._record = record

    def run(self):
        for _ in range(self._n):
            t0 = time.monotonic()
            try:
                status = self._post(self._payload)
            except Exception:
                status = -1
            self._record(status, time.monotonic() - t0)


class Recorder:
    def __init__(self):
        self._lock = threading.Lock()
        self.latencies_s = []
        self.ok = 0
        self.shed = 0
        self.errors = 0

    def record(self, status, latency_s):
        with self._lock:
            if status == 200:
                self.ok += 1
                self.latencies_s.append(latency_s)
            elif status == 429:
                self.shed += 1
            else:
                self.errors += 1

    def report(self, elapsed_s):
        with self._lock:
            xs = sorted(self.latencies_s)
            total = self.ok + self.shed + self.errors
            return {
                "requests": total,
                "ok": self.ok,
                "shed": self.shed,
                "errors": self.errors,
                "qps": round(total / elapsed_s, 2) if elapsed_s else None,
                "p50_ms": (None if not xs
                           else round(percentile(xs, 50) * 1000, 3)),
                "p99_ms": (None if not xs
                           else round(percentile(xs, 99) * 1000, 3)),
                "shed_rate": round(self.shed / total, 4) if total else None,
            }


def run_load(post, n_clients, requests_per_client, payload):
    recorder = Recorder()
    clients = [ClosedLoopClient(post, requests_per_client, payload,
                                recorder.record)
               for _ in range(n_clients)]
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    return recorder.report(time.monotonic() - t0)


def run_url_mode(args):
    import requests

    url = args.url.rstrip("/") + "/predict"
    session = requests.Session()

    def post(payload):
        resp = session.post(url, json=payload, timeout=args.deadline_s + 5)
        return resp.status_code

    payload = {"queries": [[1.0]] * args.queries_per_request,
               "deadline_s": args.deadline_s}
    return run_load(post, args.clients, args.requests_per_client, payload)


def run_smoke_mode(args):
    from werkzeug.test import Client

    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.gateway import Gateway, GatewayConfig
    from rafiki_tpu.predictor import Predictor
    from rafiki_tpu.predictor.app import PredictorApp
    from rafiki_tpu.worker.inference import InferenceWorker

    class StubModel:
        """Fixed service time, fixed output — no jax, no compile."""

        def predict(self, queries):
            time.sleep(args.service_ms / 1000.0)
            return [[0.6, 0.4] for _ in queries]

    bus = InProcBus()
    stop = threading.Event()
    threads = []
    for i in range(args.workers):
        w = InferenceWorker(bus, "bench", f"bw{i}", StubModel(),
                            stop_event=stop)
        th = threading.Thread(target=w.run, daemon=True)
        threads.append(th)
        th.start()
    deadline = time.monotonic() + 10
    while len(bus.get_workers("bench")) < args.workers:
        if time.monotonic() > deadline:
            raise RuntimeError("bench workers never registered")
        time.sleep(0.005)

    predictor = Predictor(bus, "bench", timeout_s=args.deadline_s)
    gateway = Gateway(predictor, GatewayConfig(
        max_inflight=args.max_inflight, max_queue=args.max_queue,
        hedge_grace_s=0.02))
    wsgi = Client(PredictorApp(gateway))

    def post(payload):
        return wsgi.post("/predict", json=payload).status_code

    payload = {"queries": [[1.0]] * args.queries_per_request,
               "deadline_s": args.deadline_s}
    try:
        return run_load(post, args.clients, args.requests_per_client, payload)
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=2)


def main(argv=None):
    # Platform pin FIRST: this process may import jax transitively via
    # the worker/model stack, and the image's sitecustomize would
    # otherwise hang backend init with the TPU tunnel down.
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--url", help="live predictor base URL; omit for the "
                                  "in-process smoke run")
    ap.add_argument("--smoke", action="store_true",
                    help="force the in-process deterministic run")
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--requests-per-client", type=int, default=25)
    ap.add_argument("--queries-per-request", type=int, default=4)
    ap.add_argument("--deadline-s", type=float, default=2.0)
    ap.add_argument("--workers", type=int, default=2,
                    help="stub inference workers (smoke mode)")
    ap.add_argument("--service-ms", type=float, default=1.0,
                    help="stub model service time (smoke mode)")
    ap.add_argument("--max-inflight", type=int, default=4)
    ap.add_argument("--max-queue", type=int, default=8)
    args = ap.parse_args(argv)

    if args.url and not args.smoke:
        report = run_url_mode(args)
        report["mode"] = "url"
    else:
        report = run_smoke_mode(args)
        report["mode"] = "smoke"

    print(json.dumps(report, indent=2))

    if report["errors"] or not report["ok"]:
        print(f"bench_serving: unhealthy run ({report['errors']} errors, "
              f"{report['ok']} ok)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
