#!/usr/bin/env python
"""Sharded-lane CI smoke: one big trial across a chip group, with a
mid-trial member loss and a reshard-on-restore resume (docs/sharding.md).

Two polarities, both required for the gate:

  * POSITIVE — the ``chip-loss-mid-sharded-trial`` chaos scenario end
    to end: a width-2 group loses a member mid-epoch, checkpoints stay
    durable, the group re-forms at width 1, the restore reshards 2→1,
    and the finished trial's params bit-match an unfaulted serial run.
    The preempt fault must ACTUALLY fire — a vacuous pass (nothing
    injected, nothing recovered) fails the gate.
  * NEGATIVE — a doctored wrong-width chunk (a width-4 shard spliced
    into a width-2 manifest) must be REFUSED, naming the chunk. A
    restore that silently accepts mismatched slices would corrupt
    params instead of failing loudly.

The lane leg also journals a real plan/save/reshard sequence into a
tempdir and drives the ``obs shard`` verb over it, so the forensic
reader is exercised against freshly written records, and times the
reshard restore for the SHARD_r*.json bench artifact
(scripts/bench_report.py --shard).

Output: one JSON object on stdout. Exit code: 0 iff the gate holds —
this is a CI gate (scripts/check_tier1.sh), not just a number printer.
"""

from __future__ import annotations

import contextlib
import io
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCENARIO = "chip-loss-mid-sharded-trial"


def _lane_leg(problems: list) -> dict:
    """A journaled plan/train/save/reshard round plus the doctored
    wrong-width refusal, in-process on the virtual pod."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from rafiki_tpu.obs.journal import journal
    from rafiki_tpu.shard import (ShardPlan, ShardedTrainLoop, gather_state,
                                  restore_sharded, save_sharded)
    from rafiki_tpu.store.params import ParamsStore

    import flax.linen as nn
    import optax

    class Mlp(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(4)(nn.relu(nn.Dense(16)(x)))

    m = Mlp()

    def init_fn(rng):
        return m.init(rng, jnp.zeros((1, 8), jnp.float32))

    def apply_fn(p, x):
        return m.apply(p, x)

    def loss_fn(p, batch, rng=None):
        logits = apply_fn(p, batch["x"])
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["y"]).mean()
        return loss, {"acc": (logits.argmax(-1) == batch["y"]).mean()}

    class _Ds:
        def __init__(self):
            rng = np.random.default_rng(0)
            self.x = rng.normal(size=(64, 8)).astype(np.float32)
            self.y = rng.integers(0, 4, size=(64,)).astype(np.int32)
            self.size = 64
            self.mask = None

    ds = _Ds()
    devs = jax.devices()
    epochs = 2
    prev = (journal.log_dir if journal.configured else None, journal.role)
    with tempfile.TemporaryDirectory() as d:
        journal.configure(d, role="shard-smoke")
        try:
            loops = {}
            t_train = time.monotonic()
            for w in (2, 4):
                plan = ShardPlan(width=w, family="mlp")
                plan.note()
                loop = ShardedTrainLoop(
                    init_fn, apply_fn, loss_fn, devices=devs[:w], seed=3,
                    plan=plan, program_key=("shard_smoke", "mlp"))
                for ep in range(epochs):
                    loop.run_epoch(ds, 8, epoch_seed=3 + ep)
                loops[w] = loop
            # lint: disable=RF007 — smoke artifact wall-clock
            trial_s = (time.monotonic() - t_train) / 2

            store = ParamsStore(os.path.join(d, "params"))
            save_sharded(store, "a", epochs - 1, loops[2].state, 2)
            save_sharded(store, "b", epochs - 1, loops[4].state, 4)

            # reshard 2→4, timed — the recovery headline
            _ep, blob = store.latest_checkpoint("a")
            t0 = time.monotonic()
            restored = restore_sharded(store, blob, loops[4].state,
                                       loops[4].mesh, loops[4].plan)
            # lint: disable=RF007 — smoke artifact wall-clock
            restore_s = time.monotonic() - t0
            la = jax.tree_util.tree_leaves(gather_state(restored))
            lb = jax.tree_util.tree_leaves(gather_state(loops[2].state))
            bitmatch = len(la) == len(lb) and all(
                np.asarray(x).dtype == np.asarray(y).dtype
                and np.array_equal(np.asarray(x), np.asarray(y))
                for x, y in zip(la, lb))
            if not bitmatch:
                problems.append("reshard 2->4 did not bit-match the source")

            # NEGATIVE polarity: splice a width-4 chunk into the
            # width-2 manifest — the restore must refuse, naming it.
            man = json.loads(blob.decode())
            bad_chunk = f"b_ckpt_{epochs - 1}_s0of4"
            man["shards"][0] = bad_chunk
            caught = False
            try:
                restore_sharded(store, json.dumps(man).encode(),
                                loops[2].state, loops[2].mesh, loops[2].plan)
            except IOError as e:
                caught = bad_chunk in str(e)
            if not caught:
                problems.append(
                    "doctored wrong-width chunk was NOT refused by name")

            # drive the forensic reader over the fresh records
            from rafiki_tpu.obs.cli import cmd_shard
            buf = io.StringIO()
            with contextlib.redirect_stdout(buf):
                obs_rc = cmd_shard(d, as_json=True)
            obs_rows = [json.loads(ln) for ln in buf.getvalue().splitlines()
                        if ln.strip()]
            if obs_rc != 0 or not obs_rows:
                problems.append("obs shard saw no records in a journaled "
                                "lane run")
            return {
                "restore_s": round(restore_s, 4),
                "group_trials_per_hour": round(3600.0 / (trial_s * 1.0), 2),
                "reshard_bitmatch": bitmatch,
                "wrong_width_refused": caught,
                "obs_shard_rows": len(obs_rows),
            }
        finally:
            if prev[0] is not None:
                journal.configure(prev[0], role=prev[1])
            else:
                journal.close()


def main() -> int:
    # Platform pin BEFORE jax loads; then fake a multi-chip pod on the
    # host platform (same 8-virtual-device shape as the test suite).
    from rafiki_tpu.utils.backend import (ensure_host_device_count,
                                          honor_env_platform)

    honor_env_platform()
    ensure_host_device_count(8)

    from rafiki_tpu.chaos.runner import format_report, run_scenario

    problems: list = []
    t0 = time.monotonic()
    report = run_scenario(SCENARIO)
    injected = [s for s in report.schedule if s[0] == "scheduler.preempt"]
    if not report.passed:
        problems.append("scenario invariants violated")
    if not injected:
        problems.append("no scheduler.preempt fault fired (vacuous pass)")

    lane = _lane_leg(problems)
    out = {
        "scenario": SCENARIO,
        "passed": report.passed,
        "member_loss_injected": len(injected),
        **lane,
        # lint: disable=RF007 — smoke artifact wall-clock
        "wall_s": round(time.monotonic() - t0, 2),
        "report": report.to_dict(),
    }
    if problems:
        out["problems"] = problems
    print(json.dumps(out, indent=2))
    if problems:
        print(format_report(report), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
