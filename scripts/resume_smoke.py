#!/usr/bin/env python
"""Crash-recovery CI smoke: a SIGKILLed sweep must resume to
completion in a fresh process, and a doctored WAL must refuse loudly
(docs/recovery.md).

Three phases, ~15s total:

  1. **Kill + resume** — a 4-trial RandomAdvisor sweep run through
     ``scheduler/sweep_proc.py`` with a ``supervisor.tick:kill`` fault
     installed: the whole control plane dies by SIGKILL after its
     warmup claims. A second ``sweep_proc resume`` process must adopt
     the job, reconcile the WAL with zero duplicate claims, drive it
     to COMPLETED with exactly budget-many trial rows, and ``obs
     resume`` must reconstruct the timeline from the journals alone.
     The measured recovery becomes the RESUME artifact (recovery
     wall-clock, salvaged/restarted split, duplicate claims).
  2. **Doctored WAL** — a WAL claiming a commit for a trial row that
     does not exist (``committed_unclaimed``): resume must exit
     non-zero naming the reconciliation failure instead of adopting a
     job whose budget accounting is provably wrong.
  3. **Report gate, both polarities** — ``bench_report --resume`` over
     synthetic RESUME_r*.json rounds: an improving trend exits 0, a
     collapsed round (recovery up, duplicate claims non-zero) exits 1,
     and an error round reads as no-data, not an instant recovery.

Output: one JSON object on stdout. Exit 0 when every assertion holds;
1 otherwise — this is a CI gate (scripts/check_tier1.sh). ``--out
PATH`` additionally writes phase 1's RESUME artifact to PATH.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESUME_SCHEMA_VERSION = 1
BUDGET, CHIPS, TRIALS_PER_CHIP = 4, 2, 2
SPEC = "seed=23;supervisor.tick:kill:after=30:times=1:match=g0"


def _child_env(log_dir, chaos: bool):
    from rafiki_tpu.chaos.scenarios import _sweep_proc_env

    env = _sweep_proc_env(chaos=False)  # never inherit a caller's spec
    env["RAFIKI_LOG_DIR"] = str(log_dir)
    env["RAFIKI_SUPERVISOR_HEARTBEAT_S"] = "0.2"
    env["RAFIKI_CHECKPOINT_EVERY"] = "1"
    if chaos:
        env["RAFIKI_CHAOS"] = SPEC
    return env


def phase_kill_resume(results):
    from rafiki_tpu.chaos.scenarios import _make_job, _sweep_proc, _train_env
    from rafiki_tpu.scheduler.wal import read_wal, reconcile, wal_path

    tmp = Path(tempfile.mkdtemp(prefix="resume_smoke_"))
    log_dir = tmp / "obs"
    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": BUDGET})

    killed, _ = _sweep_proc(
        "run", store, params, job["id"], chips=CHIPS,
        trials_per_chip=TRIALS_PER_CHIP, advisor="random",
        env=_child_env(log_dir, chaos=True))
    resumed, summary = _sweep_proc(
        "resume", store, params, job["id"], chips=CHIPS,
        trials_per_chip=TRIALS_PER_CHIP, stale_after_s=0.4,
        env=_child_env(log_dir, chaos=False))

    trials = store.get_trials_of_train_job(job["id"])
    wal_recs = read_wal(wal_path(store.path, job["id"]))
    rec = reconcile(wal_recs, trials)
    dup = sum(1 for r in summary.get("reconcile", [])
              for e in r.get("errors", []) if e["type"] == "duplicate_claim")

    obs = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.obs", "--dir", str(log_dir),
         "resume", job["id"]],
        env=_child_env(log_dir, chaos=False), capture_output=True,
        text=True, timeout=60)

    ph = {
        "killed_rc": killed.returncode,
        "resume_rc": resumed.returncode,
        "resume_mode": summary.get("mode"),
        "adopted": summary.get("adopted"),
        "job_status": summary.get("status"),
        "trial_rows": len(trials),
        "all_completed": all(t["status"] == "COMPLETED" for t in trials),
        "wal_reconciles": rec.ok,
        "duplicate_claims": dup,
        "obs_resume_rc": obs.returncode,
        "obs_resume_reconstructs": "resumed:" in obs.stdout,
        "ok": False,
    }
    ph["ok"] = (ph["killed_rc"] == -9 and ph["resume_rc"] == 0
                and ph["resume_mode"] == "wal"
                and (ph["adopted"] or 0) > 0
                and ph["job_status"] == "COMPLETED"
                and ph["trial_rows"] == BUDGET and ph["all_completed"]
                and ph["wal_reconciles"] and dup == 0
                and ph["obs_resume_rc"] == 0
                and ph["obs_resume_reconstructs"])
    if not ph["ok"]:
        ph["killed_stderr"] = killed.stderr[-300:]
        ph["resume_stderr"] = resumed.stderr[-300:]
        ph["reconcile_errors"] = rec.errors
    results["kill_resume"] = ph
    artifact = {
        "resume_schema_version": RESUME_SCHEMA_VERSION,
        "recovery_wall_s": summary.get("wall_s"),
        "trials_salvaged": summary.get("salvaged"),
        "trials_restarted": summary.get("restarted"),
        "duplicate_claims": dup,
        "detail": {"budget": BUDGET, "chips": CHIPS,
                   "adopted": summary.get("adopted"),
                   "generation": summary.get("generation"),
                   "spec": SPEC},
    }
    if not ph["ok"]:
        artifact["error"] = "kill/resume phase failed"
    return ph["ok"], artifact


def phase_doctored(results):
    """A WAL that commits a budget claim for a trial row the store has
    never seen: adopting anyway would compound the damage, so resume
    must refuse with the failure named."""
    from rafiki_tpu.chaos.scenarios import _make_job, _sweep_proc, _train_env
    from rafiki_tpu.constants import TrainJobStatus
    from rafiki_tpu.scheduler.wal import SweepWal, wal_path

    tmp = Path(tempfile.mkdtemp(prefix="resume_smoke_doctored_"))
    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": BUDGET})
    store.update_train_job_status(job["id"], TrainJobStatus.RUNNING.value)
    wal = SweepWal(wal_path(store.path, job["id"]))
    wal.note("sweep_config", advisor_kind="random", chips=CHIPS,
             trials_per_chip=TRIALS_PER_CHIP)
    txn = wal.intent("budget_claim", knobs_hash="h")
    wal.commit(txn, "budget_claim", trial_id="ghost")
    wal.close()

    proc, _ = _sweep_proc(
        "resume", store, params, job["id"], chips=CHIPS,
        trials_per_chip=TRIALS_PER_CHIP, stale_after_s=0.4,
        env=_child_env(tmp / "obs", chaos=False))
    ph = {
        "rc": proc.returncode,
        "refuses": proc.returncode == 1,
        "names_failure": "committed_unclaimed" in proc.stderr,
        "ok": proc.returncode == 1 and "committed_unclaimed" in proc.stderr,
    }
    if not ph["ok"]:
        ph["stderr"] = proc.stderr[-400:]
    results["doctored"] = ph
    return ph["ok"]


def phase_report_gate(results, artifact):
    """bench_report --resume over synthetic rounds seeded from the real
    r01 artifact, both polarities."""
    td = tempfile.mkdtemp(prefix="resume_rounds_")

    def _round(n, doc):
        path = os.path.join(td, f"RESUME_r{n:02d}.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    def _run(paths):
        return subprocess.run(
            [sys.executable, "scripts/bench_report.py", "--resume", *paths],
            capture_output=True, text=True, env=dict(os.environ), cwd=REPO,
            timeout=60)

    improving = [
        _round(1, dict(artifact, recovery_wall_s=12.0)),
        _round(2, dict(artifact, recovery_wall_s=10.5)),
        _round(3, {"resume_schema_version": RESUME_SCHEMA_VERSION,
                   "error": "resume never completed"}),
        _round(4, dict(artifact, recovery_wall_s=9.8)),
    ]
    ok_run = _run(improving)
    regressed = improving + [
        _round(5, dict(artifact, recovery_wall_s=40.0, duplicate_claims=2))]
    bad_run = _run(regressed)
    try:
        ok_doc = json.loads(ok_run.stdout)
        bad_doc = json.loads(bad_run.stdout)
    except ValueError:
        ok_doc, bad_doc = {}, {}
    error_round_has_data = any(
        r.get("has_data") for r in ok_doc.get("rounds", [])
        if str(r.get("round", "")).endswith("r03.json"))
    ph = {
        "ok_rc": ok_run.returncode,
        "ok_verdict": ok_doc.get("verdict"),
        "regressed_rc": bad_run.returncode,
        "regressed_metrics": bad_doc.get("regressed"),
        "error_round_counted": error_round_has_data,
        "ok": (ok_run.returncode == 0 and ok_doc.get("verdict") == "ok"
               and bad_run.returncode == 1
               and "recovery_wall_s" in (bad_doc.get("regressed") or [])
               and "duplicate_claims" in (bad_doc.get("regressed") or [])
               and not error_round_has_data),
    }
    if not ph["ok"]:
        ph["ok_stderr"] = ok_run.stderr[-300:]
        ph["regressed_stderr"] = bad_run.stderr[-300:]
    results["report_gate"] = ph
    return ph["ok"]


def main() -> int:
    ap = argparse.ArgumentParser(prog="scripts/resume_smoke.py")
    ap.add_argument("--out", help="also write the RESUME artifact here")
    args = ap.parse_args()

    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()  # pin the platform before the scenario helpers
    # pull in jax: off-TPU the run must not hang in backend init (RF001).

    results = {}
    ok, artifact = phase_kill_resume(results)
    if ok:
        ok = phase_doctored(results) and ok
    if ok:
        ok = phase_report_gate(results, artifact) and ok
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    results["ok"] = ok
    print(json.dumps(results))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
