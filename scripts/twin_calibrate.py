#!/usr/bin/env python
"""Extract a versioned twin calibration bundle from a journal dir.

    python scripts/twin_calibrate.py /path/to/journals -o twin_cal.json

Reads the merged ``journal-*.jsonl`` rings under the directory and
distills the three ingredients the simulator needs — hop-segment
sample distributions (``serving/hops``), the live gateway knobs
(``gateway/config``) and XLA cost rows (``perf/cost``) — into one
``calibration_version``-stamped JSON the twin CLI and tests load
byte-reproducibly.

With ``--train`` the TRAIN twin's bundle is extracted instead:
per-(packing_key, k) epoch samples (``perf/step``), the captured pack
placement (``mesh/pack_formed``) and sweep shape, fitted epoch
overhead, and cost rows (docs/twin.md). The usual fix for a missing-
kinds failure there is ``scripts/train_twin_smoke.py --capture DIR``.

Fails LOUDLY (exit 2) listing every missing record kind rather than
defaulting anything: a twin calibrated on air predicts air. The usual
fix is re-running the workload (e.g. ``scripts/bench_serving.py
--smoke``) with ``RAFIKI_LOG_DIR`` pointed at a fresh directory.

Exit codes: 0 bundle written, 2 calibration impossible (missing
kinds / unreadable dir), plus a summary line on stdout either way.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from rafiki_tpu.obs.twin.calibration import Calibration, CalibrationError


def main(argv: Optional[List[str]] = None) -> int:
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()  # never hang in TPU init when the tunnel is down
    p = argparse.ArgumentParser(
        prog="scripts/twin_calibrate.py",
        description="journal dir -> versioned twin calibration bundle")
    p.add_argument("log_dir", help="journal directory (RAFIKI_LOG_DIR "
                                   "of a captured serving run)")
    p.add_argument("-o", "--out", default="twin_cal.json",
                   help="bundle path (default twin_cal.json)")
    p.add_argument("--json", action="store_true",
                   help="emit the summary as JSON instead of prose")
    p.add_argument("--train", action="store_true",
                   help="extract the TRAIN twin's bundle (perf/step + "
                        "mesh/pack_formed) instead of the serving one")
    args = p.parse_args(argv)

    if args.train:
        return _main_train(args)

    try:
        cal = Calibration.from_journal_dir(args.log_dir)
    except CalibrationError as e:
        if args.json:
            print(json.dumps({"error": str(e), "missing": e.missing,
                              "source": e.source}))
        else:
            print(f"twin_calibrate: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"twin_calibrate: cannot read {args.log_dir}: {e}",
              file=sys.stderr)
        return 2

    cal.save(args.out)
    summary = {
        "out": args.out,
        "calibration_version": cal.version,
        "source": cal.source,
        "workers": cal.workers,
        "segments": {s: len(xs) for s, xs in sorted(cal.segments.items())},
        "cost_rows": len(cal.cost),
        "gateway_knobs": len(cal.gateway),
    }
    if args.json:
        print(json.dumps(summary))
    else:
        segs = ", ".join(f"{s}:{n}" for s, n in summary["segments"].items())
        print(f"wrote {args.out}: v{cal.version} bundle from "
              f"{cal.source} — {cal.workers} worker(s), "
              f"{summary['cost_rows']} cost row(s), samples [{segs}]")
    return 0


def _main_train(args) -> int:
    from rafiki_tpu.obs.twin.train.calibration import (TrainCalibration,
                                                       TrainCalibrationError)
    try:
        cal = TrainCalibration.from_journal_dir(args.log_dir)
    except TrainCalibrationError as e:
        if args.json:
            print(json.dumps({"error": str(e), "missing": e.missing,
                              "source": e.source}))
        else:
            print(f"twin_calibrate: {e}", file=sys.stderr)
        return 2
    except OSError as e:
        print(f"twin_calibrate: cannot read {args.log_dir}: {e}",
              file=sys.stderr)
        return 2

    cal.save(args.out)
    summary = {
        "out": args.out,
        "train_calibration_version": cal.version,
        "source": cal.source,
        "packing_keys": len(cal.packing_keys()),
        "packs": len(cal.packs),
        "sweep": cal.sweep,
        "epoch_overhead_s": round(cal.epoch_overhead_s, 6),
        "cost_rows": len(cal.cost),
    }
    if args.json:
        print(json.dumps(summary))
    else:
        print(f"wrote {args.out}: v{cal.version} train bundle from "
              f"{cal.source} — {summary['packing_keys']} packing key(s), "
              f"{summary['packs']} pack(s), "
              f"overhead {summary['epoch_overhead_s']}s/epoch, "
              f"{summary['cost_rows']} cost row(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
