#!/usr/bin/env python
"""Observability CI smoke: one traced query stitched across processes.

End-to-end check of the observability plane (docs/observability.md):

  1. train one tiny trial, then serve it from TWO real inference worker
     processes over the mp bus — journals land under a shared
     ``RAFIKI_LOG_DIR`` (one JSONL file per process);
  2. POST one query through the gateway WSGI app with a pinned
     ``X-Rafiki-Trace-Id``, then run the REAL reader —
     ``python -m rafiki_tpu.obs trace <id>`` — and assert the stitched
     trace spans >= 3 distinct processes (gateway + both workers);
  3. GET ``/metrics?format=prom`` and line-parse the exposition: every
     line must be a comment or a ``name[{labels}] value`` sample.

Output: one JSON object on stdout, e.g.

  {"trace_id": ..., "trace_records": 9, "trace_processes": 3,
   "prom_lines": 120, "wall_s": ...}

Exit code: 0 when every assertion holds; 1 otherwise — this is a CI
gate (scripts/check_tier1.sh), not just a number printer.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
import time
import uuid

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL_SRC = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.ff import _Mlp

class ObsFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
            "batch_size": FixedKnob(64),
            "epochs": FixedKnob(2),
            "seed": FixedKnob(0),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1, hidden_units=32, num_classes=num_classes)
"""

TRAIN = "synthetic://images?classes=4&n=256&w=8&h=8&c=1&seed=0"
VAL = "synthetic://images?classes=4&n=64&w=8&h=8&c=1&seed=1"
JOB = "obs-smoke"
N_WORKERS = 2

# Prometheus text exposition: comments, or `name[{labels}] value`.
_PROM_COMMENT = re.compile(r"^# (TYPE|HELP) ")
_PROM_SAMPLE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.e+-]+(\s+[0-9]+)?$')


def _spawn_workers(ctx, bus, tmp, trial_id):
    import multiprocessing  # noqa: F401  (spawn ctx passed in)

    from rafiki_tpu.worker.inference import run_inference_worker_process

    procs = [
        ctx.Process(
            target=run_inference_worker_process,
            args=(bus, os.path.join(tmp, "meta.sqlite3"),
                  os.path.join(tmp, "params"), trial_id, JOB, f"ow-{i}"),
            daemon=True)
        for i in range(N_WORKERS)
    ]
    for p in procs:
        p.start()
    deadline = time.monotonic() + 120
    while len(bus.get_workers(JOB)) < len(procs):
        dead = [(p.name, p.exitcode) for p in procs if not p.is_alive()]
        if dead:
            raise RuntimeError(f"worker died before registering: {dead}")
        if time.monotonic() > deadline:
            raise RuntimeError("inference workers never registered")
        time.sleep(0.05)
    return procs


def _stitch_via_cli(log_dir: str, trace_id: str):
    """Run the real reader — the exact command docs/observability.md
    tells an operator to run — and parse its JSONL output."""
    proc = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.obs", "--dir", log_dir,
         "--json", "trace", trace_id],
        capture_output=True, text=True, timeout=60)
    if proc.returncode != 0:
        raise RuntimeError(f"obs trace exited {proc.returncode}: "
                           f"{proc.stderr.strip()[:300]}")
    records = [json.loads(line) for line in proc.stdout.splitlines() if line]
    return records


def main() -> int:
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    import multiprocessing as mp

    import numpy as np
    from werkzeug.test import Client

    from rafiki_tpu.bus import make_mp_bus
    from rafiki_tpu.gateway import Gateway, GatewayConfig
    from rafiki_tpu.model.base import load_model_class  # noqa: F401 (validates src)
    from rafiki_tpu.obs.journal import journal
    from rafiki_tpu.predictor import Predictor
    from rafiki_tpu.predictor.app import PredictorApp
    from rafiki_tpu.scheduler import LocalScheduler
    from rafiki_tpu.store import MetaStore, ParamsStore

    t0 = time.monotonic()
    problems = []
    with tempfile.TemporaryDirectory(prefix="rafiki-obssmoke-") as tmp:
        log_dir = os.path.join(tmp, "obs")
        # The spawn env is the propagation channel: children inherit
        # RAFIKI_LOG_DIR and open their own journal files under it.
        os.environ["RAFIKI_LOG_DIR"] = log_dir
        journal.configure(log_dir, role="gateway")

        store = MetaStore(os.path.join(tmp, "meta.sqlite3"))
        params = ParamsStore(os.path.join(tmp, "params"))
        model = store.create_model("obsff", "IMAGE_CLASSIFICATION", None,
                                   MODEL_SRC, "ObsFF")
        job = store.create_train_job("obs", "IMAGE_CLASSIFICATION", None,
                                     TRAIN, VAL, {"MODEL_TRIAL_COUNT": 1})
        store.create_sub_train_job(job["id"], model["id"])
        result = LocalScheduler(store, params).run_train_job(
            job["id"], n_workers=1, advisor_kind="random")
        best = result.best_trials[0]

        ctx = mp.get_context("spawn")
        bus = make_mp_bus(ctx.Manager())
        procs = _spawn_workers(ctx, bus, tmp, best["id"])
        try:
            predictor = Predictor(bus, JOB, timeout_s=10.0, worker_ttl_s=3.0)
            gateway = Gateway(predictor, GatewayConfig(min_replies=2))
            wsgi = Client(PredictorApp(gateway))
            query = np.random.default_rng(0).uniform(
                0, 1, size=(1, 8, 8, 1)).astype(np.float32)
            payload = {"queries": [q.tolist() for q in query]}

            # Warm until both subprocess compiles are paid and a batch
            # answers cleanly within the deadline.
            deadline = time.monotonic() + 120
            while True:
                r = wsgi.post("/predict", json=payload)
                body = r.get_json() or {}
                preds = body.get("predictions") or []
                if r.status_code == 200 and preds and all(
                        not (isinstance(p, dict) and "error" in p)
                        for p in preds):
                    break
                if time.monotonic() > deadline:
                    raise RuntimeError(
                        f"serving never warmed: {r.status_code} "
                        f"{str(body)[:200]}")
                time.sleep(0.5)

            # THE traced query: pin the id, like a caller would.
            tid = uuid.uuid4().hex
            r = wsgi.post("/predict", json=payload,
                          headers={"X-Rafiki-Trace-Id": tid})
            if r.status_code != 200:
                problems.append(f"traced query failed: {r.status_code}")
            if (r.get_json() or {}).get("trace_id") != tid:
                problems.append("gateway did not echo the pinned trace id")

            # Stitch via the real CLI. Worker journal writes are
            # line-buffered, but give the pop→journal hop a beat.
            records, pids = [], set()
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline:
                records = _stitch_via_cli(log_dir, tid)
                pids = {(rec.get("role"), rec.get("pid")) for rec in records}
                if len(pids) >= 3:
                    break
                time.sleep(0.25)
            if len(pids) < 3:
                problems.append(
                    f"trace {tid} stitched only {len(pids)} processes "
                    f"({sorted(pids)}), expected >= 3")
            if not any(rec.get("kind") == "bus" for rec in records):
                problems.append("no bus hop in the stitched trace")

            # Prometheus exposition must line-parse.
            pr = wsgi.get("/metrics?format=prom")
            prom_lines = []
            if pr.status_code != 200:
                problems.append(f"/metrics?format=prom -> {pr.status_code}")
            else:
                prom_lines = pr.get_data(as_text=True).splitlines()
                bad = [ln for ln in prom_lines
                       if ln and not _PROM_COMMENT.match(ln)
                       and not _PROM_SAMPLE.match(ln)]
                if bad:
                    problems.append(f"unparseable prom lines: {bad[:3]}")
                if not any(ln.startswith("rafiki_predictor_queries")
                           for ln in prom_lines):
                    problems.append(
                        "rafiki_predictor_queries missing from exposition")
        finally:
            for p in procs:
                if p.is_alive():
                    p.kill()
            journal.close()
            os.environ.pop("RAFIKI_LOG_DIR", None)

        out = {
            "trace_id": tid,
            "trace_records": len(records),
            "trace_processes": len(pids),
            "prom_lines": len(prom_lines),
            # lint: disable=RF007 — smoke artifact wall-clock
            "wall_s": round(time.monotonic() - t0, 3),
        }
        if problems:
            out["problems"] = problems
        print(json.dumps(out))
        return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
