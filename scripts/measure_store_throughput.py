"""Measure the store plane: sqlite-WAL meta ceiling + CAS params dedup.

SURVEY.md §7 step 5 prescribed a store "swap-able for Postgres"; this
deployment keeps sqlite-WAL (one TPU host drives the chips — the
control plane is host-local) and instead DOCUMENTS its measured
multi-process ceiling (docs/architecture.md "Meta-store scale"). Phase
one produces that number: N worker PROCESSES (sqlite contention is
cross-process file locking, so threads would flatter it) hammer one
store with the real trial-loop write mix — atomic budget-claimed trial
creation, per-epoch log appends, throttled heartbeats, completion
marks — and the run asserts the budget invariant held (exactly
max_trials trials) while reporting aggregate write-transactions/sec.

Phase two measures the content-addressed params store (store/cas.py,
docs/autoscale.md): a synthetic params-like tree is checkpointed, a
near-identical successor (one layer nudged — the shape of step N vs
step N+1) is checkpointed again, and the artifact reports how many
bytes the second write actually streamed. The ISSUE 14 acceptance
gate is ``second_write_frac < 0.20``: consecutive checkpoints must
ride chunk-level dedup, not rewrite the tree.

Usage::

    python scripts/measure_store_throughput.py [n_workers] [trials] \
        [--out STORE_rNN.json]

Prints one machine-readable JSON line (headline keys at top level —
``bench_report --store`` trends STORE_r*.json artifacts of it); exits
non-zero when the dedup gate fails.
"""

from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import pickle
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(db_path: str, sub_id: str, svc_id: str, max_trials: int,
            logs_per_trial: int, out_q) -> None:
    from rafiki_tpu.store import MetaStore

    store = MetaStore(db_path)
    ops = 0
    t0 = time.monotonic()
    while True:
        t = store.create_trial(sub_id, "M", {"lr": 0.1}, worker_id=str(os.getpid()),
                               service_id=svc_id, budget_max=max_trials)
        ops += 1
        if t is None:
            break
        for i in range(logs_per_trial):
            store.add_trial_log(t["id"], {"epoch": i, "loss": 0.5})
            ops += 1
        store.update_service(svc_id, heartbeat=True)
        store.mark_trial_as_completed(t["id"], 0.9, None)
        ops += 2
    out_q.put((ops, time.monotonic() - t0))


def _meta_phase(n_workers: int, max_trials: int) -> dict:
    logs_per_trial = 10
    from rafiki_tpu.store import MetaStore

    tmp = tempfile.mkdtemp(prefix="store-bench-")
    db = os.path.join(tmp, "meta.sqlite3")
    store = MetaStore(db)
    model = store.create_model("m", "T", None, b"x", "M")
    job = store.create_train_job("app", "T", None, "t", "v",
                                 {"MODEL_TRIAL_COUNT": max_trials})
    sub = store.create_sub_train_job(job["id"], model["id"])
    services = [store.create_service("TRAIN_WORKER") for _ in range(n_workers)]

    q = mp.Queue()
    procs = [mp.Process(target=_worker,
                        args=(db, sub["id"], services[i]["id"], max_trials,
                              logs_per_trial, q))
             for i in range(n_workers)]
    t0 = time.monotonic()
    for p in procs:
        p.start()
    results = [q.get(timeout=300) for _ in procs]
    for p in procs:
        p.join()
    wall = time.monotonic() - t0

    trials = store.get_trials_of_sub_train_job(sub["id"])
    assert len(trials) == max_trials, f"budget violated: {len(trials)}"
    assert all(t["status"] == "COMPLETED" for t in trials)
    total_ops = sum(r[0] for r in results)
    return {
        "n_worker_processes": n_workers,
        "trials": max_trials,
        "logs_per_trial": logs_per_trial,
        "wall_s": round(wall, 2),
        "write_txn_per_s": round(total_ops / wall, 1),
        "trials_per_s": round(max_trials / wall, 1),
        "budget_exact": True,
    }


def _synthetic_params(seed: int, n_layers: int = 16,
                      layer_kb: int = 64) -> bytes:
    """A params-like pickled tree: named float32 layers, the shape a
    JaxModel.dump_parameters blob has after serialization. Seeded so
    the first/second checkpoint relationship is reproducible."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n = (layer_kb * 1024) // 4
    tree = {f"layer_{i}/w": rng.standard_normal(n, dtype=np.float32)
            for i in range(n_layers)}
    return pickle.dumps(tree, protocol=4)


def _perturbed_params(seed: int, n_layers: int = 16,
                      layer_kb: int = 64) -> bytes:
    """The step-N+1 checkpoint: identical tree, ONE layer nudged.
    Real consecutive checkpoints differ in every layer, but by the
    pickle framing most chunk boundaries survive — this models the
    best case the dedup gate certifies the mechanism against."""
    import numpy as np

    rng = np.random.default_rng(seed)
    n = (layer_kb * 1024) // 4
    tree = {f"layer_{i}/w": rng.standard_normal(n, dtype=np.float32)
            for i in range(n_layers)}
    tree["layer_0/w"] = tree["layer_0/w"] + np.float32(1e-3)
    return pickle.dumps(tree, protocol=4)


def _cas_phase(seed: int = 0) -> dict:
    from rafiki_tpu.store.cas import CasParamsStore

    tmp = tempfile.mkdtemp(prefix="cas-bench-")
    store = CasParamsStore(tmp)
    first = _synthetic_params(seed)
    second = _perturbed_params(seed)

    t0 = time.monotonic()
    store.save(first, "trial_ckpt_1")
    first_dump_s = time.monotonic() - t0
    first_bytes = store.stats()["bytes_written"]

    t0 = time.monotonic()
    store.save(second, "trial_ckpt_2")
    cas_dump_s = time.monotonic() - t0
    second_bytes = store.stats()["bytes_written"] - first_bytes

    # Integrity before any throughput claim: both checkpoints must
    # round-trip bit-exactly through the chunk store.
    assert store.load("trial_ckpt_1") == first
    assert store.load("trial_ckpt_2") == second

    stats = store.stats()
    return {
        "cas_blob_bytes": len(first),
        "cas_chunk_bytes": stats["chunk_bytes"],
        "cas_first_write_bytes": first_bytes,
        "cas_second_write_bytes": second_bytes,
        "second_write_frac": round(second_bytes / max(1, first_bytes), 4),
        "dedup_ratio": stats["dedup_ratio"],
        "cas_first_dump_s": round(first_dump_s, 4),
        "cas_dump_s": round(cas_dump_s, 4),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="scripts/measure_store_throughput.py",
        description="meta-store ceiling + CAS params dedup, one JSON line")
    p.add_argument("n_workers", nargs="?", type=int, default=8)
    p.add_argument("trials", nargs="?", type=int, default=400)
    p.add_argument("--out", help="also write the artifact here "
                                 "(STORE_rNN.json round file)")
    args = p.parse_args(argv)

    doc = {"store_schema_version": 1}
    doc.update(_meta_phase(args.n_workers, args.trials))
    doc.update(_cas_phase())
    # The ISSUE 14 acceptance gate: a near-identical second checkpoint
    # streams deltas, not the tree.
    doc["dedup_gate"] = doc["second_write_frac"] < 0.20
    line = json.dumps(doc)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0 if doc["dedup_gate"] else 1


if __name__ == "__main__":
    sys.exit(main())
