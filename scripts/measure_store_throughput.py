"""Measure the sqlite-WAL meta store's ceiling under racing workers.

SURVEY.md §7 step 5 prescribed a store "swap-able for Postgres"; this
deployment keeps sqlite-WAL (one TPU host drives the chips — the
control plane is host-local) and instead DOCUMENTS its measured
multi-process ceiling (docs/architecture.md "Meta-store scale"). This
script produces that number: N worker PROCESSES (sqlite contention is
cross-process file locking, so threads would flatter it) hammer one
store with the real trial-loop write mix — atomic budget-claimed trial
creation, per-epoch log appends, throttled heartbeats, completion
marks — and the run asserts the budget invariant held (exactly
max_trials trials) while reporting aggregate write-transactions/sec.

Usage: python scripts/measure_store_throughput.py [n_workers] [trials]
Prints one JSON line.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _worker(db_path: str, sub_id: str, svc_id: str, max_trials: int,
            logs_per_trial: int, out_q) -> None:
    from rafiki_tpu.store import MetaStore

    store = MetaStore(db_path)
    ops = 0
    t0 = time.monotonic()
    while True:
        t = store.create_trial(sub_id, "M", {"lr": 0.1}, worker_id=str(os.getpid()),
                               service_id=svc_id, budget_max=max_trials)
        ops += 1
        if t is None:
            break
        for i in range(logs_per_trial):
            store.add_trial_log(t["id"], {"epoch": i, "loss": 0.5})
            ops += 1
        store.update_service(svc_id, heartbeat=True)
        store.mark_trial_as_completed(t["id"], 0.9, None)
        ops += 2
    out_q.put((ops, time.monotonic() - t0))


def main() -> None:
    n_workers = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    max_trials = int(sys.argv[2]) if len(sys.argv) > 2 else 400
    logs_per_trial = 10
    from rafiki_tpu.store import MetaStore

    tmp = tempfile.mkdtemp(prefix="store-bench-")
    db = os.path.join(tmp, "meta.sqlite3")
    store = MetaStore(db)
    model = store.create_model("m", "T", None, b"x", "M")
    job = store.create_train_job("app", "T", None, "t", "v",
                                 {"MODEL_TRIAL_COUNT": max_trials})
    sub = store.create_sub_train_job(job["id"], model["id"])
    services = [store.create_service("TRAIN_WORKER") for _ in range(n_workers)]

    q = mp.Queue()
    procs = [mp.Process(target=_worker,
                        args=(db, sub["id"], services[i]["id"], max_trials,
                              logs_per_trial, q))
             for i in range(n_workers)]
    t0 = time.monotonic()
    for p in procs:
        p.start()
    results = [q.get(timeout=300) for _ in procs]
    for p in procs:
        p.join()
    wall = time.monotonic() - t0

    trials = store.get_trials_of_sub_train_job(sub["id"])
    assert len(trials) == max_trials, f"budget violated: {len(trials)}"
    assert all(t["status"] == "COMPLETED" for t in trials)
    total_ops = sum(r[0] for r in results)
    print(json.dumps({
        "n_worker_processes": n_workers,
        "trials": max_trials,
        "logs_per_trial": logs_per_trial,
        "wall_s": round(wall, 2),
        "write_txn_per_s": round(total_ops / wall, 1),
        "trials_per_s": round(max_trials / wall, 1),
        "budget_exact": True,
    }))


if __name__ == "__main__":
    main()
