#!/usr/bin/env bash
# Stop the rafiki-tpu admin server started by scripts/start.sh.
# Reference parity: scripts/stop.sh (unverified — SURVEY.md §2).
set -euo pipefail

RUN_DIR="${RAFIKI_TPU_DATA_DIR:-$HOME/.rafiki_tpu}"
PID_FILE="$RUN_DIR/admin.pid"

if [[ ! -f "$PID_FILE" ]]; then
  echo "no pid file at $PID_FILE — nothing to stop"
  exit 0
fi
PID="$(cat "$PID_FILE")"
if kill -0 "$PID" 2>/dev/null; then
  kill "$PID"
  for _ in $(seq 1 50); do
    kill -0 "$PID" 2>/dev/null || break
    sleep 0.2
  done
  kill -0 "$PID" 2>/dev/null && kill -9 "$PID" || true
  echo "stopped admin (pid $PID)"
else
  echo "admin (pid $PID) was not running"
fi
rm -f "$PID_FILE"
