#!/usr/bin/env python
"""Numerics-health CI smoke: the whole containment chain, both polarities.

Two phases in one process (docs/health.md):

  1. **Quiet run (no injection)** — a 2-trial serial TrainWorker round
     under a fresh journal dir. The sentinels are ON (they always are)
     but must stay silent: ZERO ``health/divergence`` records, ZERO
     ``capsule-*.rcap`` files, zero divergences in ``health.stats()``,
     and the real ``obs health`` CLI must render a clean bill (exit 0).
     The same journals must also surface both trials' learning curves
     through ``obs curves --json`` — the quiet half of the plane.

  2. **Injected run** — same process, reset stores, chaos plane now
     corrupting one mid-epoch step's gradients to NaN in the first
     trial (``train.nan``, ``times=1``): that trial must land ERRORED with a
     ``diverged:`` diagnosis while the second trial completes and
     scores (containment); the journal must carry the
     ``health/divergence`` verdict AND its ``health/capsule`` pointer;
     and the capsule must re-execute **bit-exactly** through the real
     ``python -m rafiki_tpu.obs replay`` CLI in a fresh process — the
     deterministic-replay contract, enforced end to end.

Output: one JSON object on stdout. Exit code: 0 when every assertion
holds; 1 otherwise — this is a CI gate (scripts/check_tier1.sh).
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TRAIN = "synthetic://images?classes=4&n=128&w=8&h=8&c=1&seed=0"
VAL = "synthetic://images?classes=4&n=64&w=8&h=8&c=1&seed=1"
NAN_SPEC = "seed=3;train.nan:nan:times=1"


def _run(cmd, timeout=300):
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=timeout)


class _ScriptedAdvisor:
    """Fixed knobs: both phases train the identical program, so the
    quiet phase doubles as the no-false-positive control for the
    injected phase's detection."""

    def __init__(self):
        self.fed = []

    def propose(self):
        return dict(hidden_layers=1, hidden_units=32, learning_rate=1e-3,
                    batch_size=32, epochs=2, seed=0)

    def propose_batch(self, n):
        return [self.propose() for _ in range(n)]

    def feedback(self, score, knobs):
        self.fed.append(round(float(score), 6))


def _fresh_stores(log_dir):
    """Point the journal at a fresh dir and zero every in-process
    accumulator the two phases must not share."""
    from rafiki_tpu import telemetry
    from rafiki_tpu.obs import health
    from rafiki_tpu.obs.journal import journal
    from rafiki_tpu.obs.ledger import ledger

    os.environ["RAFIKI_LOG_DIR"] = log_dir
    journal.configure(log_dir, role="healthsmoke")
    telemetry.reset()
    ledger.reset()
    health.reset_stats()


def run_serial_round(n_trials):
    """One serial TrainWorker round; returns the final trial rows and
    the advisor's feedback log."""
    from rafiki_tpu.models.ff import FeedForward
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import TrainWorker

    with tempfile.TemporaryDirectory(prefix="rafiki-healthsmoke-db-") as tmp:
        store = MetaStore(os.path.join(tmp, "meta.sqlite3"))
        params = ParamsStore(os.path.join(tmp, "params"))
        model = store.create_model("healthff", "IMAGE_CLASSIFICATION", None,
                                   b"", "FeedForward")
        job = store.create_train_job("healthsmoke", "IMAGE_CLASSIFICATION",
                                     None, TRAIN, VAL,
                                     {"MODEL_TRIAL_COUNT": n_trials})
        sub = store.create_sub_train_job(job["id"], model["id"])
        adv = _ScriptedAdvisor()
        worker = TrainWorker(store, params, sub["id"], FeedForward, adv,
                             TRAIN, VAL, {"MODEL_TRIAL_COUNT": n_trials},
                             async_persist=False)
        n = worker.run()
        return n, store.get_trials_of_sub_train_job(sub["id"]), adv.fed


def _health_cli(log_dir):
    proc = _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", log_dir,
                 "--json", "health"])
    if proc.returncode != 0:
        raise RuntimeError(f"obs health exited {proc.returncode}: "
                           f"{proc.stderr.strip()[:200]}")
    return json.loads(proc.stdout)


def check_quiet(problems, quiet_dir):
    """Phase 1: the sentinel must not cry wolf on a clean run — and the
    journals it leaves must still surface the learning curves."""
    from rafiki_tpu.obs import health
    from rafiki_tpu.obs.journal import journal

    n, trials, _fed = run_serial_round(2)
    if n != 2:
        problems.append(f"quiet round ran {n}/2 trials")
    bad = [t for t in trials if t["status"] != "COMPLETED"]
    if bad:
        problems.append(f"quiet run left non-COMPLETED trials: "
                        f"{[(t['status'], t['error']) for t in bad][:2]}")
    stats = health.stats()
    if stats["divergences"] or stats["capsules"]:
        problems.append(f"uninjected run tripped the detector: {stats}")
    caps = glob.glob(os.path.join(quiet_dir, "capsule-*.rcap"))
    if caps:
        problems.append(f"uninjected run dumped {len(caps)} capsules")
    journal.close()  # flush before subprocess readers
    try:
        report = _health_cli(quiet_dir)
        if report["divergences"] or report["capsule_errors"]:
            problems.append(f"obs health on quiet dir not clean: "
                            f"{str(report)[:200]}")
    except (RuntimeError, ValueError) as e:
        problems.append(f"obs health failed on quiet dir: {e}")
    curves = {}
    proc = _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", quiet_dir,
                 "--json", "curves"])
    if proc.returncode != 0:
        problems.append(f"obs curves exited {proc.returncode} on quiet dir")
    else:
        curves = json.loads(proc.stdout)["trials"]
        if len(curves) != 2 or any(len(v) < 2 for v in curves.values()):
            problems.append(f"obs curves surfaced "
                            f"{ {k: len(v) for k, v in curves.items()} }, "
                            "expected 2 trials x >=2 epochs")
    return {"trials": n, "stats": stats, "curve_trials": len(curves)}


def check_injected(problems, injected_dir):
    """Phase 2: injected NaN -> contained trial -> capsule -> the real
    replay CLI reproduces the divergent step bit-exactly."""
    from rafiki_tpu import chaos
    from rafiki_tpu.obs import health
    from rafiki_tpu.obs.journal import journal, read_dir

    os.environ["RAFIKI_CHAOS"] = NAN_SPEC
    try:
        chaos.reset_from_env()
        n, trials, fed = run_serial_round(2)
    finally:
        os.environ.pop("RAFIKI_CHAOS", None)
        chaos.reset_from_env()
    if n != 2:
        problems.append(f"injected round ran {n}/2 trials")
    statuses = sorted(t["status"] for t in trials)
    if statuses != ["COMPLETED", "ERRORED"]:
        problems.append(f"injected run statuses {statuses}, expected "
                        "one contained ERRORED + one COMPLETED survivor")
    else:
        sick = next(t for t in trials if t["status"] == "ERRORED")
        if "diverged" not in (sick["error"] or ""):
            problems.append(f"errored trial lacks diverged diagnosis: "
                            f"{sick['error']!r}")
        good = next(t for t in trials if t["status"] == "COMPLETED")
        if good["score"] is None:
            problems.append("surviving trial completed without a score")
        if 0.0 not in fed:
            problems.append("diverged trial never fed the floor score "
                            "back to the advisor")
    stats = health.stats()
    if stats["divergences"] != 1 or stats["contained"] != 1:
        problems.append(f"injected stats off: {stats}")
    recs = [r for r in read_dir(injected_dir) if r.get("kind") == "health"]
    names = {r.get("name") for r in recs}
    if "divergence" not in names or "capsule" not in names:
        problems.append(f"journal missing health records, saw {sorted(names)}")
    caps = sorted(glob.glob(os.path.join(injected_dir, "capsule-*.rcap")))
    journal.close()
    replay = {}
    if not caps:
        problems.append("injected divergence dumped no capsule")
    else:
        # The contract, end to end: a FRESH process re-executes the
        # capsule through the operator CLI and bit-verifies it.
        proc = _run([sys.executable, "-m", "rafiki_tpu.obs", "--json",
                     "replay", caps[-1]])
        try:
            replay = json.loads(proc.stdout or "{}")
        except ValueError:
            replay = {}
        if proc.returncode != 0:
            problems.append(f"obs replay exited {proc.returncode}: "
                            f"{(replay.get('mismatches') or proc.stderr.strip())!s:.200}")
        elif not replay.get("reproduced") or not replay.get("poisoned"):
            problems.append(f"replay did not reproduce the poisoned step: "
                            f"{str(replay)[:200]}")
    return {"trials": n, "stats": stats, "capsules": len(caps),
            "replay_reproduced": bool(replay.get("reproduced"))}


def main() -> int:
    os.environ.pop("RAFIKI_CHAOS", None)  # phase 1 must be uninjected

    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    from rafiki_tpu import chaos

    chaos.reset_from_env()
    t0 = time.monotonic()
    problems = []
    with tempfile.TemporaryDirectory(prefix="rafiki-healthsmoke-") as tmp:
        quiet_dir = os.path.join(tmp, "quiet")
        _fresh_stores(quiet_dir)
        quiet = check_quiet(problems, quiet_dir)

        injected_dir = os.path.join(tmp, "injected")
        _fresh_stores(injected_dir)
        injected = check_injected(problems, injected_dir)

        os.environ.pop("RAFIKI_LOG_DIR", None)
        out = {
            "quiet": quiet,
            "injected": injected,
            # lint: disable=RF007 — smoke artifact wall-clock
            "wall_s": round(time.monotonic() - t0, 3),
        }
        if problems:
            out["problems"] = problems
        print(json.dumps(out))
        return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
