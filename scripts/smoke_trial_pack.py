#!/usr/bin/env python
"""Trial-packing CI smoke: one packed worker round, end to end.

Runs a TrainWorker with ``RAFIKI_TRIAL_PACK`` (default 4) over a
fixed-shape FF template on synthetic data and asserts the PER-TRIAL
contract the packed path must preserve (docs/trial_packing.md): one
COMPLETED store row per trial with a score and persisted params, one
TrialLog stream per trial, advisor feedback per trial, and the
``trial_pack.*`` / ``worker.packed_*`` telemetry.

Output: one JSON object on stdout, e.g.

  {"trials": 4, "pack": 4, "packed_rounds": 1.0, "packed_trials": 4.0,
   "scores": [...], "wall_s": ...}

Exit code: 0 when every assertion holds; 1 otherwise — this is a CI
gate (scripts/check_tier1.sh), not just a number printer.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

MODEL_SRC = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.ff import _Mlp

class PackFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
            "batch_size": FixedKnob(64),
            "epochs": FixedKnob(2),
            "seed": FixedKnob(0),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1, hidden_units=64, num_classes=num_classes)
"""

TRAIN = "synthetic://images?classes=4&n=512&w=8&h=8&c=1&seed=0"
VAL = "synthetic://images?classes=4&n=128&w=8&h=8&c=1&seed=1"


def main() -> int:
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    from rafiki_tpu import telemetry
    from rafiki_tpu.advisor import AdvisorService
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import InProcAdvisorHandle, TrainWorker

    # Export the smoke's wider default instead of reading with a
    # different fallback than the library (RF016): every reader in
    # this process (and any child) now agrees on the width.
    os.environ.setdefault("RAFIKI_TRIAL_PACK", "4")
    pack = max(2, int(os.environ["RAFIKI_TRIAL_PACK"]))
    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="rafiki-packsmoke-") as tmp:
        store = MetaStore(os.path.join(tmp, "meta.sqlite3"))
        params = ParamsStore(os.path.join(tmp, "params"))
        cls = load_model_class(MODEL_SRC, "PackFF")
        model = store.create_model("packff", "IMAGE_CLASSIFICATION", None,
                                   MODEL_SRC, "PackFF")
        job = store.create_train_job("packsmoke", "IMAGE_CLASSIFICATION", None,
                                     TRAIN, VAL, {"MODEL_TRIAL_COUNT": pack})
        sub = store.create_sub_train_job(job["id"], model["id"])
        advisors = AdvisorService()
        aid = advisors.create_advisor(cls.get_knob_config(), kind="random")
        worker = TrainWorker(store, params, sub["id"], cls,
                             InProcAdvisorHandle(advisors, aid),
                             TRAIN, VAL, {"MODEL_TRIAL_COUNT": pack},
                             async_persist=False, trial_pack=pack)
        n = worker.run()

        trials = store.get_trials_of_sub_train_job(sub["id"])
        snap = telemetry.snapshot()
        counters = snap["counters"]
        problems = []
        if n != pack:
            problems.append(f"ran {n} trials, expected {pack}")
        if len(trials) != pack:
            problems.append(f"{len(trials)} store rows, expected {pack}")
        for t in trials:
            if t["status"] != "COMPLETED":
                problems.append(f"trial {t['id']}: status {t['status']}")
            if t["score"] is None or not t["params_id"]:
                problems.append(f"trial {t['id']}: missing score/params")
            elif not (0.0 <= float(t["score"]) <= 1.0):
                problems.append(f"trial {t['id']}: score {t['score']} out of range")
            logs = store.get_trial_logs(t["id"])
            if sum(e.get("type") == "values" for e in logs) < 1:
                problems.append(f"trial {t['id']}: no TrialLog values entries")
        if counters.get("worker.packed_rounds", 0.0) < 1.0:
            problems.append("worker.packed_rounds counter never incremented "
                            "(the packed path did not run)")
        if counters.get("worker.packed_trials", 0.0) < pack:
            problems.append("worker.packed_trials below pack size")
        if "trial_pack.size" not in snap["histograms"]:
            problems.append("trial_pack.size histogram missing")

        out = {
            "trials": len(trials),
            "pack": pack,
            "packed_rounds": counters.get("worker.packed_rounds", 0.0),
            "packed_trials": counters.get("worker.packed_trials", 0.0),
            "scores": [round(float(t["score"]), 4) for t in trials
                       if t["score"] is not None],
            # lint: disable=RF007 — smoke artifact wall-clock
            "wall_s": round(time.monotonic() - t0, 3),
        }
        if problems:
            out["problems"] = problems
        print(json.dumps(out))
        return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
