#!/usr/bin/env python
"""Tenancy CI smoke: isolation + co-hosting, both polarities
(docs/multitenancy.md).

Three legs, all journal-evidenced:

  * **Co-hosting**: ONE InferenceWorker serves TWO distinct models
    (jobA/jobB) behind a ProgramHost whose ResidencyManager budget fits
    only one — every cross-program query forces an LRU swap, and the
    swaps must appear in the ``tenancy/residency`` journal. This is the
    acceptance criterion "one worker process demonstrably serves >= 2
    distinct models with an LRU residency swap journaled under an HBM
    budget", at CPU size.
  * **Isolation holds**: the ``noisy-neighbor-shed`` chaos scenario
    must PASS — weighted admission + per-tenant quotas keep the gold
    victim's p99 inside budget while the flooding batch aggressor sheds
    ``tenant_quota``.
  * **Doctored polarity**: the SAME scenario under
    ``RAFIKI_TENANT_UNWEIGHTED=1`` (quota off, arbitration degraded to
    global FIFO — the pre-tenancy gateway) must FAIL, and must fail
    the ``victim_p99_within_budget`` check specifically: a gate that
    cannot catch unfair admission is not a gate.

The chaos CLI exits 0 even on scenario FAIL (it is a reporter); this
smoke therefore drives the runner's Python API and reads the per-check
verdicts off the ScenarioReport, never the exit code.

Output: one JSON object on stdout; exit 0 only when every leg holds —
this is a CI gate (scripts/check_tier1.sh), not just a number printer.
~20s (the doctored leg is slow BY DESIGN: the victim really does queue
behind the whole flood).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time
from pathlib import Path

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCENARIO = "noisy-neighbor-shed"
UNWEIGHTED_VAR = "RAFIKI_TENANT_UNWEIGHTED"
P99_CHECK = "victim_p99_within_budget"


class _TagModel:
    """Distinct, recognizable models: program 'A' answers 'A:<q>'."""

    def __init__(self, tag: str):
        self.tag = tag

    def predict(self, queries):
        return [f"{self.tag}:{q}" for q in queries]


def _cohost_leg(checks: list) -> None:
    """One worker, two models, a budget that fits only one."""
    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.obs.journal import journal
    from rafiki_tpu.predictor.predictor import Predictor
    from rafiki_tpu.tenancy.hosting import ProgramHost, ProgramSpec
    from rafiki_tpu.tenancy.residency import ResidencyManager
    from rafiki_tpu.worker.inference import InferenceWorker

    with tempfile.TemporaryDirectory(prefix="tenancy-smoke-") as td:
        log_dir = Path(td) / "obs"
        journal.configure(log_dir, role="smoke")
        try:
            # 100-byte budget vs two 80-byte programs: every program
            # switch MUST evict the other — the LRU swap is forced,
            # not incidental.
            residency = ResidencyManager(budget_bytes=100)
            host = ProgramHost(
                [ProgramSpec("jobA", lambda: _TagModel("A"), 80),
                 ProgramSpec("jobB", lambda: _TagModel("B"), 80)],
                residency=residency)
            bus = InProcBus()
            stop = threading.Event()
            worker = InferenceWorker(bus, "jobA", "w0", host,
                                     stop_event=stop,
                                     extra_job_ids=["jobB"])
            th = threading.Thread(target=worker.run, daemon=True)
            th.start()
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline and (
                    "w0" not in bus.get_workers("jobA")
                    or "w0" not in bus.get_workers("jobB")):
                time.sleep(0.01)
            checks.append({
                "name": "one_worker_registered_under_both_jobs",
                "ok": (bus.get_workers("jobA") == ["w0"]
                       and bus.get_workers("jobB") == ["w0"]),
                "detail": f"jobA={bus.get_workers('jobA')} "
                          f"jobB={bus.get_workers('jobB')}"})
            pa = Predictor(bus, "jobA", timeout_s=5.0, program="jobA")
            pb = Predictor(bus, "jobB", timeout_s=5.0, program="jobB")
            answers = [pa.predict(["x"])[0], pb.predict(["y"])[0],
                       pa.predict(["z"])[0]]
            stop.set()
            th.join(timeout=5)
            host.destroy()
            checks.append({
                "name": "both_models_served_through_one_worker",
                "ok": answers == ["A:x", "B:y", "A:z"],
                "detail": f"answers={answers}"})
            recs = journal_mod.read_dir(log_dir)
            events = [r.get("event") for r in recs
                      if r.get("kind") == "tenancy"
                      and r.get("name") == "residency"]
            checks.append({
                "name": "lru_swap_journaled",
                "ok": events.count("activate") >= 3
                and events.count("evict") >= 2,
                "detail": f"residency events={events}"})
            over = [r for r in recs if r.get("kind") == "tenancy"
                    and r.get("name") == "residency"
                    and r.get("used_bytes", 0) > 100]
            checks.append({
                "name": "hbm_budget_never_exceeded",
                "ok": not over,
                "detail": f"{len(over)} records over the 100B budget"})
        finally:
            journal.close()


def _scenario_leg(checks: list, doctored: bool) -> dict:
    from rafiki_tpu.chaos.runner import format_report, run_scenario

    saved = os.environ.get(UNWEIGHTED_VAR)
    if doctored:
        os.environ[UNWEIGHTED_VAR] = "1"
    else:
        os.environ.pop(UNWEIGHTED_VAR, None)
    try:
        report = run_scenario(SCENARIO)
    finally:
        if saved is None:
            os.environ.pop(UNWEIGHTED_VAR, None)
        else:
            os.environ[UNWEIGHTED_VAR] = saved
    p99 = next((c for c in report.checks if c.name == P99_CHECK), None)
    if doctored:
        # The doctored gate is SPECIFIC: unweighted admission must be
        # caught by the victim-p99 check, not by some incidental error.
        checks.append({
            "name": "doctored_unweighted_fails_victim_p99_gate",
            "ok": (not report.passed and report.error is None
                   and p99 is not None and not p99.ok),
            "detail": (p99.detail if p99 is not None
                       else "victim_p99 check missing")})
    else:
        checks.append({
            "name": "weighted_isolation_scenario_passes",
            "ok": report.passed,
            "detail": "" if report.passed else format_report(report)})
    return report.to_dict()


def main() -> int:
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    t0 = time.monotonic()
    checks: list = []
    _cohost_leg(checks)
    weighted = _scenario_leg(checks, doctored=False)
    doctored = _scenario_leg(checks, doctored=True)
    out = {
        "checks": checks,
        "passed": sum(1 for c in checks if c["ok"]),
        "failed": sum(1 for c in checks if not c["ok"]),
        # lint: disable=RF007 — smoke artifact wall-clock
        "wall_s": round(time.monotonic() - t0, 2),
        "weighted_report": weighted,
        "doctored_report": doctored,
    }
    print(json.dumps(out, indent=2))
    for c in checks:
        if not c["ok"]:
            print(f"FAIL {c['name']}: {c['detail']}", file=sys.stderr)
    return 1 if out["failed"] else 0


if __name__ == "__main__":
    sys.exit(main())
