#!/usr/bin/env python
"""Mesh-sweep CI smoke: a 2-virtual-chip elastic sweep with one
injected chip loss (docs/mesh_sweep.md).

Runs the ``mesh-chip-loss-repack`` chaos scenario end to end: a
MeshSweepScheduler sweep (k=2 packed trials per chip x 2 chips, one
``propose_batch(4)`` draft) has chip 1 preempted mid-pack via the
``scheduler.preempt`` fault site. The gate holds iff

  * every trial completes with a recorded score (no lost/duplicated
    rows after re-packing onto the survivor);
  * the loss and re-pack are journaled (``mesh/chip_lost``,
    ``mesh/repack``) and downtime is charged to the goodput ledger;
  * resumed trials' final params bit-match unfaulted serial runs;
  * the preempt fault ACTUALLY fired — a vacuous pass (nothing
    injected, nothing recovered) fails the gate.

Output: one JSON object on stdout. Exit code: 0 iff the gate holds —
this is a CI gate (scripts/check_tier1.sh), not just a number printer.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCENARIO = "mesh-chip-loss-repack"


def main() -> int:
    # Platform pin BEFORE jax loads; then fake a multi-chip pod on the
    # host platform (same 8-virtual-device shape as the test suite).
    from rafiki_tpu.utils.backend import (ensure_host_device_count,
                                          honor_env_platform)

    honor_env_platform()
    ensure_host_device_count(8)

    from rafiki_tpu.chaos.runner import format_report, run_scenario

    t0 = time.monotonic()
    report = run_scenario(SCENARIO)
    injected = [s for s in report.schedule if s[0] == "scheduler.preempt"]
    out = {
        "scenario": SCENARIO,
        "passed": report.passed,
        "chip_loss_injected": len(injected),
        # lint: disable=RF007 — smoke artifact wall-clock
        "wall_s": round(time.monotonic() - t0, 2),
        "report": report.to_dict(),
    }
    problems = []
    if not report.passed:
        problems.append("scenario invariants violated")
    if not injected:
        problems.append("no scheduler.preempt fault fired (vacuous pass)")
    if problems:
        out["problems"] = problems
    print(json.dumps(out, indent=2))
    if problems:
        print(format_report(report), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
