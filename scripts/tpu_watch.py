"""Opportunistic TPU evidence capture (round-5 directive 1).

The TPU tunnel on this machine is flaky: the driver's bench window hit
it down in rounds 3 and 4, and nothing in-repo recorded whether it was
ever up during the builder's session. This watcher makes hardware
evidence capture durable:

  * probes the backend in a throwaway subprocess (jax backend init has
    no timeout and hangs when the tunnel is down) on a loop;
  * appends EVERY attempt to TUNNEL_LOG.jsonl — committed, so a
    down-all-session outage is provable, not just claimed;
  * the FIRST time the probe is green, runs the full bench
    (compile-inclusive) -> BENCH_SELF_r05.json, then the canonical-task
    calibration -> CALIBRATION_TPU.json, commits all three artifacts
    with `git commit -- <paths>` (leaves unrelated staged work alone),
    and exits 0.

Run: python scripts/tpu_watch.py   (backgrounded; exits only on green
capture, so a nonzero-uptime session always ends with committed
hardware numbers and a zero-uptime session ends with a committed probe
log proving it).
"""
from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.path.join(REPO, "TUNNEL_LOG.jsonl")
BENCH_OUT = os.path.join(REPO, "BENCH_SELF_r05.json")
CAL_OUT = os.path.join(REPO, "CALIBRATION_TPU.json")
PROBE_CODE = "import jax; d = jax.devices(); print(d[0].platform, len(d))"
PROBE_TIMEOUT_S = 90
SLEEP_S = 540  # ~9 min between probes; ~10.5 min cycle when down


def _log(rec: dict) -> None:
    rec = {"iso": datetime.datetime.now(datetime.timezone.utc)
           .isoformat(timespec="seconds"), **rec}
    with open(LOG, "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def probe() -> tuple[bool, str]:
    try:
        r = subprocess.run([sys.executable, "-c", PROBE_CODE],
                           capture_output=True, text=True,
                           timeout=PROBE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return False, f"probe timed out after {PROBE_TIMEOUT_S}s (tunnel down)"
    if r.returncode != 0:
        return False, f"rc={r.returncode}: {r.stderr.strip()[-300:]}"
    return True, r.stdout.strip()


def _run(label: str, cmd: list[str], timeout_s: float) -> tuple[int, str, str]:
    t0 = time.monotonic()
    try:
        r = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=timeout_s, cwd=REPO)
        rc, out, err = r.returncode, r.stdout, r.stderr
    except subprocess.TimeoutExpired as ex:
        rc = -9
        out = (ex.stdout or b"").decode("utf-8", "replace") \
            if isinstance(ex.stdout, bytes) else (ex.stdout or "")
        err = f"timed out after {timeout_s:.0f}s"
    _log({"event": label, "rc": rc,
          "wall_s": round(time.monotonic() - t0, 1),
          "stderr_tail": err.strip()[-300:]})
    return rc, out, err


def capture() -> bool:
    """Green window: bench first (the headline artifact), calibration
    second (tunnel may drop mid-window), then commit what we got.

    The bench output only counts as a headline when rc==0 AND its last
    line parses as headline JSON — a crashed/killed bench whose stdout
    happens to contain a '{' line must not be committed as evidence.
    On an unusable run the watcher logs it and keeps probing (returns
    False) instead of dying on a JSONDecodeError."""
    rc, out, _ = _run("bench", [sys.executable, "bench.py"], timeout_s=2100)
    got_bench = False
    lines = out.strip().splitlines()
    headline = None
    if rc == 0 and lines:
        try:
            headline = json.loads(lines[-1])
        except (json.JSONDecodeError, ValueError):
            headline = None
    if isinstance(headline, dict):
        headline["rc"] = rc  # provenance: the exit code travels with the artifact
        with open(BENCH_OUT, "w") as f:
            f.write(json.dumps(headline) + "\n")
        got_bench = True
        _log({"event": "bench_saved", "rc": rc,
              "headline": headline.get("value")})
    else:
        _log({"event": "bench_unusable", "rc": rc,
              "tail": lines[-1][-200:] if lines else ""})

    rc2, out2, _ = _run("calibration",
                        [sys.executable, "scripts/calibrate_bench_task.py",
                         "--canonical"], timeout_s=3000)
    got_cal = False
    if rc2 == 0 and out2.strip():
        with open(CAL_OUT, "w") as f:
            f.write(out2)
        got_cal = True

    paths = [LOG] + ([BENCH_OUT] if got_bench else []) \
        + ([CAL_OUT] if got_cal else [])
    subprocess.run(["git", "add"] + paths, cwd=REPO)
    subprocess.run(["git", "commit", "-m",
                    "Self-captured TPU evidence: bench%s + tunnel log"
                    % (" + calibration" if got_cal else ""),
                    "--"] + paths, cwd=REPO)
    _log({"event": "committed", "bench": got_bench, "calibration": got_cal})
    return got_bench


def main() -> None:
    n = 0
    while True:
        n += 1
        up, msg = probe()
        _log({"event": "probe", "n": n, "up": up, "msg": msg})
        if up and capture():
            return
        time.sleep(SLEEP_S)


if __name__ == "__main__":
    main()
