#!/usr/bin/env python
"""Digital-twin CI smoke: calibrate, validate both polarities, sweep
deterministically, gate the TWIN_r* trend both ways (docs/twin.md).

Five phases, real subprocesses throughout:

  1. **Capture** — ``bench_serving --smoke --service-ms 20`` with a
     fresh ``RAFIKI_LOG_DIR``. The 20ms forward dominates the ~ms
     wiring overheads, so the mis-calibration polarity below produces
     a ~50% latency error instead of drowning in noise.
  2. **Calibrate, both polarities** — ``twin_calibrate`` must write a
     versioned bundle from the captured journals (exit 0), and must
     exit 2 on an empty dir, naming BOTH missing record kinds
     (serving/hops, gateway/config) in one message.
  3. **Validate, both polarities** — ``obs twin validate`` replaying
     the captured run must land predicted-vs-measured p50/p99 inside
     tolerance (exit 0); with ``--scale forward=0.5`` the same gate
     must FAIL (exit 1) — a twin that cannot detect a halved forward
     time validates nothing.
  4. **Deterministic sweep** — ``obs twin sweep`` over a worker grid,
     run twice with one seed, must emit byte-identical JSON, each row
     must name its first-saturating resource, and ``--suggest-slo``
     must emit a 2-spec auto-tuned RAFIKI_SLO set that round-trips
     through the live burn-rate engine's own parser.
  5. **Report gate, both polarities** — ``bench_report --twin`` over
     synthetic TWIN_r*.json rounds: an improving error trend exits 0,
     a regressed round (calibration drift) exits 1, and an
     error-payload round reads as no-data, not a perfect score.

Output: one JSON object on stdout. Exit 0 when every assertion holds;
1 otherwise — this is a CI gate (scripts/check_tier1.sh).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEED = "7"


def _run(cmd, env=None, timeout=300):
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.run(cmd, capture_output=True, text=True,
                          env=full_env, timeout=timeout, cwd=REPO)


def _twin(log_dir, *verb_args):
    return _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", log_dir,
                 "--json", "twin", *verb_args])


def phase_capture(results):
    log_dir = tempfile.mkdtemp(prefix="twin_smoke_")
    r = _run([sys.executable, "scripts/bench_serving.py", "--smoke",
              "--service-ms", "20"], env={"RAFIKI_LOG_DIR": log_dir})
    try:
        report = json.loads(r.stdout)
    except ValueError:
        report = {"unparseable_stdout": r.stdout[-400:]}
    ph = {"bench_rc": r.returncode,
          "qps": report.get("qps"), "p50_ms": report.get("p50_ms"),
          "ok": r.returncode == 0 and bool(report.get("qps"))}
    if not ph["ok"]:
        ph["bench_stderr"] = r.stderr[-400:]
    results["capture"] = ph
    return log_dir if ph["ok"] else None


def phase_calibrate(results, log_dir):
    bundle = os.path.join(tempfile.mkdtemp(prefix="twin_cal_"),
                          "twin_cal.json")
    pos = _run([sys.executable, "scripts/twin_calibrate.py", log_dir,
                "-o", bundle, "--json"])
    empty = tempfile.mkdtemp(prefix="twin_cal_empty_")
    neg = _run([sys.executable, "scripts/twin_calibrate.py", empty,
                "-o", os.path.join(empty, "x.json"), "--json"])
    try:
        neg_doc = json.loads(neg.stdout)
    except ValueError:
        neg_doc = {}
    missing = neg_doc.get("missing") or []
    ph = {
        "calibrate_rc": pos.returncode,
        "bundle_written": os.path.exists(bundle),
        "empty_dir_rc": neg.returncode,
        "empty_dir_missing": missing,
        "ok": (pos.returncode == 0 and os.path.exists(bundle)
               and neg.returncode == 2
               and set(missing) == {"serving/hops", "gateway/config"}),
    }
    if not ph["ok"]:
        ph["calibrate_stderr"] = pos.stderr[-300:]
        ph["empty_stderr"] = neg.stderr[-300:]
    results["calibrate"] = ph
    return bundle if ph["ok"] else None


def phase_validate(results, log_dir, bundle):
    good = _twin(log_dir, "validate", "--seed", SEED)
    bad = _twin(log_dir, "validate", "--seed", SEED,
                "--scale", "forward=0.5")
    try:
        good_doc = json.loads(good.stdout)
    except ValueError:
        good_doc = {}
    try:
        bad_doc = json.loads(bad.stdout)
    except ValueError:
        bad_doc = {}
    ph = {
        "good_rc": good.returncode,
        "good_p50_err": good_doc.get("p50_err"),
        "good_p99_err": good_doc.get("p99_err"),
        "tolerance": good_doc.get("tolerance"),
        "miscal_rc": bad.returncode,
        "miscal_p50_err": bad_doc.get("p50_err"),
        "ok": (good.returncode == 0 and good_doc.get("ok") is True
               and bad.returncode == 1 and bad_doc.get("ok") is False),
    }
    if not ph["ok"]:
        ph["good_stderr"] = good.stderr[-300:]
        ph["miscal_stderr"] = bad.stderr[-300:]
    results["validate"] = ph
    return good_doc if ph["ok"] else None


def _slo_roundtrip(specs):
    """The suggested spec set must survive the live engine's own
    parser: RAFIKI_SLO=json.dumps(specs) -> _specs_from_env -> the
    same names/thresholds. A suggestion the burn-rate engine cannot
    load is a paste-time landmine, not an SLO."""
    from rafiki_tpu.obs.perf.slo import _specs_from_env

    old = os.environ.get("RAFIKI_SLO")
    os.environ["RAFIKI_SLO"] = json.dumps(specs)
    try:
        parsed = _specs_from_env() or []
    finally:
        if old is None:
            os.environ.pop("RAFIKI_SLO", None)
        else:
            os.environ["RAFIKI_SLO"] = old
    return ([(s.name, s.threshold) for s in parsed]
            == [(d["name"], d["threshold"]) for d in specs])


def phase_sweep(results, log_dir):
    args = ("sweep", "--seed", SEED, "--qps", "60", "--duration", "4",
            "--grid", "workers=1,2,4", "--fleet", "--suggest-slo")
    a = _twin(log_dir, *args)
    b = _twin(log_dir, *args)
    try:
        doc = json.loads(a.stdout)
    except ValueError:
        doc = {}
    rows = doc.get("rows") or []
    specs = doc.get("suggested_slo") or []
    ph = {
        "rc": a.returncode,
        "rows": len(rows),
        "deterministic": a.stdout == b.stdout and a.returncode == 0,
        "saturating_named": bool(rows) and all(
            r.get("first_saturating") for r in rows),
        "fleet_workers": (doc.get("fleet") or {}).get("workers"),
        "suggested_slo_specs": len(specs),
        "suggested_slo_parses": bool(specs) and _slo_roundtrip(specs),
        "ok": False,
    }
    ph["ok"] = (ph["rc"] == 0 and ph["rows"] == 3 and ph["deterministic"]
                and ph["saturating_named"]
                and ph["fleet_workers"] is not None
                and ph["suggested_slo_specs"] == 2
                and ph["suggested_slo_parses"])
    if not ph["ok"]:
        ph["stderr"] = a.stderr[-300:]
    results["sweep"] = ph
    return ph["ok"]


def phase_report_gate(results, good_doc):
    """bench_report --twin over synthetic rounds, both polarities.
    Round artifacts reuse the real validate doc with doctored errors
    so the trend exercises the actual artifact schema."""
    td = tempfile.mkdtemp(prefix="twin_rounds_")

    def _round(n, doc):
        path = os.path.join(td, f"TWIN_r{n:02d}.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    base = dict(good_doc)
    improving = [
        _round(1, dict(base, p50_err=0.30, p99_err=0.35)),
        _round(2, dict(base, p50_err=0.12, p99_err=0.15)),
        _round(3, {"error": "no journals captured this round"}),
        _round(4, dict(base, p50_err=0.10, p99_err=0.12)),
    ]
    ok_run = _run([sys.executable, "scripts/bench_report.py", "--twin",
                   *improving])
    regressed = improving + [
        _round(5, dict(base, p50_err=0.55, p99_err=0.60))]
    bad_run = _run([sys.executable, "scripts/bench_report.py", "--twin",
                    *regressed])
    try:
        ok_doc = json.loads(ok_run.stdout)
        bad_doc = json.loads(bad_run.stdout)
    except ValueError:
        ok_doc, bad_doc = {}, {}
    error_round_has_data = any(
        r.get("has_data") for r in ok_doc.get("rounds", [])
        if str(r.get("round", "")).endswith("r03.json"))
    ph = {
        "ok_rc": ok_run.returncode,
        "ok_verdict": ok_doc.get("verdict"),
        "regressed_rc": bad_run.returncode,
        "regressed_metrics": bad_doc.get("regressed"),
        "error_round_counted": error_round_has_data,
        "ok": (ok_run.returncode == 0 and ok_doc.get("verdict") == "ok"
               and bad_run.returncode == 1
               and "p50_err" in (bad_doc.get("regressed") or [])
               and not error_round_has_data),
    }
    if not ph["ok"]:
        ph["ok_stderr"] = ok_run.stderr[-300:]
        ph["regressed_stderr"] = bad_run.stderr[-300:]
    results["report_gate"] = ph
    return ph["ok"]


def main() -> int:
    results = {}
    log_dir = phase_capture(results)
    ok = log_dir is not None
    bundle = good_doc = None
    if ok:
        bundle = phase_calibrate(results, log_dir)
        ok = bundle is not None
    if ok:
        good_doc = phase_validate(results, log_dir, bundle)
        ok = good_doc is not None
    if ok:
        ok = phase_sweep(results, log_dir) and ok
    if ok and good_doc:
        ok = phase_report_gate(results, good_doc) and ok
    results["ok"] = ok
    print(json.dumps(results))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
