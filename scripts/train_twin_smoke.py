#!/usr/bin/env python
"""Train-twin CI smoke: capture a real mesh sweep, calibrate, validate
both polarities, sweep deterministically, gate the TRAINTWIN_r* trend
both ways (docs/twin.md).

Five phases, real subprocesses throughout:

  1. **Capture** — ``train_twin_smoke.py --capture DIR`` in a child
     process: a real ``MeshSweepScheduler.run_sweep`` (2 virtual chips
     x k=2 packed trials, one ``propose_batch(4)`` draft) with
     ``RAFIKI_LOG_DIR`` pointed at a fresh directory, so the sweep
     plane journals ``mesh/pack_formed`` and packing-key-stamped
     ``perf/step`` records — the train twin's two required kinds.
  2. **Calibrate, both polarities** — ``twin_calibrate --train`` must
     write a versioned train bundle from the capture (exit 0), and
     must exit 2 on an empty dir naming BOTH missing record kinds
     (perf/step, mesh/pack_formed) in one message.
  3. **Validate, both polarities** — ``obs twin train validate``
     replaying the captured packs must land predicted-vs-measured
     trials/hour and wall inside tolerance (exit 0); with ``--scale
     step=2.0 --scale compile=2.0`` the same gate must FAIL (exit 1).
     (The mini-sweep's epochs are compile-dominated at CI scale, so
     the doctored polarity scales both epoch segments; the pure 2x
     step-time polarity is pinned by tests/test_train_twin.py on
     synthetic journals where the step cost dominates.)
  4. **Deterministic sweep** — ``obs twin train sweep`` over a
     chips x pack grid, run twice with one seed, must emit
     byte-identical JSON, and every row must carry its event-log
     fingerprint.
  5. **Report gate, both polarities** — ``bench_report --train-twin``
     over synthetic TRAINTWIN_r*.json rounds: an improving error trend
     exits 0, a regressed round (model drift) exits 1, and an
     error-payload round reads as no-data, not a perfect score.

Output: one JSON object on stdout. Exit 0 when every assertion holds;
1 otherwise — this is a CI gate (scripts/check_tier1.sh).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SEED = "7"


def _run(cmd, env=None, timeout=600):
    full_env = dict(os.environ)
    if env:
        full_env.update(env)
    return subprocess.run(cmd, capture_output=True, text=True,
                          env=full_env, timeout=timeout, cwd=REPO)


def _twin(log_dir, *verb_args):
    return _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", log_dir,
                 "--json", "twin", "train", *verb_args])


def capture(log_dir: str) -> int:
    """Child-process mode: run the real mini mesh sweep that journals
    the train twin's calibration kinds under ``log_dir``."""
    from rafiki_tpu.utils.backend import (ensure_host_device_count,
                                          honor_env_platform)

    honor_env_platform()
    ensure_host_device_count(8)

    # Spawned chip workers inherit RAFIKI_LOG_DIR; the scheduler's own
    # mesh/* records ride this process's journal.
    os.environ["RAFIKI_LOG_DIR"] = log_dir
    from rafiki_tpu.obs.journal import journal
    journal.configure(log_dir, role="sweep")

    from rafiki_tpu.chaos.scenarios import FF_SOURCE, TRAIN, VAL
    from rafiki_tpu.scheduler import MeshSweepScheduler
    from rafiki_tpu.store import MetaStore, ParamsStore

    tmp = tempfile.mkdtemp(prefix="train_twin_cap_")
    store = MetaStore(os.path.join(tmp, "meta.sqlite3"))
    params = ParamsStore(os.path.join(tmp, "params"))
    model = store.create_model("twinff", "IMAGE_CLASSIFICATION", None,
                               FF_SOURCE, "ChaosFF")
    job = store.create_train_job("traintwin", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, {"MODEL_TRIAL_COUNT": 4})
    store.create_sub_train_job(job["id"], model["id"])
    result = MeshSweepScheduler(store, params).run_sweep(
        job["id"], chips=2, trials_per_chip=2, advisor_kind="random")
    journal.close()
    print(json.dumps({"status": result.status,
                      "trials": len(result.best_trials or []),
                      "errors": result.errors}))
    return 0 if result.status == "COMPLETED" else 1


def phase_capture(results):
    log_dir = tempfile.mkdtemp(prefix="train_twin_smoke_")
    r = _run([sys.executable, "scripts/train_twin_smoke.py",
              "--capture", log_dir])
    try:
        report = json.loads(r.stdout.splitlines()[-1]) if r.stdout else {}
    except ValueError:
        report = {"unparseable_stdout": r.stdout[-400:]}
    journals = [f for f in os.listdir(log_dir)
                if f.startswith("journal-")] if os.path.isdir(log_dir) else []
    ph = {"capture_rc": r.returncode,
          "status": report.get("status"),
          "journal_files": len(journals),
          "ok": (r.returncode == 0 and report.get("status") == "COMPLETED"
                 and bool(journals))}
    if not ph["ok"]:
        ph["capture_stderr"] = r.stderr[-400:]
    results["capture"] = ph
    return log_dir if ph["ok"] else None


def phase_calibrate(results, log_dir):
    bundle = os.path.join(tempfile.mkdtemp(prefix="train_twin_cal_"),
                          "train_twin_cal.json")
    pos = _run([sys.executable, "scripts/twin_calibrate.py", "--train",
                log_dir, "-o", bundle, "--json"])
    empty = tempfile.mkdtemp(prefix="train_twin_cal_empty_")
    neg = _run([sys.executable, "scripts/twin_calibrate.py", "--train",
                empty, "-o", os.path.join(empty, "x.json"), "--json"])
    try:
        pos_doc = json.loads(pos.stdout)
    except ValueError:
        pos_doc = {}
    try:
        neg_doc = json.loads(neg.stdout)
    except ValueError:
        neg_doc = {}
    missing = neg_doc.get("missing") or []
    ph = {
        "calibrate_rc": pos.returncode,
        "bundle_written": os.path.exists(bundle),
        "packing_keys": pos_doc.get("packing_keys"),
        "packs": pos_doc.get("packs"),
        "empty_dir_rc": neg.returncode,
        "empty_dir_missing": missing,
        "ok": (pos.returncode == 0 and os.path.exists(bundle)
               and (pos_doc.get("packs") or 0) >= 2
               and neg.returncode == 2
               and set(missing) == {"perf/step", "mesh/pack_formed"}),
    }
    if not ph["ok"]:
        ph["calibrate_stderr"] = pos.stderr[-300:]
        ph["empty_stderr"] = neg.stderr[-300:]
    results["calibrate"] = ph
    return bundle if ph["ok"] else None


def phase_validate(results, log_dir):
    good = _twin(log_dir, "validate", "--seed", SEED)
    bad = _twin(log_dir, "validate", "--seed", SEED,
                "--scale", "step=2.0", "--scale", "compile=2.0")
    try:
        good_doc = json.loads(good.stdout)
    except ValueError:
        good_doc = {}
    try:
        bad_doc = json.loads(bad.stdout)
    except ValueError:
        bad_doc = {}
    ph = {
        "good_rc": good.returncode,
        "good_tph_err": good_doc.get("tph_err"),
        "good_wall_err": good_doc.get("wall_err"),
        "tolerance": good_doc.get("tolerance"),
        "miscal_rc": bad.returncode,
        "miscal_wall_err": bad_doc.get("wall_err"),
        "ok": (good.returncode == 0 and good_doc.get("ok") is True
               and bad.returncode == 1 and bad_doc.get("ok") is False),
    }
    if not ph["ok"]:
        ph["good_stderr"] = good.stderr[-300:]
        ph["miscal_stderr"] = bad.stderr[-300:]
    results["validate"] = ph
    return good_doc if ph["ok"] else None


def phase_sweep(results, log_dir):
    args = ("sweep", "--seed", SEED, "--grid", "chips=1,2",
            "--grid", "pack=1,2", "--best-k", "--split")
    a = _twin(log_dir, *args)
    b = _twin(log_dir, *args)
    try:
        doc = json.loads(a.stdout)
    except ValueError:
        doc = {}
    rows = doc.get("rows") or []
    ph = {
        "rc": a.returncode,
        "rows": len(rows),
        "deterministic": a.stdout == b.stdout and a.returncode == 0,
        "fingerprinted": bool(rows) and all(
            r.get("event_log_sha1") for r in rows),
        "best_k_keys": len(doc.get("best_k") or {}),
        "split_best": (doc.get("split") or {}).get("best"),
        "ok": False,
    }
    ph["ok"] = (ph["rc"] == 0 and ph["rows"] == 4 and ph["deterministic"]
                and ph["fingerprinted"] and ph["best_k_keys"] >= 1
                and ph["split_best"] is not None)
    if not ph["ok"]:
        ph["stderr"] = a.stderr[-300:]
    results["sweep"] = ph
    return ph["ok"]


def phase_report_gate(results, good_doc):
    """bench_report --train-twin over synthetic rounds, both
    polarities. Round artifacts reuse the real validate doc with
    doctored errors so the trend exercises the actual schema."""
    td = tempfile.mkdtemp(prefix="train_twin_rounds_")

    def _round(n, doc):
        path = os.path.join(td, f"TRAINTWIN_r{n:02d}.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    base = dict(good_doc)
    improving = [
        _round(1, dict(base, tph_err=0.20, wall_err=0.22)),
        _round(2, dict(base, tph_err=0.10, wall_err=0.12)),
        _round(3, {"error": "no sweep captured this round"}),
        _round(4, dict(base, tph_err=0.08, wall_err=0.10)),
    ]
    ok_run = _run([sys.executable, "scripts/bench_report.py",
                   "--train-twin", *improving])
    regressed = improving + [
        _round(5, dict(base, tph_err=0.45, wall_err=0.50))]
    bad_run = _run([sys.executable, "scripts/bench_report.py",
                    "--train-twin", *regressed])
    try:
        ok_doc = json.loads(ok_run.stdout)
        bad_doc = json.loads(bad_run.stdout)
    except ValueError:
        ok_doc, bad_doc = {}, {}
    error_round_has_data = any(
        r.get("has_data") for r in ok_doc.get("rounds", [])
        if str(r.get("round", "")).endswith("r03.json"))
    ph = {
        "ok_rc": ok_run.returncode,
        "ok_verdict": ok_doc.get("verdict"),
        "mode": ok_doc.get("mode"),
        "regressed_rc": bad_run.returncode,
        "regressed_metrics": bad_doc.get("regressed"),
        "error_round_counted": error_round_has_data,
        "ok": (ok_run.returncode == 0 and ok_doc.get("verdict") == "ok"
               and ok_doc.get("mode") == "train-twin"
               and bad_run.returncode == 1
               and "tph_err" in (bad_doc.get("regressed") or [])
               and not error_round_has_data),
    }
    if not ph["ok"]:
        ph["ok_stderr"] = ok_run.stderr[-300:]
        ph["regressed_stderr"] = bad_run.stderr[-300:]
    results["report_gate"] = ph
    return ph["ok"]


def main() -> int:
    p = argparse.ArgumentParser(prog="scripts/train_twin_smoke.py")
    p.add_argument("--capture", metavar="DIR", default=None,
                   help="child mode: run the mini mesh sweep journaling "
                        "into DIR, then exit")
    args = p.parse_args()
    if args.capture:
        return capture(args.capture)

    results = {}
    log_dir = phase_capture(results)
    ok = log_dir is not None
    good_doc = None
    if ok:
        ok = phase_calibrate(results, log_dir) is not None
    if ok:
        good_doc = phase_validate(results, log_dir)
        ok = good_doc is not None
    if ok:
        ok = phase_sweep(results, log_dir) and ok
    if ok and good_doc:
        ok = phase_report_gate(results, good_doc) and ok
    results["ok"] = ok
    print(json.dumps(results))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
