#!/usr/bin/env python
"""Elasticity CI smoke: the closed autoscale loop must work, and a
doctored undamped loop must be CAUGHT flapping (docs/autoscale.md).

Three phases, ~5s total:

  1. **Closed loop** — the ``load-spike-scale-up`` chaos scenario: one
     serving replica pinned 0.3s slow, the burn engine breaches the
     serving-p99 SLO, the controller scales the inference lane up, the
     breach clears. Recovery-time-to-SLO and the actuation count land
     in a SCALE_r artifact for the trend gate.
  2. **Flap, both polarities** — the ``autoscale-flap-damping``
     scenario (damped bounded vs undamped thrashing on a fake clock)
     must pass; then the vacuous-pass rejection: an always-burning
     sensor driven through a controller with damping DISABLED is
     journaled and ``obs autoscale --check`` must exit 1 naming the
     flap, while the identical signal with damping enabled must exit
     0. A checker that cannot catch the doctored loop would pass
     vacuously forever.
  3. **Report gate, both polarities** — ``bench_report --scale`` over
     synthetic SCALE_r*.json rounds seeded from the real phase-1
     artifact (improving trend exits 0, a slow-recovery round exits
     1), and the same both-ways gate for ``--store`` over
     STORE_r*.json rounds.

Output: one JSON object on stdout. Exit 0 when every assertion holds;
1 otherwise — this is a CI gate (scripts/check_tier1.sh).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, timeout=120):
    return subprocess.run(cmd, capture_output=True, text=True,
                          env=dict(os.environ), timeout=timeout, cwd=REPO)


def phase_closed_loop(results):
    """Run the acceptance scenario in-process; harvest the recovery
    gauge the scenario sets (the runner resets telemetry BEFORE the
    body, not after) into a SCALE round artifact."""
    from rafiki_tpu import telemetry
    from rafiki_tpu.chaos.runner import run_scenario

    report = run_scenario("load-spike-scale-up")
    recovery_s = telemetry.get_gauge("autoscale.recovery_s")
    actuations = telemetry.get_counter("autoscale.actuations")
    artifact = {
        "scale_schema_version": 1,
        "scenario": report.name,
        "recovery_s": recovery_s,
        "actuations": actuations,
        "decisions": telemetry.get_counter("autoscale.decisions"),
        "duration_s": round(report.duration_s, 3),
    }
    if not report.passed:
        artifact["error"] = "load-spike-scale-up scenario failed"
    ph = {
        "scenario_passed": report.passed,
        "checks": {c.name: c.ok for c in report.checks},
        "recovery_s": recovery_s,
        "actuations": actuations,
        "ok": (report.passed and recovery_s is not None
               and recovery_s > 0 and actuations >= 1),
    }
    results["closed_loop"] = ph
    return artifact if ph["ok"] else None


def _journaled_flap_run(damping: bool) -> str:
    """Drive an always-oscillating sensor through a controller on a
    fake clock, journaled to a fresh dir — the material `obs autoscale
    --check` gates on. With ``damping=False`` this is the DOCTORED
    loop the checker must catch."""
    from rafiki_tpu.autoscale.controller import AutoscaleController, LaneSpec
    from rafiki_tpu.obs.journal import journal

    log_dir = tempfile.mkdtemp(
        prefix=f"autoscale_smoke_{'damped' if damping else 'undamped'}_")

    class _StubLane:
        def __init__(self):
            self.n = 2

        def size(self):
            return self.n

        def scale_to(self, n):
            self.n = n

    clock = {"t": 0.0}
    phase = {"i": 0}

    def sensors():
        phase["i"] += 1
        high = phase["i"] % 2 == 1
        return {"slo_breaching": ["flap"] if high else [],
                "slo_burn": 2.0 if high else 0.0,
                "queue_frac": 0.0, "shed_rate": 0.0}

    journal.configure(log_dir, role="autoscale-smoke")
    try:
        ctl = AutoscaleController(
            lanes=[LaneSpec("inference", min_size=1, max_size=8,
                            up_threshold=1.0, down_threshold=0.3,
                            up_cooldown_s=1.0, down_cooldown_s=1.0)],
            sensor_fn=sensors,
            actuators={"inference": _StubLane()},
            clock=lambda: clock["t"],
            seed=0, tick_s=2.0, damping=damping,
            flap_window_s=600.0, flap_flips=2, flap_backoff=2.0,
            flap_guard_s=2.0, flap_guard_cap_s=64.0,
            tick_global_slo=False)
        for _ in range(120):
            ctl.tick()
            clock["t"] += 2.0
    finally:
        journal.close()
    return log_dir


def phase_flap(results):
    from rafiki_tpu.chaos.runner import run_scenario

    report = run_scenario("autoscale-flap-damping")
    undamped_dir = _journaled_flap_run(damping=False)
    damped_dir = _journaled_flap_run(damping=True)
    caught = _run([sys.executable, "-m", "rafiki_tpu.obs",
                   "--dir", undamped_dir, "autoscale", "--check"])
    clean = _run([sys.executable, "-m", "rafiki_tpu.obs",
                  "--dir", damped_dir, "autoscale", "--check"])
    ph = {
        "scenario_passed": report.passed,
        "checks": {c.name: c.ok for c in report.checks},
        "undamped_rc": caught.returncode,
        "undamped_caught": "FLAPPING" in caught.stderr,
        "damped_rc": clean.returncode,
        "ok": (report.passed
               and caught.returncode == 1
               and "FLAPPING" in caught.stderr
               and clean.returncode == 0),
    }
    if not ph["ok"]:
        ph["undamped_stderr"] = caught.stderr[-300:]
        ph["damped_stderr"] = clean.stderr[-300:]
    results["flap"] = ph
    return ph["ok"]


def phase_report_gate(results, artifact):
    """bench_report --scale and --store over synthetic rounds, both
    polarities, seeded from real artifacts so the trend exercises the
    actual schemas."""
    td = tempfile.mkdtemp(prefix="scale_rounds_")

    def _round(prefix, n, doc):
        path = os.path.join(td, f"{prefix}_r{n:02d}.json")
        with open(path, "w") as f:
            json.dump(doc, f)
        return path

    improving = [
        _round("SCALE", 1, dict(artifact, recovery_s=2.0, actuations=2)),
        _round("SCALE", 2, dict(artifact, recovery_s=1.5, actuations=2)),
        _round("SCALE", 3, {"scale_schema_version": 1,
                            "error": "scenario failed"}),
        _round("SCALE", 4, dict(artifact, recovery_s=1.2, actuations=1)),
    ]
    ok_run = _run([sys.executable, "scripts/bench_report.py", "--scale",
                   *improving])
    regressed = improving + [
        _round("SCALE", 5, dict(artifact, recovery_s=9.0, actuations=12))]
    bad_run = _run([sys.executable, "scripts/bench_report.py", "--scale",
                    *regressed])

    store_base = {"store_schema_version": 1, "write_txn_per_s": 8000.0,
                  "dedup_ratio": 0.4, "second_write_frac": 0.13,
                  "cas_dump_s": 0.004}
    store_ok = _run([sys.executable, "scripts/bench_report.py", "--store",
                     _round("STORE", 1, store_base),
                     _round("STORE", 2, dict(store_base,
                                             second_write_frac=0.11))])
    store_bad = _run([sys.executable, "scripts/bench_report.py", "--store",
                      _round("STORE", 1, store_base),
                      _round("STORE", 3, dict(store_base,
                                              second_write_frac=0.45,
                                              write_txn_per_s=3000.0))])
    try:
        ok_doc = json.loads(ok_run.stdout)
        bad_doc = json.loads(bad_run.stdout)
        store_bad_doc = json.loads(store_bad.stdout)
    except ValueError:
        ok_doc, bad_doc, store_bad_doc = {}, {}, {}
    error_round_has_data = any(
        r.get("has_data") for r in ok_doc.get("rounds", [])
        if str(r.get("round", "")).endswith("r03.json"))
    ph = {
        "scale_ok_rc": ok_run.returncode,
        "scale_ok_verdict": ok_doc.get("verdict"),
        "scale_regressed_rc": bad_run.returncode,
        "scale_regressed_metrics": bad_doc.get("regressed"),
        "error_round_counted": error_round_has_data,
        "store_ok_rc": store_ok.returncode,
        "store_regressed_rc": store_bad.returncode,
        "store_regressed_metrics": store_bad_doc.get("regressed"),
        "ok": (ok_run.returncode == 0 and ok_doc.get("verdict") == "ok"
               and bad_run.returncode == 1
               and "recovery_s" in (bad_doc.get("regressed") or [])
               and not error_round_has_data
               and store_ok.returncode == 0
               and store_bad.returncode == 1
               and "second_write_frac" in (store_bad_doc.get("regressed")
                                           or [])),
    }
    if not ph["ok"]:
        ph["scale_ok_stderr"] = ok_run.stderr[-300:]
        ph["scale_regressed_stderr"] = bad_run.stderr[-300:]
        ph["store_stderr"] = store_bad.stderr[-300:]
    results["report_gate"] = ph
    return ph["ok"]


def main(argv=None) -> int:
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()  # pin the platform before the scenario pulls
    # in jax: off-TPU the child must not hang in backend init (RF001).
    out = None
    if argv is None:
        argv = sys.argv[1:]
    if argv[:1] == ["--out"]:
        out = argv[1]
    results = {}
    artifact = phase_closed_loop(results)
    ok = artifact is not None
    if ok:
        ok = phase_flap(results) and ok
    if ok:
        ok = phase_report_gate(results, artifact) and ok
    results["ok"] = ok
    if out and artifact is not None:
        with open(out, "w") as f:
            json.dump(artifact, f)
            f.write("\n")
    print(json.dumps(results))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
