#!/usr/bin/env bash
# The tier-1 verify gate, verbatim from ROADMAP.md — builders, the TPU
# watcher and CI must all run the IDENTICAL command so "tests pass"
# means the same thing everywhere. Edit ROADMAP.md and this file
# together or not at all.
#
# Prints DOTS_PASSED=<n> (count of passing-test dots) after the pytest
# summary; exits with pytest's own return code.
set -o pipefail
cd "$(dirname "$0")/.."
# Lint gate first: a static-analysis regression fails the same gate as
# tests (docs/static_analysis.md). Cheap (~1s, no jax touch), so it
# runs before the 870s pytest budget is spent.
scripts/check_lint.sh > /tmp/_lint.json || { echo "TIER1 LINT FAILED (see /tmp/_lint.json)"; exit 1; }
# Serving smoke: a deterministic in-process closed-loop run against the
# gateway + predictor stack (docs/serving.md). Sub-second; fails the
# gate on any 5xx or zero completed requests.
env JAX_PLATFORMS=cpu python scripts/bench_serving.py --smoke > /tmp/_bench_serving.json \
  || { echo "TIER1 SERVING SMOKE FAILED (see /tmp/_bench_serving.json)"; exit 1; }
# Trial-packing smoke: one RAFIKI_TRIAL_PACK=4 worker round over the
# fixed-shape FF template (docs/trial_packing.md) — asserts per-trial
# store rows, logs, feedback and the trial_pack.* telemetry. ~3s.
env JAX_PLATFORMS=cpu RAFIKI_TRIAL_PACK=4 python scripts/smoke_trial_pack.py > /tmp/_smoke_trial_pack.json \
  || { echo "TIER1 TRIAL PACK SMOKE FAILED (see /tmp/_smoke_trial_pack.json)"; exit 1; }
# Chaos smoke: three deterministic fault-injection recovery scenarios
# (docs/chaos.md) — kill-mid-trial resume, straggler quorum, drain
# under load. ~10s; fails the gate on any violated recovery invariant.
env JAX_PLATFORMS=cpu python scripts/chaos_smoke.py > /tmp/_chaos_smoke.json \
  || { echo "TIER1 CHAOS SMOKE FAILED (see /tmp/_chaos_smoke.json)"; exit 1; }
# Observability smoke: one gateway query traced end to end — the
# `obs trace` CLI must stitch >= 3 processes from the journals, and
# /metrics?format=prom must line-parse (docs/observability.md). ~6s.
env JAX_PLATFORMS=cpu python scripts/obs_smoke.py > /tmp/_obs_smoke.json \
  || { echo "TIER1 OBS SMOKE FAILED (see /tmp/_obs_smoke.json)"; exit 1; }
# Perf-sentinel smoke: bench_report must gate both ways on the
# BENCH_r* history, an uninjected packed round must profile clean
# (obs profile reports packed-program MFU, zero anomalies/breaches),
# and an injected 0.25s epoch delay must land anomaly -> SLO breach
# -> flight record (docs/perf.md). ~7s.
env JAX_PLATFORMS=cpu python scripts/perf_smoke.py > /tmp/_perf_smoke.json \
  || { echo "TIER1 PERF SMOKE FAILED (see /tmp/_perf_smoke.json)"; exit 1; }
# Mesh-sweep smoke: a 2-virtual-chip elastic sweep with one injected
# chip loss (docs/mesh_sweep.md) — re-packs onto the survivor, every
# trial scores, resumed params bit-match serial. ~10s; a vacuous pass
# (no fault fired) also fails the gate.
env JAX_PLATFORMS=cpu python scripts/mesh_smoke.py > /tmp/_mesh_smoke.json \
  || { echo "TIER1 MESH SMOKE FAILED (see /tmp/_mesh_smoke.json)"; exit 1; }
# Numerics-health smoke: a quiet 2-trial round must trip nothing,
# then an injected train.nan must land a contained ERRORED trial, a
# health/divergence verdict, a replay capsule — and the real
# `obs replay` CLI must reproduce the divergent step bit-exactly in a
# fresh process (docs/health.md). ~13s.
env JAX_PLATFORMS=cpu python scripts/health_smoke.py > /tmp/_health_smoke.json \
  || { echo "TIER1 HEALTH SMOKE FAILED (see /tmp/_health_smoke.json)"; exit 1; }
# Request-anatomy smoke: a clean mp run must reconstruct a pinned
# >=4-hop waterfall across >=3 pids with hop sums reconciling, and an
# injected inference.forward delay must be attributed to the forward
# hop by `obs tails` AND breach its latency-budget SLO
# (docs/serving_anatomy.md).
env JAX_PLATFORMS=cpu python scripts/serving_obs_smoke.py > /tmp/_serving_obs_smoke.json \
  || { echo "TIER1 SERVING OBS SMOKE FAILED (see /tmp/_serving_obs_smoke.json)"; exit 1; }
# Digital-twin smoke: calibrate from a fresh captured run, validate
# predicted-vs-measured latency BOTH ways (correct calibration passes,
# a halved forward time fails), sweep deterministically from one seed,
# and gate the TWIN_r* error trend both ways (docs/twin.md). ~15s.
env JAX_PLATFORMS=cpu python scripts/twin_smoke.py > /tmp/_twin_smoke.json \
  || { echo "TIER1 TWIN SMOKE FAILED (see /tmp/_twin_smoke.json)"; exit 1; }
# Search-anatomy smoke: a seeded 12-trial GP sweep must reconstruct
# end to end from its journals alone (`obs sweep` — every proposal
# audited, regret non-increasing, lift CI present), a doctored journal
# missing one advisor/propose must fail reconciliation loudly, and
# bench_report --sweep must gate the SWEEP_r* trend both ways
# (docs/search_anatomy.md). ~10s.
env JAX_PLATFORMS=cpu python scripts/sweep_smoke.py > /tmp/_sweep_smoke.json \
  || { echo "TIER1 SWEEP SMOKE FAILED (see /tmp/_sweep_smoke.json)"; exit 1; }
# Elasticity smoke: the load-spike-scale-up chaos scenario must close
# the loop (breach -> scale-up -> recovery, time recorded for the
# SCALE_r* trend), a doctored undamped controller must be CAUGHT
# flapping by `obs autoscale --check`, and bench_report --scale/--store
# must gate both ways (docs/autoscale.md). ~5s.
env JAX_PLATFORMS=cpu python scripts/autoscale_smoke.py > /tmp/_autoscale_smoke.json \
  || { echo "TIER1 AUTOSCALE SMOKE FAILED (see /tmp/_autoscale_smoke.json)"; exit 1; }
# Crash-recovery smoke: a SIGKILLed sweep supervisor must be adopted
# by a fresh process (WAL reconciled with zero duplicate claims, job
# driven to COMPLETED, timeline reconstructible via `obs resume`), a
# doctored WAL must refuse resume loudly, and bench_report --resume
# must gate the RESUME_r* trend both ways (docs/recovery.md). ~15s.
env JAX_PLATFORMS=cpu python scripts/resume_smoke.py > /tmp/_resume_smoke.json \
  || { echo "TIER1 RESUME SMOKE FAILED (see /tmp/_resume_smoke.json)"; exit 1; }
# Train-twin smoke: capture a real seeded mini mesh sweep, calibrate
# the train bundle BOTH ways (real capture passes, an empty dir fails
# naming perf/step + mesh/pack_formed), validate predicted-vs-measured
# trials/hour BOTH ways (correct calibration passes, a doctored epoch
# scale fails), sweep a chips x pack grid byte-identically from one
# seed, and gate the TRAINTWIN_r* error trend both ways
# (docs/twin.md). ~30s.
env JAX_PLATFORMS=cpu python scripts/train_twin_smoke.py > /tmp/_train_twin_smoke.json \
  || { echo "TIER1 TRAIN TWIN SMOKE FAILED (see /tmp/_train_twin_smoke.json)"; exit 1; }
# Tenancy smoke: one worker must serve two distinct models through a
# journaled LRU residency swap under an HBM budget, the
# noisy-neighbor-shed scenario must PASS weighted (victim p99 inside
# its gold budget, aggressor sheds tenant_quota), and the doctored
# RAFIKI_TENANT_UNWEIGHTED=1 polarity must FAIL the victim-p99 gate
# specifically (docs/multitenancy.md). ~20s.
env JAX_PLATFORMS=cpu python scripts/tenancy_smoke.py > /tmp/_tenancy_smoke.json \
  || { echo "TIER1 TENANCY SMOKE FAILED (see /tmp/_tenancy_smoke.json)"; exit 1; }
# Sharded-lane smoke: the chip-loss-mid-sharded-trial scenario must
# PASS with the preempt fault actually fired (width-2 group loses a
# member, resumes at width 1 via reshard-on-restore, final params
# bit-match an unfaulted serial run), AND the doctored wrong-width
# chunk polarity must be REFUSED naming the chunk — a restore that
# silently accepts mismatched slices is the failure the lane exists
# to prevent (docs/sharding.md). ~35s.
env JAX_PLATFORMS=cpu python scripts/shard_smoke.py > /tmp/_shard_smoke.json \
  || { echo "TIER1 SHARD SMOKE FAILED (see /tmp/_shard_smoke.json)"; exit 1; }
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
exit $rc
