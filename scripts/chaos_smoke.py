#!/usr/bin/env python
"""Chaos CI smoke: four recovery scenarios, end to end (docs/chaos.md).

Runs the fast core of the chaos catalog through the scenario runner:

  * ``kill-mid-trial-resume`` — a subprocess worker SIGKILLs itself at
    epoch 1; the respawned worker adopts and resumes from the epoch-1
    checkpoint; no lost/duplicated trial rows;
  * ``straggler-quorum`` — one of three serving replicas stuck 3s per
    forward; quorum gather answers fast, hedging past it;
  * ``drain-under-load`` — gateway drain with injected frontend latency
    holding inflight slots: flushes, then sheds as ``draining``;
  * ``stacked-worker-loss-fallback`` — the stacked serving route's loss
    story: SIGKILL the one worker holding a whole top-k ensemble
    mid-load; the fallback supervisor degrades to replicated workers,
    the gateway's blackout re-route drops zero admitted requests, and
    the loss reconstructs from the journals.

(The full catalog, including the kill-mid-pack acceptance scenario,
runs via ``python -m rafiki_tpu.chaos run all`` and tests/test_chaos.py.)

Output: one JSON object on stdout, e.g.

  {"scenarios": 4, "passed": 4, "injected_faults": 7, "wall_s": ...,
   "reports": [{"name": ..., "passed": true, ...}, ...]}

Exit code: 0 when every scenario's invariants hold; 1 otherwise — this
is a CI gate (scripts/check_tier1.sh), not just a number printer.
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

SCENARIOS = ["kill-mid-trial-resume", "straggler-quorum", "drain-under-load",
             "stacked-worker-loss-fallback"]


def main() -> int:
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    from rafiki_tpu.chaos.runner import format_report, run_scenarios

    t0 = time.monotonic()
    reports = run_scenarios(SCENARIOS)
    # The kill scenario must leave black-box evidence: the scheduler
    # dumps a flight record on the SIGKILLed worker's behalf, and the
    # runner carries it in the report (docs/observability.md). A kill
    # we can't reconstruct afterwards fails the gate even if recovery
    # itself worked.
    kill = next(r for r in reports if r.name == "kill-mid-trial-resume")
    flight_missing = kill.flight_record is None
    out = {
        "scenarios": len(reports),
        "passed": sum(1 for r in reports if r.passed),
        "injected_faults": sum(len(r.schedule) for r in reports),
        # lint: disable=RF007 — smoke artifact wall-clock
        "wall_s": round(time.monotonic() - t0, 2),
        "reports": [r.to_dict() for r in reports],
    }
    if flight_missing:
        out["problems"] = ["kill-mid-trial-resume produced no flight record"]
    print(json.dumps(out, indent=2))
    failed = [r for r in reports if not r.passed]
    for r in failed:
        print(format_report(r), file=sys.stderr)
    return 1 if failed or flight_missing else 0


if __name__ == "__main__":
    sys.exit(main())
