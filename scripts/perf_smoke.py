#!/usr/bin/env python
"""Perf-sentinel CI smoke: the whole detection chain, both polarities.

Three gates in one process (docs/perf.md):

  1. **Bench regression gate** — scripts/bench_report.py over the real
     BENCH_r*.json history must exit 0 (error-bearing rounds are
     no-data, not regressions), and over a doctored two-round fixture
     with a 3x throughput drop must exit nonzero naming the metric.

  2. **Quiet run (no injection)** — a packed TrainWorker round under a
     fresh journal dir: cost capture (``perf/cost``) and step sampling
     (``perf/step``) must appear, the ``obs profile --json`` CLI must
     report achieved FLOP/s + MFU for the *packed* program, and there
     must be ZERO ``perf/anomaly`` records, ZERO ``slo/breach``
     records and ZERO flight recordings — the sentinel must not cry
     wolf on an uninjected run.

  3. **Injected run** — same process, reset stores, chaos plane now
     delaying ``train.epoch`` 0.25s from its 16th hit (a >100x step
     inflation): the anomaly detector must fire (``perf/anomaly`` +
     badput), the burn-rate engine must breach the anomaly-rate SLO
     (``slo/breach``), and the breach must dump a flight record.

``RAFIKI_PERF_K=6`` is pinned for the whole smoke: the injected spike
is ~100x the warm mean, so a wider band costs no sensitivity there
while making the quiet phase's zero-anomaly assertion robust to CPU
scheduler jitter on sub-millisecond steps.

Output: one JSON object on stdout. Exit code: 0 when every assertion
holds; 1 otherwise — this is a CI gate (scripts/check_tier1.sh).
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODEL_SRC = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.ff import _Mlp

class PerfFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
            "batch_size": FixedKnob(64),
            "epochs": FixedKnob(3),
            "seed": FixedKnob(0),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1, hidden_units=64, num_classes=num_classes)
"""

TRAIN = "synthetic://images?classes=4&n=512&w=8&h=8&c=1&seed=0"
VAL = "synthetic://images?classes=4&n=128&w=8&h=8&c=1&seed=1"


def _run(cmd, **kw):
    return subprocess.run(cmd, capture_output=True, text=True,
                          timeout=120, **kw)


def check_bench_gate(problems, tmp):
    """Gate 1: the report must pass real history and fail a doctored
    regression — both directions, via the real CLI."""
    report = os.path.join(REPO, "scripts", "bench_report.py")
    real = _run([sys.executable, report])
    if real.returncode != 0:
        problems.append(f"bench_report on real history exited "
                        f"{real.returncode}: {real.stderr.strip()[:200]}")
    try:
        verdict = json.loads(real.stdout or "{}").get("verdict")
        if real.returncode == 0 and verdict != "ok":
            problems.append(f"bench_report rc 0 but verdict {verdict!r}")
    except ValueError:
        problems.append("bench_report emitted unparseable stdout")

    r1 = {"n": 1, "cmd": "bench", "rc": 0, "tail": [], "parsed": {
        "metric": "m", "value": 1200.0,
        "headline": {"trials_per_hour": 1200.0, "canonical_trial_s": 3.0,
                     "compile_s": 12.0, "train_img_per_s": 45000.0}}}
    r2 = json.loads(json.dumps(r1))
    r2["n"] = 2
    r2["parsed"]["headline"]["trials_per_hour"] = 400.0  # 3x drop
    fix = []
    for doc in (r1, r2):
        p = os.path.join(tmp, f"BENCH_r{doc['n']:02d}.json")
        with open(p, "w") as f:
            json.dump(doc, f)
        fix.append(p)
    doctored = _run([sys.executable, report] + fix)
    if doctored.returncode == 0:
        problems.append("bench_report passed a doctored 3x regression")
    else:
        regressed = json.loads(doctored.stdout or "{}").get("regressed", [])
        if "trials_per_hour" not in regressed:
            problems.append(f"doctored regression blamed {regressed}, "
                            "expected trials_per_hour")
    return {"real_rc": real.returncode, "doctored_rc": doctored.returncode}


def _read_perf(log_dir):
    from rafiki_tpu.obs.journal import read_dir

    recs = read_dir(log_dir)
    return {
        "costs": [r for r in recs
                  if r["kind"] == "perf" and r["name"] == "cost"],
        "steps": [r for r in recs
                  if r["kind"] == "perf" and r["name"] == "step"],
        "anomalies": [r for r in recs
                      if r["kind"] == "perf" and r["name"] == "anomaly"],
        "breaches": [r for r in recs
                     if r["kind"] == "slo" and r["name"] == "breach"],
        "flights": glob.glob(os.path.join(log_dir, "flight-*.json")),
    }


def _fresh_stores(log_dir, tick_s):
    """Point the journal at a fresh dir and zero every in-process
    accumulator the two phases must not share."""
    from rafiki_tpu import telemetry
    from rafiki_tpu.obs.journal import journal
    from rafiki_tpu.obs.perf import profiler, slo

    os.environ["RAFIKI_LOG_DIR"] = log_dir
    journal.configure(log_dir, role="perfsmoke")
    telemetry.reset()
    profiler.reset()
    slo.configure([slo.SloSpec(name="step_anomaly_rate",
                               source="counter:perf.anomalies",
                               threshold=0.0, windows=(0.4, 1.2))],
                  tick_s=tick_s)


def run_packed_round(pack):
    """One packed TrainWorker round — the program whose MFU the CLI
    must report (obs profile joins its perf/cost x perf/step)."""
    from rafiki_tpu.advisor import AdvisorService
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import InProcAdvisorHandle, TrainWorker

    with tempfile.TemporaryDirectory(prefix="rafiki-perfsmoke-store-") as tmp:
        store = MetaStore(os.path.join(tmp, "meta.sqlite3"))
        params = ParamsStore(os.path.join(tmp, "params"))
        cls = load_model_class(MODEL_SRC, "PerfFF")
        model = store.create_model("perfff", "IMAGE_CLASSIFICATION", None,
                                   MODEL_SRC, "PerfFF")
        job = store.create_train_job("perfsmoke", "IMAGE_CLASSIFICATION",
                                     None, TRAIN, VAL,
                                     {"MODEL_TRIAL_COUNT": pack})
        sub = store.create_sub_train_job(job["id"], model["id"])
        advisors = AdvisorService()
        aid = advisors.create_advisor(cls.get_knob_config(), kind="random")
        worker = TrainWorker(store, params, sub["id"], cls,
                             InProcAdvisorHandle(advisors, aid),
                             TRAIN, VAL, {"MODEL_TRIAL_COUNT": pack},
                             async_persist=False, trial_pack=pack)
        return worker.run()


def run_serial_trials(n_trials):
    """Serial lr-varied trials sharing one program key, so the
    per-program detector accumulates warm samples across trials."""
    from rafiki_tpu.models.ff import FeedForward

    for i in range(n_trials):
        m = FeedForward(hidden_layers=1, hidden_units=32,
                        learning_rate=1e-3 * (1 + i),
                        batch_size=32, epochs=5, seed=0)
        m.train("synthetic://images?classes=4&n=128&w=8&h=8&c=1&seed=0")
        m.destroy()


def _tick_until_breach(deadline_s):
    from rafiki_tpu.obs.perf import slo

    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        state = slo.engine.tick()
        if any(st.get("breaching") for st in state.values()):
            return True
        time.sleep(0.05)
    return False


def _profile_via_cli(log_dir):
    """The real operator command from docs/perf.md, JSON mode."""
    proc = _run([sys.executable, "-m", "rafiki_tpu.obs", "--dir", log_dir,
                 "--json", "profile"])
    if proc.returncode != 0:
        raise RuntimeError(f"obs profile exited {proc.returncode}: "
                           f"{proc.stderr.strip()[:200]}")
    return json.loads(proc.stdout)["programs"]


def main() -> int:
    # Pinned before any detector exists — see module docstring.
    os.environ.setdefault("RAFIKI_PERF_K", "6")
    os.environ.pop("RAFIKI_CHAOS", None)  # phase 2 must be uninjected

    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    from rafiki_tpu import chaos
    from rafiki_tpu.obs.journal import journal

    t0 = time.monotonic()
    problems = []
    # Export the smoke's wider default instead of reading with a
    # different fallback than the library (RF016): every reader in
    # this process (and any child) now agrees on the width.
    os.environ.setdefault("RAFIKI_TRIAL_PACK", "4")
    pack = max(2, int(os.environ["RAFIKI_TRIAL_PACK"]))
    with tempfile.TemporaryDirectory(prefix="rafiki-perfsmoke-") as tmp:
        bench = check_bench_gate(problems, tmp)

        # -- phase 2: quiet ------------------------------------------------
        quiet_dir = os.path.join(tmp, "quiet")
        _fresh_stores(quiet_dir, tick_s=0.05)
        chaos.reset_from_env()  # RAFIKI_CHAOS popped above -> inert
        n = run_packed_round(pack)
        if n != pack:
            problems.append(f"packed round ran {n}/{pack} trials")
        _tick_until_breach(0.6)  # give the engine real ticks to NOT fire
        quiet = _read_perf(quiet_dir)
        if not quiet["costs"]:
            problems.append("quiet run captured no perf/cost record")
        if len(quiet["steps"]) < 2:
            problems.append(f"quiet run journaled {len(quiet['steps'])} "
                            "perf/step records, expected >= 2")
        for kind_name in ("anomalies", "breaches", "flights"):
            if quiet[kind_name]:
                problems.append(f"uninjected run produced "
                                f"{len(quiet[kind_name])} {kind_name}: "
                                f"{str(quiet[kind_name][0])[:150]}")
        packed_rows = []
        try:
            packed_rows = [r for r in _profile_via_cli(quiet_dir)
                           # lint: disable=RF014 — obs profile CLI rows keyed by program kind, not journal records
                           if r.get("kind") == "packed"]
        except (RuntimeError, ValueError, KeyError) as e:
            problems.append(f"obs profile failed on quiet dir: {e}")
        if not packed_rows:
            problems.append("obs profile reported no packed program")
        elif not (packed_rows[0].get("achieved_flops_s")
                  and packed_rows[0].get("mfu_vs_peak") is not None):
            problems.append(f"packed program row lacks MFU join: "
                            f"{str(packed_rows[0])[:200]}")

        # -- phase 3: injected ---------------------------------------------
        injected_dir = os.path.join(tmp, "injected")
        _fresh_stores(injected_dir, tick_s=0.05)
        os.environ["RAFIKI_CHAOS"] = "train.epoch:delay:delay=0.25:after=15"
        try:
            chaos.reset_from_env()
            run_serial_trials(4)
            breached = _tick_until_breach(2.5)
        finally:
            os.environ.pop("RAFIKI_CHAOS", None)
            chaos.reset_from_env()
        injected = _read_perf(injected_dir)
        if not injected["anomalies"]:
            problems.append("injected 0.25s epoch delay raised no "
                            "perf/anomaly record")
        if not breached or not injected["breaches"]:
            problems.append(f"anomaly-rate SLO never breached "
                            f"(tick saw breach={breached}, journal "
                            f"breaches={len(injected['breaches'])})")
        if not injected["flights"]:
            problems.append("SLO breach dumped no flight record")

        out = {
            "bench_gate": bench,
            "quiet": {k: len(v) for k, v in quiet.items()},
            "packed_mfu": (packed_rows[0].get("mfu_vs_peak")
                           if packed_rows else None),
            "injected": {k: len(v) for k, v in injected.items()},
            # lint: disable=RF007 — smoke artifact wall-clock
            "wall_s": round(time.monotonic() - t0, 3),
        }
        journal.close()
        os.environ.pop("RAFIKI_LOG_DIR", None)
        if problems:
            out["problems"] = problems
        print(json.dumps(out))
        return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
