#!/usr/bin/env bash
# Start the rafiki-tpu admin server on this TPU host.
#
# Reference parity: scripts/start.sh (unverified — SURVEY.md §3.3)
# boots Postgres, Redis, admin and web containers on a Docker swarm.
# The TPU-native control plane is one process (sqlite meta store,
# in-proc bus, web UI served by the admin app), so "start" is just
# supervising that process.
#
# Configuration via env (see rafiki_tpu/config.py for the full list):
#   RAFIKI_TPU_DATA_DIR      state root        (default ~/.rafiki_tpu)
#   RAFIKI_TPU_ADMIN_HOST    bind address      (default 127.0.0.1)
#   RAFIKI_TPU_ADMIN_PORT    admin port        (default 3000)
#   RAFIKI_TPU_JWT_SECRET    token secret      (CHANGE IN PRODUCTION)
#   RAFIKI_PROFILE_DIR       per-trial profiler traces (optional)
set -euo pipefail

cd "$(dirname "$0")/.."
RUN_DIR="${RAFIKI_TPU_DATA_DIR:-$HOME/.rafiki_tpu}"
mkdir -p "$RUN_DIR"
PID_FILE="$RUN_DIR/admin.pid"

if [[ -f "$PID_FILE" ]] && kill -0 "$(cat "$PID_FILE")" 2>/dev/null; then
  echo "admin already running (pid $(cat "$PID_FILE"))"
  exit 0
fi

nohup python -m rafiki_tpu serve > "$RUN_DIR/admin.out" 2>&1 &
echo $! > "$PID_FILE"
echo "rafiki-tpu admin starting (pid $(cat "$PID_FILE")); log: $RUN_DIR/admin.out"
for _ in $(seq 1 50); do
  if curl -fs "http://${RAFIKI_TPU_ADMIN_HOST:-127.0.0.1}:${RAFIKI_TPU_ADMIN_PORT:-3000}/healthz" > /dev/null 2>&1; then
    echo "admin is up"
    exit 0
  fi
  sleep 0.2
done
echo "WARNING: admin did not report healthy within 10s; check $RUN_DIR/admin.out" >&2
exit 1
