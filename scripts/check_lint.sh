#!/usr/bin/env bash
# Static-analysis gate: zero unsuppressed findings over the canonical
# path set (see docs/static_analysis.md). Same checkers, same paths as
# tests/test_lint_clean.py — this is the shell-visible form CI and
# check_tier1.sh use. JSON output so a failing run leaves a
# machine-readable artifact on stdout.
set -o pipefail
cd "$(dirname "$0")/.."
env JAX_PLATFORMS=cpu python -m rafiki_tpu.analysis rafiki_tpu bench.py scripts --format json
rc=$?
if [ $rc -ne 0 ]; then
  echo "check_lint: unsuppressed findings (or parse errors) — run" >&2
  echo "  python -m rafiki_tpu.analysis rafiki_tpu bench.py scripts" >&2
  echo "and fix or justify-suppress each (docs/static_analysis.md)." >&2
fi
exit $rc
