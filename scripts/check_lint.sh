#!/usr/bin/env bash
# Static-analysis gate: zero unsuppressed findings over the canonical
# path set (see docs/static_analysis.md). Same checkers, same paths as
# tests/test_lint_clean.py — this is the shell-visible form CI and
# check_tier1.sh use. JSON output so a failing run leaves a
# machine-readable artifact on stdout.
#
# The contracts pass (docs/static_analysis.md, "Contracts") then diffs
# the freshly extracted contracts manifest and the generated knob docs
# against their committed copies: any journal-kind / env-knob /
# telemetry-name drift fails the gate as a reviewable diff.
#
#   --contracts-only   skip the checker pass; run only the contracts
#                      extraction + golden/docs diffs (fast path for
#                      regenerate-and-recheck loops)
set -o pipefail
cd "$(dirname "$0")/.."

PATHS="rafiki_tpu bench.py scripts"
GOLDEN=tests/data/contracts_manifest.json
KNOBS=docs/knobs.md

if [ "${1:-}" != "--contracts-only" ]; then
  env JAX_PLATFORMS=cpu python -m rafiki_tpu.analysis $PATHS --format json
  rc=$?
  if [ $rc -ne 0 ]; then
    echo "check_lint: unsuppressed findings (or parse errors) — run" >&2
    echo "  python -m rafiki_tpu.analysis $PATHS" >&2
    echo "and fix or justify-suppress each (docs/static_analysis.md)." >&2
    exit $rc
  fi
fi

# -- contracts pass ----------------------------------------------------------

tmp="$(mktemp -d)"
trap 'rm -rf "$tmp"' EXIT

env JAX_PLATFORMS=cpu python -m rafiki_tpu.analysis --contracts $PATHS \
  > "$tmp/manifest.json" || exit 2
if ! diff -u "$GOLDEN" "$tmp/manifest.json"; then
  echo "check_lint: contracts manifest drifted from $GOLDEN —" >&2
  echo "review the diff above (a renamed journal kind, env knob, or" >&2
  echo "metric changes a cross-process contract), then regenerate:" >&2
  echo "  python -m rafiki_tpu.analysis --contracts > $GOLDEN" >&2
  exit 1
fi

env JAX_PLATFORMS=cpu python -m rafiki_tpu.analysis --contracts --docs \
  $PATHS > "$tmp/knobs.md" || exit 2
if ! diff -u "$KNOBS" "$tmp/knobs.md"; then
  echo "check_lint: $KNOBS is stale — it is generated, not" >&2
  echo "hand-edited. Regenerate:" >&2
  echo "  python -m rafiki_tpu.analysis --contracts --docs > $KNOBS" >&2
  exit 1
fi
if grep -q "undocumented" "$tmp/knobs.md"; then
  echo "check_lint: undocumented env knob(s) — add a one-line" >&2
  echo "description to rafiki_tpu/analysis/contracts/knobdocs.py" >&2
  echo "and regenerate $KNOBS." >&2
  grep "undocumented" "$tmp/knobs.md" | head -5 >&2
  exit 1
fi

echo "check_lint: contracts manifest and knob docs match the tree"
exit 0
