"""CLI: ``python -m rafiki_tpu <command>``.

Reference parity: scripts/*.sh (unverified — SURVEY.md §2 deployment
row) started the reference's services as containers; here the whole
control plane is one process, so the CLI is the deployment surface:

  python -m rafiki_tpu serve [--host H] [--port P]   admin + web UI
  python -m rafiki_tpu bench                          one-chip benchmark
  python -m rafiki_tpu version
"""

from __future__ import annotations

import argparse
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="rafiki_tpu")
    sub = parser.add_subparsers(dest="command")

    serve_p = sub.add_parser("serve", help="run the admin server (+ web UI)")
    serve_p.add_argument("--host", default=None)
    serve_p.add_argument("--port", type=int, default=None)

    sub.add_parser("bench", help="run the one-chip AutoML benchmark")
    sub.add_parser("version", help="print version")

    args = parser.parse_args(argv)
    # Pin the platform before ANY branch touches jax (the serve path
    # imports the admin stack, which imports jax transitively, and
    # enable_compilation_cache imports jax itself): a JAX_PLATFORMS=cpu
    # request must survive this image's sitecustomize TPU hijack.
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    if args.command == "serve":
        from rafiki_tpu.admin.app import serve
        from rafiki_tpu.utils.backend import enable_compilation_cache

        enable_compilation_cache()
        serve(host=args.host, port=args.port)
        return 0
    if args.command == "bench":
        import runpy
        from pathlib import Path

        from rafiki_tpu.utils.backend import enable_compilation_cache

        enable_compilation_cache()
        bench = Path(__file__).resolve().parent.parent / "bench.py"
        runpy.run_path(str(bench), run_name="__main__")
        return 0
    if args.command == "version":
        import rafiki_tpu

        print(rafiki_tpu.__version__)
        return 0
    parser.print_help()
    return 1


if __name__ == "__main__":
    sys.exit(main())
