"""Unified telemetry layer: one process-wide metrics registry + span
tracer behind a module-level functional API.

Every subsystem writes through these functions; every reader (the
``GET /metrics`` endpoints on the admin and predictor apps, bench.py's
embedded snapshot, ``scripts/tpu_watch.py``, tests) reads the SAME
state via :func:`snapshot`, so "what the bench reports" and "what the
serving endpoint shows" can never drift apart.

Write API (cheap, thread-safe, never raises into callers):
    inc("bus.reaped_workers")            counters (floats allowed)
    set_gauge("bus.queue_depth", 3)      point-in-time values
    add_gauge("scheduler.active_workers", +1)
    observe("predictor.gather_s", 0.01)  histograms (bounded reservoir)
    with span("trial.train", trial_id=t): ...   nestable timed phases

Read API:
    snapshot()        -> one JSON-able dict (registry + span aggregates
                         + registered collectors, e.g. program_cache)
    span_records()    -> the bounded ring of finished spans
    dump_jsonl(path)  -> span records + final snapshot, one JSON/line

Scope: telemetry is PER-PROCESS (like the program cache). Subprocess
workers accumulate their own registries; cross-process aggregation is
the reader's job (each process exposes/dumps its own state).
"""

from __future__ import annotations

import json
from typing import Any, Callable, Dict, List

from rafiki_tpu.telemetry.registry import Histogram, Registry
from rafiki_tpu.telemetry.spans import Span, Tracer

__all__ = [
    "Histogram", "Registry", "Span", "Tracer",
    "inc", "set_gauge", "add_gauge", "observe", "span",
    "get_counter", "get_gauge", "get_registry", "get_tracer",
    "register_collector", "snapshot", "span_records", "dump_jsonl",
    "reset", "current_span_id",
]

_registry = Registry()
_tracer = Tracer()


def get_registry() -> Registry:
    return _registry


def get_tracer() -> Tracer:
    return _tracer


# -- writes ------------------------------------------------------------------


def inc(name: str, n: float = 1.0) -> None:
    _registry.inc(name, n)


def set_gauge(name: str, value: float) -> None:
    _registry.set_gauge(name, value)


def add_gauge(name: str, delta: float) -> None:
    _registry.add_gauge(name, delta)


def observe(name: str, value: float) -> None:
    _registry.observe(name, value)


def span(name: str, **tags: Any) -> Span:
    return _tracer.span(name, **tags)


def current_span_id():
    """The innermost open span id on this thread, or None."""
    return _tracer.current_span_id()


def register_collector(name: str, fn: Callable[[], Dict[str, Any]]) -> None:
    _registry.register_collector(name, fn)


# -- reads -------------------------------------------------------------------


def get_counter(name: str) -> float:
    return _registry.get_counter(name)


def get_gauge(name: str):
    return _registry.get_gauge(name)


def snapshot() -> Dict[str, Any]:
    """The whole telemetry state as one JSON-able dict."""
    out = _registry.snapshot()
    out["spans"] = _tracer.summary()
    return out


def span_records() -> List[Dict[str, Any]]:
    return _tracer.records()


def dump_jsonl(path) -> int:
    """Write finished span records then a final ``{"type": "snapshot"}``
    line to ``path``. Returns the number of lines written."""
    records = _tracer.records()
    snap = dict(snapshot(), type="snapshot")
    with open(path, "w") as f:
        for rec in records:
            f.write(json.dumps(rec) + "\n")
        f.write(json.dumps(snap) + "\n")
    return len(records) + 1


def reset(clear_collectors: bool = False) -> None:
    """Zero all metrics and spans (tests; collectors stay by default
    since they register at module import)."""
    _registry.reset(clear_collectors=clear_collectors)
    _tracer.reset()
