"""Lightweight span tracer: where did this trial's wall-clock go?

``span("trial.train", trial_id=...)`` is a nestable context manager.
Nesting is tracked per thread (worker threads each carry their own
stack), so a span records its parent's name and depth — enough to
reassemble a trial's phase tree from the flat JSONL export without a
distributed-tracing dependency.

Costs: two ``time`` calls plus one locked deque append per span — spans
wrap phases (compile, epoch, persist, gather), never per-step device
work.

Exports:
  * per-name aggregates (count / total_s / min / max) for snapshots;
  * a bounded ring of finished span records for ``dump_jsonl`` — old
    spans fall off instead of growing the process (same philosophy as
    the bus's expired-query ring).
"""

from __future__ import annotations

import threading
import time
import uuid
from collections import deque
from typing import Any, Dict, List, Optional

from rafiki_tpu.obs import context as _trace_context
from rafiki_tpu.obs.journal import journal as _journal


class Span:
    """Context manager recording one timed, possibly-nested phase."""

    __slots__ = ("name", "tags", "_tracer", "_t0", "_start_ts",
                 "_parent", "_span_id", "_parent_id", "_trace_id")

    def __init__(self, tracer: "Tracer", name: str, tags: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.tags = tags
        self._t0 = 0.0
        self._start_ts = 0.0
        self._parent: Optional[str] = None
        self._span_id = ""
        self._parent_id: Optional[str] = None
        self._trace_id: Optional[str] = None

    @property
    def span_id(self) -> str:
        return self._span_id

    def __enter__(self) -> "Span":
        stack = self._tracer._stack()
        if stack:
            self._parent, self._parent_id = stack[-1]
        self._span_id = uuid.uuid4().hex[:16]
        self._trace_id = _trace_context.current_trace_id()
        stack.append((self.name, self._span_id))
        self._start_ts = time.time()
        self._t0 = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur = time.monotonic() - self._t0
        stack = self._tracer._stack()
        if stack and stack[-1][0] == self.name:
            stack.pop()
        self._tracer._record(self, dur, error=exc_type is not None)
        return False  # never swallow


class Tracer:
    _RECORD_CAP = 4096

    def __init__(self):
        self._lock = threading.Lock()
        self._tls = threading.local()
        # name -> [count, total_s, min_s, max_s]
        self._agg: Dict[str, List[float]] = {}
        self._records: "deque[Dict[str, Any]]" = deque(maxlen=self._RECORD_CAP)

    def _stack(self) -> list:
        """Per-thread stack of (name, span_id) tuples for open spans."""
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current_span_id(self) -> Optional[str]:
        """The innermost open span's id on this thread (trace
        propagation: the bus envelope carries it as parent_span)."""
        stack = self._stack()
        return stack[-1][1] if stack else None

    def span(self, name: str, **tags: Any) -> Span:
        return Span(self, name, tags)

    def _record(self, span: Span, dur_s: float, error: bool) -> None:
        rec: Dict[str, Any] = {
            "type": "span",
            "name": span.name,
            "ts": span._start_ts,
            "dur_s": round(dur_s, 6),
            "parent": span._parent,
            "span_id": span._span_id,
            "parent_id": span._parent_id,
        }
        if span._trace_id:
            rec["trace_id"] = span._trace_id
        if span.tags:
            rec["tags"] = span.tags
        if error:
            rec["error"] = True
        # Durable copy first (journal has its own lock; no-op when the
        # process hasn't opted in via RAFIKI_LOG_DIR).
        _journal.record(
            "span", span.name, ts=span._start_ts,
            dur_s=rec["dur_s"], span_id=span._span_id,
            parent_id=span._parent_id, trace_id=span._trace_id,
            **({"tags": span.tags} if span.tags else {}),
            **({"error": True} if error else {}))
        with self._lock:
            agg = self._agg.get(span.name)
            if agg is None:
                self._agg[span.name] = [1, dur_s, dur_s, dur_s]
            else:
                agg[0] += 1
                agg[1] += dur_s
                agg[2] = min(agg[2], dur_s)
                agg[3] = max(agg[3], dur_s)
            self._records.append(rec)

    # -- reads ---------------------------------------------------------------

    def summary(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {
                name: {
                    "count": int(c),
                    "total_s": round(total, 6),
                    "min_s": round(mn, 6),
                    "max_s": round(mx, 6),
                }
                for name, (c, total, mn, mx) in self._agg.items()
            }

    def records(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._records)

    def reset(self) -> None:
        with self._lock:
            self._agg.clear()
            self._records.clear()
