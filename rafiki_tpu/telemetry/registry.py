"""Process-wide metrics registry: counters, gauges, histograms.

Design constraints (ISSUE 1):
  * thread-safe — trial worker threads, the predictor's HTTP threads,
    heartbeat daemons and the bench's serving threads all write
    concurrently; one registry lock is plenty at this event rate
    (every write is a dict update, far off any hot device path);
  * bounded memory — histograms keep a fixed-size reservoir
    (Vitter's algorithm R), never the full observation stream;
  * pull-based re-export — subsystems with their own counters (the
    program cache in ops/train.py) register a *collector* callable and
    the snapshot inlines its dict, so legacy stats surface through the
    same endpoint without double bookkeeping.

Everything is plain floats/ints/strings, so ``snapshot()`` is always
``json.dumps``-able — the contract the ``/metrics`` endpoints and
BENCH artifacts rely on.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class Histogram:
    """Count/sum/min/max plus a bounded reservoir for percentiles."""

    __slots__ = ("count", "sum", "min", "max", "_reservoir", "_cap", "_rng")

    def __init__(self, reservoir_cap: int = 512):
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._cap = reservoir_cap
        self._reservoir: List[float] = []
        # Seeded per-histogram: reservoir contents are reproducible in
        # tests and never consume the global random stream.
        self._rng = random.Random(0x5EED)

    def observe(self, value: float) -> None:
        v = float(value)
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = v if self.max is None else max(self.max, v)
        if len(self._reservoir) < self._cap:
            self._reservoir.append(v)
        else:  # algorithm R: each of the n observations keeps cap/n odds
            i = self._rng.randrange(self.count)
            if i < self._cap:
                self._reservoir[i] = v

    def summary(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "count": self.count,
            "sum": round(self.sum, 6),
            "min": self.min,
            "max": self.max,
            "mean": round(self.sum / self.count, 6) if self.count else None,
        }
        if self._reservoir:
            xs = sorted(self._reservoir)
            last = len(xs) - 1
            for p in (50, 90, 99):
                out[f"p{p}"] = xs[min(last, int(last * p / 100))]
        return out


class Registry:
    """Thread-safe named metrics with a JSON-able snapshot."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._gauges: Dict[str, float] = {}
        self._hists: Dict[str, Histogram] = {}
        self._collectors: Dict[str, Callable[[], Dict[str, Any]]] = {}

    # -- writes --------------------------------------------------------------

    def inc(self, name: str, n: float = 1.0) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0.0) + n

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def add_gauge(self, name: str, delta: float) -> None:
        with self._lock:
            self._gauges[name] = self._gauges.get(name, 0.0) + delta

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            hist = self._hists.get(name)
            if hist is None:
                hist = self._hists[name] = Histogram()
            hist.observe(value)

    def register_collector(self, name: str,
                           fn: Callable[[], Dict[str, Any]]) -> None:
        """Attach a pull-based stats source; its dict appears verbatim
        under ``name`` in every snapshot. Re-registering replaces."""
        with self._lock:
            self._collectors[name] = fn

    # -- reads ---------------------------------------------------------------

    def get_counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def get_gauge(self, name: str) -> Optional[float]:
        with self._lock:
            return self._gauges.get(name)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            out: Dict[str, Any] = {
                "ts": time.time(),
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {k: h.summary() for k, h in self._hists.items()},
            }
            collectors = list(self._collectors.items())
        # Collectors run OUTSIDE the registry lock: they may take their
        # own locks (program cache) and must not deadlock against a
        # metric write from under them.
        for name, fn in collectors:
            try:
                out[name] = fn()
            except Exception as e:  # a broken collector can't break /metrics
                out[name] = {"error": f"{type(e).__name__}: {e}"}
        return out

    def reset(self, clear_collectors: bool = False) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()
            if clear_collectors:
                self._collectors.clear()
