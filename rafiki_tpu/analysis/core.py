"""AST static-analysis core: checker registry, project context, runner.

Why in-repo instead of flake8 plugins: every checker here encodes a
failure class this codebase has actually shipped (see
docs/static_analysis.md for the catalog and the historical bug behind
each id). The framework is deliberately small:

  * a :class:`Checker` subclass registers itself via :func:`register`
    and receives one :class:`ModuleContext` per analyzed file;
  * project-wide facts (the jax import-taint set) are computed once in
    :class:`ProjectContext` before any checker runs, so checkers can
    ask "does importing this module pull in jax?" without re-walking
    the tree;
  * findings are suppressed inline with ``# lint: disable=RF00x — why``
    on the offending line (or an immediately preceding comment line).
    A suppression WITHOUT a justification does not suppress — the rule
    "every suppression carries its one-line why" is enforced here, not
    by review vigilance.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple, Type

SEVERITIES = ("error", "warning")

# ``# lint: disable=RF001`` or ``# lint: disable=RF001,RF003 — reason``.
# The justification separator is any of ``—``, ``--``, ``-`` or ``:``
# followed by non-empty text.
_SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*disable=([A-Z]{2,}\d+(?:\s*,\s*[A-Z]{2,}\d+)*)"
    r"\s*(?:(?:—|--|-|:)\s*(\S.*))?")


@dataclass
class Finding:
    checker_id: str
    path: str
    line: int
    col: int
    severity: str
    message: str
    suppressed: bool = False
    justification: str = ""

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {
            "checker": self.checker_id,
            "path": self.path,
            "line": self.line,
            "col": self.col,
            "severity": self.severity,
            "message": self.message,
            "suppressed": self.suppressed,
            "justification": self.justification,
        }


@dataclass
class ModuleContext:
    """Everything a checker may want to know about one analyzed file."""

    path: str                 # as given on the command line (relative ok)
    module_name: str          # dotted, e.g. "rafiki_tpu.bus.queues"
    tree: ast.Module
    source: str
    lines: List[str]
    project: "ProjectContext"

    # (line -> (set of ids | None for all, justification)) built lazily
    _suppressions: Optional[Dict[int, Tuple[Set[str], str]]] = None

    def suppression_at(self, line: int) -> Optional[Tuple[Set[str], str]]:
        """The suppression covering ``line``: same line or an
        immediately preceding comment-only line."""
        if self._suppressions is None:
            sup: Dict[int, Tuple[Set[str], str]] = {}
            for i, text in enumerate(self.lines, start=1):
                m = _SUPPRESS_RE.search(text)
                if not m:
                    continue
                ids = {s.strip() for s in m.group(1).split(",")}
                just = (m.group(2) or "").strip()
                sup[i] = (ids, just)
                # a comment-only line covers the next code line
                if text.lstrip().startswith("#"):
                    sup.setdefault(i + 1, (ids, just))
            self._suppressions = sup
        return self._suppressions.get(line)


class ProjectContext:
    """Cross-file facts shared by all checkers for one analysis run."""

    def __init__(self, modules: Dict[str, ModuleContext]):
        self.modules = modules            # module_name -> ctx
        self._facts: Dict[str, object] = {}
        self.jax_tainted: Set[str] = self._compute_jax_taint()

    def fact(self, key: str, compute):
        """Memoized whole-program fact shared across checkers — the
        contracts extraction (RF014–RF016) walks every tree once per
        run through this, not once per (checker, module) pair."""
        if key not in self._facts:
            self._facts[key] = compute(self)
        return self._facts[key]

    # -- jax import taint ----------------------------------------------------

    @staticmethod
    def _imported_module_names(tree: ast.AST) -> Set[str]:
        """Every module name this tree may import (module- or
        function-level): for ``from M import a, b`` both ``M`` and
        ``M.a``/``M.b`` are candidates (a may itself be a submodule)."""
        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    names.add(alias.name)
            elif isinstance(node, ast.ImportFrom) and node.module:
                names.add(node.module)
                for alias in node.names:
                    names.add(f"{node.module}.{alias.name}")
        return names

    def _compute_jax_taint(self) -> Set[str]:
        """Fixpoint: a module is jax-tainted if it imports jax, or
        imports an analyzed module that is. Bounded to the analyzed
        file set — callers who need whole-project taint analyze the
        whole project."""
        imports = {name: self._imported_module_names(ctx.tree)
                   for name, ctx in self.modules.items()}
        tainted = {name for name, imps in imports.items()
                   if any(i == "jax" or i.startswith("jax.") for i in imps)}
        changed = True
        while changed:
            changed = False
            for name, imps in imports.items():
                if name in tainted:
                    continue
                if any(i in tainted for i in imps):
                    tainted.add(name)
                    changed = True
        return tainted

    def is_jax_tainted(self, module_name: str) -> bool:
        return module_name in self.jax_tainted


class Checker:
    """Base class. Subclasses set ``id``/``name``/``severity`` and
    implement :meth:`check_module`; :func:`register` puts them in the
    registry the CLI and tests discover checkers from."""

    id: str = ""
    name: str = ""
    severity: str = "warning"
    rationale: str = ""  # one-liner surfaced by ``--explain``

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str,
                severity: Optional[str] = None) -> Finding:
        return Finding(
            checker_id=self.id, path=ctx.path,
            line=getattr(node, "lineno", 0), col=getattr(node, "col_offset", 0),
            severity=severity or self.severity, message=message)


REGISTRY: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if not cls.id:
        raise ValueError(f"{cls.__name__} has no checker id")
    if cls.id in REGISTRY and REGISTRY[cls.id] is not cls:
        raise ValueError(f"duplicate checker id {cls.id}")
    REGISTRY[cls.id] = cls
    return cls


def load_builtin_checkers() -> None:
    """Plugin discovery: import every module in the checkers package;
    each registers itself on import."""
    import importlib
    import pkgutil

    from rafiki_tpu.analysis import checkers as pkg

    for mod in pkgutil.iter_modules(pkg.__path__):
        importlib.import_module(f"{pkg.__name__}.{mod.name}")


# ---------------------------------------------------------------------------
# File collection and module naming
# ---------------------------------------------------------------------------


def _collect_py_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                out.extend(os.path.join(root, f) for f in sorted(files)
                           if f.endswith(".py"))
        elif p.endswith(".py"):
            out.append(p)
    # de-dup, stable order
    seen: Set[str] = set()
    uniq = []
    for f in out:
        key = os.path.abspath(f)
        if key not in seen:
            seen.add(key)
            uniq.append(f)
    return uniq


def module_name_for(path: str) -> str:
    """Dotted module name: walk up while __init__.py exists, so
    rafiki_tpu/bus/queues.py -> rafiki_tpu.bus.queues; a top-level
    script (bench.py) is just its stem."""
    path = os.path.abspath(path)
    parts = [os.path.splitext(os.path.basename(path))[0]]
    d = os.path.dirname(path)
    while os.path.exists(os.path.join(d, "__init__.py")):
        parts.append(os.path.basename(d))
        d = os.path.dirname(d)
    name = ".".join(reversed(parts))
    return name[: -len(".__init__")] if name.endswith(".__init__") else name


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


@dataclass
class AnalysisResult:
    findings: List[Finding] = field(default_factory=list)
    files_analyzed: int = 0
    parse_errors: List[str] = field(default_factory=list)

    @property
    def unsuppressed(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]


def analyze_paths(paths: Sequence[str],
                  select: Optional[Sequence[str]] = None) -> AnalysisResult:
    """Parse every .py under ``paths``, build project context, run the
    registered checkers (all, or only ``select`` ids), apply inline
    suppressions. Checkers must already be loaded/registered."""
    result = AnalysisResult()
    modules: Dict[str, ModuleContext] = {}
    for path in _collect_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError) as e:
            result.parse_errors.append(f"{path}: {e}")
            continue
        ctx = ModuleContext(path=path, module_name=module_name_for(path),
                            tree=tree, source=source,
                            lines=source.splitlines(), project=None)  # type: ignore[arg-type]
        modules[ctx.module_name] = ctx
    project = ProjectContext(modules)
    for ctx in modules.values():
        ctx.project = project

    ids = sorted(REGISTRY) if select is None else [i for i in sorted(REGISTRY)
                                                  if i in set(select)]
    checkers = [REGISTRY[i]() for i in ids]
    for ctx in modules.values():
        result.files_analyzed += 1
        for checker in checkers:
            for f in checker.check_module(ctx):
                sup = ctx.suppression_at(f.line)
                if sup is not None and f.checker_id in sup[0]:
                    if sup[1]:
                        f.suppressed = True
                        f.justification = sup[1]
                    else:
                        f.message += (" [suppression present but has no "
                                      "justification — add one after the id]")
                result.findings.append(f)
    result.findings.sort(key=lambda f: (f.path, f.line, f.checker_id))
    return result
