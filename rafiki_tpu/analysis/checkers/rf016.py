"""RF016 env-knob-contract.

Every ``RAFIKI_*`` environment variable is a cross-process config
channel with no schema. Two failure classes recur:

* **default divergence** — the same knob read in two places with two
  different constant defaults. Whichever process reads it first
  "wins" its own default, and behavior depends on which code path ran
  — set the knob and both agree, unset it and they silently differ.
  Only distinct *constant* defaults count: a required read
  (``os.environ["K"]``) or a computed default can't statically
  disagree with anything. One finding per knob, anchored at its first
  read in path order, listing every site and its default.
* **unpropagated knob** — a subprocess spawned with an explicitly
  constructed env dict (NOT ``dict(os.environ)``/``.copy()``, which
  inherit everything) whose ``-m`` target transitively reads knobs the
  dict never sets. The child silently falls back to defaults the
  parent may have overridden. One finding per spawn site, listing the
  missing knobs.

Deliberately different defaults (a smoke that wants a bigger pack than
the library fallback) suppress with a why stating the intent.
"""

from __future__ import annotations

from typing import Iterable, List

from rafiki_tpu.analysis.checkers._ast_util import LineNode
from rafiki_tpu.analysis.core import (
    Checker, Finding, ModuleContext, ProjectContext, register)
from rafiki_tpu.analysis.contracts import env_contracts
from rafiki_tpu.analysis.contracts.envknobs import knobs_in_closure


@register
class EnvKnobContract(Checker):
    id = "RF016"
    name = "env-knob-contract"
    severity = "error"
    rationale = ("same knob, different defaults: behavior depends on "
                 "which process read it; unpropagated knobs silently "
                 "reset in children")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        env = env_contracts(ctx.project)
        out: List[Finding] = []
        for knob, reads in sorted(env.divergent().items()):
            anchor = reads[0]  # reads are (knob, path, line)-sorted
            if anchor.path != ctx.path:
                continue
            sites = ", ".join(f"{r.path}:{r.line}={r.default}"
                              for r in reads)
            out.append(self.finding(
                ctx, LineNode(anchor.line),
                f"knob '{knob}' is read with "
                f"{len({r.default for r in reads})} different constant "
                f"defaults ({sites}) — unset, behavior depends on "
                f"which code path ran"))
        for s in env.spawns:
            if (s.path != ctx.path or s.inherits_environ
                    or s.target_module is None):
                continue
            child = knobs_in_closure(
                ctx.project.modules,
                ProjectContext._imported_module_names,
                s.target_module, env)
            missing = sorted(k for k in child if k not in s.explicit_keys)
            if not missing:
                continue
            shown = ", ".join(missing[:6])
            if len(missing) > 6:
                shown += f", +{len(missing) - 6} more"
            out.append(self.finding(
                ctx, LineNode(s.line),
                f"spawn of {s.target_module} passes an explicit env "
                f"that omits knob(s) the child reads: {shown} — "
                f"inherit os.environ or propagate them"))
        return out
