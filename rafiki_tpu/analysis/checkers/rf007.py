"""RF007 leaked-span / hand-rolled timing.

Observability-plane finding (PR 6): the span primitive only measures —
and only journals — on ``__exit__``. Two ways call sites defeat it:

* **error** — ``telemetry.span(...)`` (or a bare ``span()`` imported
  from rafiki_tpu.telemetry) called anywhere but as a ``with`` context
  expression (or handed straight to ``ExitStack.enter_context``). A
  span that never enters/exits records nothing, flushes nothing to the
  journal, and — if entered without a paired exit — corrupts the
  parent stack for everything nested after it.
* **warning** — an end-minus-start delta ``time.monotonic() - x`` in a
  module that imports rafiki_tpu.telemetry: such a module already has
  the primitive whose exits feed ``obs trace``/``obs slowest`` and the
  goodput ledger, so a hand-rolled delta is timing that observability
  cannot see. Wrap the region in ``telemetry.span(...)`` — or
  justify-suppress where the delta feeds a different accounting
  surface (a ledger bucket charge, a deadline budget, an EWMA).

``rafiki_tpu/telemetry/`` and ``rafiki_tpu/obs/`` are exempt: they
implement the layer this rule points everyone else at. The
remaining-budget shape ``deadline - time.monotonic()`` is not a delta
and is never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name, parent_map

_EXEMPT_PREFIXES = ("rafiki_tpu.telemetry", "rafiki_tpu.obs")


def _span_call_names(tree: ast.Module) -> Set[str]:
    """Dotted names that resolve to telemetry's span() in this module:
    always ``*.span`` via a telemetry module alias, plus any bare alias
    from ``from rafiki_tpu.telemetry import span [as x]``."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "rafiki_tpu.telemetry":
                for a in node.names:
                    if a.name == "span":
                        names.add(a.asname or a.name)
            elif node.module == "rafiki_tpu":
                for a in node.names:
                    if a.name == "telemetry":
                        names.add(f"{a.asname or a.name}.span")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "rafiki_tpu.telemetry":
                    names.add(f"{a.asname or a.name}.span")
    return names


def _imports_telemetry(tree: ast.Module) -> bool:
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.startswith("rafiki_tpu.telemetry"):
                return True
            if node.module == "rafiki_tpu" and any(
                    a.name == "telemetry" for a in node.names):
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.startswith("rafiki_tpu.telemetry")
                   for a in node.names):
                return True
    return False


def _is_with_context(call: ast.Call, parents) -> bool:
    """Is this call a `with` item's context expression, or fed straight
    to ExitStack.enter_context (the dynamic equivalent)?"""
    parent = parents.get(call)
    if isinstance(parent, ast.withitem) and parent.context_expr is call:
        return True
    if (isinstance(parent, ast.Call) and call in parent.args
            and dotted_name(parent.func).endswith("enter_context")):
        return True
    return False


@register
class LeakedSpan(Checker):
    id = "RF007"
    name = "leaked-span"
    severity = "error"
    rationale = ("a span not used as a `with` context never exits — it "
                 "records nothing, journals nothing, and corrupts the "
                 "span parent stack; hand-rolled monotonic deltas are "
                 "timing the observability plane cannot see")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.module_name.startswith(_EXEMPT_PREFIXES):
            return []
        findings: List[Finding] = []
        parents = parent_map(ctx.tree)
        span_names = _span_call_names(ctx.tree)
        has_telemetry = _imports_telemetry(ctx.tree)
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.Call) and span_names
                    and dotted_name(node.func) in span_names
                    and not _is_with_context(node, parents)):
                findings.append(self.finding(
                    ctx, node,
                    "telemetry.span(...) outside a `with` never exits: "
                    "no duration recorded, no journal flush, and the "
                    "span parent stack is corrupted for everything "
                    "after it — use `with telemetry.span(...):`"))
            elif (has_telemetry and isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and isinstance(node.left, ast.Call)
                    and dotted_name(node.left.func) == "time.monotonic"):
                findings.append(self.finding(
                    ctx, node,
                    "hand-rolled `time.monotonic() - ...` delta in a "
                    "telemetry-importing module: invisible to `obs "
                    "trace`/`obs slowest` — wrap the region in "
                    "telemetry.span(...) or justify-suppress",
                    severity="warning"))
        return findings
