"""RF005 jit-hazard.

Failure class: the hot path (`ops/`, `parallel/`) is only fast while
its jitted programs stay jitted. Three mechanical ways to lose that:

  * Python ``if``/``while`` on a *traced* value inside a jitted
    function — a TracerBoolConversionError at best, a silent
    per-value recompile when the value is marked static;
  * host syncs (``.item()``, ``float(...)``/``int(...)``,
    ``np.asarray(...)``) inside a jitted function — each one stalls
    the device pipeline on a device->host transfer;
  * constructing ``jax.jit(...)`` inside a loop — every iteration
    makes a fresh callable with a fresh (empty) compile cache.

Rule, applied to functions this module passes to ``jax.jit`` (or
decorates with it): flag host-sync calls, ``jax.jit`` calls inside
``for``/``while`` bodies anywhere in the module, and ``if``/``while``
whose test references a function parameter through an order comparison
or bare truthiness (``in``/``is`` tests are trace-time static and
stay legal).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name

_HOST_SYNC_CALLS = {"float", "int", "bool"}
_HOST_SYNC_ATTRS = {"item", "tolist"}
_NP_SYNC = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}


def _jitted_function_names(tree: ast.Module) -> Set[str]:
    """Names of functions passed to jax.jit(...) or decorated @jax.jit
    anywhere in the module (nested defs included — ops.train builds its
    steps inside Program.__init__)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            callee = dotted_name(node.func)
            if callee.endswith("jit") and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Name):
                    names.add(arg.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if dotted_name(target).endswith("jit"):
                    names.add(node.name)
    return names


def _params_of(fn: ast.FunctionDef) -> Set[str]:
    a = fn.args
    return {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)} | (
        {a.vararg.arg} if a.vararg else set()) | (
        {a.kwarg.arg} if a.kwarg else set())


def _test_trips_on_param(test: ast.AST, params: Set[str]) -> bool:
    """True when the branch condition's truthiness can depend on a
    traced parameter: a bare param name, or a param inside an order/
    equality comparison or arithmetic. `x in d` / `x is None` are
    resolved at trace time and excluded."""
    if isinstance(test, ast.Name):
        return test.id in params
    if isinstance(test, ast.Compare):
        if all(isinstance(op, (ast.In, ast.NotIn, ast.Is, ast.IsNot))
               for op in test.ops):
            return False
        return any(isinstance(n, ast.Name) and n.id in params
                   for n in ast.walk(test))
    if isinstance(test, ast.BoolOp):
        return any(_test_trips_on_param(v, params) for v in test.values)
    if isinstance(test, ast.UnaryOp):
        return _test_trips_on_param(test.operand, params)
    if isinstance(test, (ast.BinOp, ast.Subscript, ast.Attribute, ast.Call)):
        return any(isinstance(n, ast.Name) and n.id in params
                   for n in ast.walk(test))
    return False


@register
class JitHazard(Checker):
    id = "RF005"
    name = "jit-hazard"
    severity = "warning"
    rationale = ("python control flow on traced values, host syncs inside "
                 "jitted fns, and jax.jit built inside loops all silently "
                 "destroy the compile-once model the hot path depends on")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        jitted = _jitted_function_names(ctx.tree)

        # jax.jit constructed inside a loop — module-wide
        for loop in [n for n in ast.walk(ctx.tree)
                     if isinstance(n, (ast.For, ast.While))]:
            for node in ast.walk(loop):
                if (isinstance(node, ast.Call)
                        and dotted_name(node.func) in ("jax.jit", "jit",
                                                       "jax.pmap", "pmap")):
                    findings.append(self.finding(
                        ctx, node,
                        f"`{dotted_name(node.func)}(...)` constructed inside "
                        f"a loop: each iteration builds a fresh callable "
                        f"with an empty compile cache — hoist the jit out "
                        f"of the loop"))

        if not jitted:
            return findings
        for fn in [n for n in ast.walk(ctx.tree)
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                   and n.name in jitted]:
            params = _params_of(fn)
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While)):
                    if _test_trips_on_param(node.test, params):
                        kind = "if" if isinstance(node, ast.If) else "while"
                        findings.append(self.finding(
                            ctx, node,
                            f"python `{kind}` on a value derived from "
                            f"traced parameter(s) inside jitted "
                            f"`{fn.name}` — use jnp.where / lax.cond, or "
                            f"mark the argument static"))
                elif isinstance(node, ast.Call):
                    callee = dotted_name(node.func)
                    leaf = callee.rsplit(".", 1)[-1]
                    if ((callee in _NP_SYNC)
                            or (leaf in _HOST_SYNC_ATTRS
                                and isinstance(node.func, ast.Attribute))
                            or (callee in _HOST_SYNC_CALLS and node.args
                                and not isinstance(node.args[0],
                                                   ast.Constant))):
                        findings.append(self.finding(
                            ctx, node,
                            f"host sync `{callee}(...)` inside jitted "
                            f"`{fn.name}`: forces a device->host transfer "
                            f"per call (or fails to trace) — keep values "
                            f"on device or move the sync outside the jit"))
        return findings
