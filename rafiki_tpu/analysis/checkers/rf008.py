"""RF008 metric-name drift.

Perf-sentinel finding (docs/perf.md): SLO specs, the prom golden file
and dashboard queries all address telemetry series *by name string*.
A metric name built at the call site — an f-string, a ``"a" + b``
concatenation, a lowercase variable — can silently fork one logical
series into many (per-id cardinality explosions) or rename it out from
under every consumer; nothing fails, the SLO just stops seeing data.

The rule: the name argument to ``telemetry.inc`` / ``observe`` /
``set_gauge`` / ``add_gauge`` / ``span`` must be *statically known* —
a string literal, an UPPER_CASE registry constant (bare or dotted),
or a conditional between such values (the train loop's
``"train.cold_epoch_s" if cold else "train.epoch_s"`` split names two
literal series, not a dynamic one).

Genuinely bounded dynamic refinements (the gateway's per-reason shed
counters, the chaos plane's site×mode injection counters) stay legal
via justify-suppression — the justification is where "bounded" gets
argued. ``rafiki_tpu/telemetry/`` and ``rafiki_tpu/obs/`` are exempt:
they implement the registry this rule protects.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name

_EXEMPT_PREFIXES = ("rafiki_tpu.telemetry", "rafiki_tpu.obs")

#: Telemetry entry points whose first argument is a series name.
_METHODS = ("inc", "observe", "set_gauge", "add_gauge", "span")


def _metric_call_names(tree: ast.Module) -> Set[str]:
    """Dotted names that resolve to a telemetry name-taking entry point
    in this module — ``<alias>.<method>`` for module aliases, plus bare
    aliases from ``from rafiki_tpu.telemetry import inc [as x]``."""
    names: Set[str] = set()
    module_aliases: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module == "rafiki_tpu.telemetry":
                for a in node.names:
                    if a.name in _METHODS:
                        names.add(a.asname or a.name)
            elif node.module == "rafiki_tpu":
                for a in node.names:
                    if a.name == "telemetry":
                        module_aliases.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "rafiki_tpu.telemetry":
                    module_aliases.add(a.asname or a.name)
    for alias in module_aliases:
        for m in _METHODS:
            names.add(f"{alias}.{m}")
    return names


def _is_static_name(node: ast.AST) -> bool:
    """A statically-known series name: literal, UPPER_CASE constant
    (bare or as the final attribute of a dotted path), or an IfExp /
    BoolOp choosing between such values."""
    if isinstance(node, ast.Constant):
        return isinstance(node.value, str)
    if isinstance(node, ast.Name):
        return node.id.isupper()
    if isinstance(node, ast.Attribute):
        return node.attr.isupper()
    if isinstance(node, ast.IfExp):
        return _is_static_name(node.body) and _is_static_name(node.orelse)
    if isinstance(node, ast.BoolOp):
        return all(_is_static_name(v) for v in node.values)
    return False


def _describe(node: ast.AST) -> str:
    if isinstance(node, ast.JoinedStr):
        return "an f-string"
    if isinstance(node, ast.BinOp):
        return "a concatenation/expression"
    if isinstance(node, ast.Name):
        return f"the variable {node.id!r}"
    if isinstance(node, ast.Call):
        return "a call result"
    return "a dynamic expression"


@register
class MetricNameDrift(Checker):
    id = "RF008"
    name = "metric-name-drift"
    severity = "error"
    rationale = ("metric/span names built at the call site silently "
                 "fork or rename series out from under prom exposition, "
                 "the golden file and SLO specs — names must be string "
                 "literals or UPPER_CASE registry constants")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.module_name.startswith(_EXEMPT_PREFIXES):
            return []
        findings: List[Finding] = []
        call_names = _metric_call_names(ctx.tree)
        if not call_names:
            return []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = dotted_name(node.func)
            if fn not in call_names or not node.args:
                continue
            name_arg = node.args[0]
            if _is_static_name(name_arg):
                continue
            method = fn.rsplit(".", 1)[-1]
            findings.append(self.finding(
                ctx, name_arg,
                f"telemetry.{method} name is {_describe(name_arg)}: "
                "dynamic series names drift away from prom exposition "
                "and SLO specs — use a string literal or an UPPER_CASE "
                "constant, or justify-suppress a bounded refinement"))
        return findings
