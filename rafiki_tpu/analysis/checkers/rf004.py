"""RF004 unguarded-shared-mutation.

Failure class: the bus, the telemetry registry and the advisor service
are all mutated from many threads (trial workers, HTTP handlers,
heartbeat daemons). Each owns a lock — but a lock only helps when
every mutation of the shared dict/list state actually holds it, and a
method added later that skips the ``with self._lock:`` compiles, runs,
and corrupts state only under load.

Rule: in a class that assigns a lock attribute in ``__init__``
(``threading.Lock/RLock/Condition`` or a manager's ``.Lock()``), any
mutation of ``self.<attr>`` container state — subscript assignment,
``del``, augmented assignment, or a mutating method call
(``append``/``pop``/``update``/...) — outside a ``with self.<lock>:``
block is flagged. ``__init__``/``__getstate__``/``__setstate__`` are
exempt (construction and pickling are single-threaded by contract).
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name, is_self_attr

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}
_MUTATORS = {"append", "add", "extend", "update", "insert", "setdefault",
             "pop", "popitem", "clear", "remove", "discard", "appendleft",
             "extendleft", "popleft", "sort", "reverse"}
_EXEMPT_METHODS = {"__init__", "__getstate__", "__setstate__", "__del__",
                   "__reduce__", "__copy__", "__deepcopy__"}


def _lock_attrs(cls: ast.ClassDef) -> Set[str]:
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            name = dotted_name(node.value.func)
            if name.rsplit(".", 1)[-1] in _LOCK_CTORS:
                for t in node.targets:
                    attr = is_self_attr(t)
                    if attr:
                        attrs.add(attr)
    return attrs


class _MethodVisitor(ast.NodeVisitor):
    """Walks one method tracking whether a ``with self.<lock>:`` is
    held on the current path; nested functions are visited with the
    hold state of their definition site (threads started on unlocked
    nested fns are beyond static reach — the conservative choice)."""

    def __init__(self, checker: "UnguardedSharedMutation",
                 ctx: ModuleContext, lock_attrs: Set[str],
                 findings: List[Finding]):
        self.checker = checker
        self.ctx = ctx
        self.lock_attrs = lock_attrs
        self.findings = findings
        self.depth = 0  # nesting depth of held self-lock withs

    def _is_self_lock(self, expr: ast.AST) -> bool:
        return is_self_attr(expr, self.lock_attrs) is not None

    def visit_With(self, node: ast.With) -> None:
        held = any(self._is_self_lock(item.context_expr)
                   for item in node.items)
        if held:
            self.depth += 1
        self.generic_visit(node)
        if held:
            self.depth -= 1

    def _flag(self, node: ast.AST, attr: str, what: str) -> None:
        if self.depth == 0:
            self.findings.append(self.checker.finding(
                self.ctx, node,
                f"{what} of shared `self.{attr}` outside the class's lock "
                f"— every mutation in a lock-owning class must hold it "
                f"(wrap in `with self.{sorted(self.lock_attrs)[0]}:`)"))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                attr = is_self_attr(t.value)
                if attr:
                    self._flag(node, attr, "subscript assignment")
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        target: Optional[ast.AST] = node.target
        if isinstance(target, ast.Subscript):
            attr = is_self_attr(target.value)
            if attr:
                self._flag(node, attr, "augmented assignment")
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            if isinstance(t, ast.Subscript):
                attr = is_self_attr(t.value)
                if attr:
                    self._flag(node, attr, "del")
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in _MUTATORS:
            attr = is_self_attr(fn.value)
            if attr and attr not in self.lock_attrs:
                self._flag(node, attr, f".{fn.attr}()")
            # self.X[k].append(...) — mutation of a shared entry
            elif (isinstance(fn.value, ast.Subscript)):
                sub_attr = is_self_attr(fn.value.value)
                if sub_attr:
                    self._flag(node, sub_attr, f"[...] .{fn.attr}()")
        self.generic_visit(node)


@register
class UnguardedSharedMutation(Checker):
    id = "RF004"
    name = "unguarded-shared-mutation"
    severity = "warning"
    rationale = ("a lock-owning class mutating its shared dict/list state "
                 "without holding the lock corrupts state only under "
                 "load — bus/telemetry/advisor class of bug")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            lock_attrs = _lock_attrs(cls)
            if not lock_attrs:
                continue
            for item in cls.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                if item.name in _EXEMPT_METHODS:
                    continue
                _MethodVisitor(self, ctx, lock_attrs, findings).visit(item)
        return findings
