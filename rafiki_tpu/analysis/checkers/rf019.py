"""RF019 full-gather-hazard.

Sharded-lane finding (docs/sharding.md): a group-sharded train state
is the ONE pytree in the system deliberately too big for one host —
that is why the trial got a chip group in the first place. Any code
that materializes it whole (``jax.device_get``, ``np.asarray`` /
``np.array`` on the state or a loop bound to one) silently re-creates
the exact OOM the lane exists to avoid: it works in the CPU tests,
where the virtual chips share host RAM, and falls over on a real
topology at the worst width.

The sanctioned paths both live in ``rafiki_tpu/shard/checkpoint.py``:

* ``save_sharded`` — each shard writes only its local chunk bytes
  (``addressable_shards``), never the whole tree;
* ``gather_state`` — the one audited full gather, leaf-at-a-time, for
  the trial-completion hand-off into a serial loop.

Flagged, in any module except ``rafiki_tpu.shard.checkpoint`` itself:
a call to ``jax.device_get`` or ``numpy.asarray``/``numpy.array``
(under any import alias) whose argument is — or contains — group
state: a name bound to ``ShardedTrainLoop(...)`` or ``train_sharded
(...)``, or the ``.state`` attribute of one, or a name bound to that
attribute. Legitimate exceptions (a debug harness that truncates the
state first) justify-suppress, stating why the copy is bounded.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name

#: The one module allowed to flatten group state onto a host.
SANCTIONED_MODULE = "rafiki_tpu.shard.checkpoint"

#: Calls whose result carries group-sharded state.
STATE_SOURCES = frozenset({"ShardedTrainLoop", "train_sharded"})

#: (module prefix, function names) pairs that materialize an array on
#: the host.
_JAX_GATHERS = frozenset({"device_get"})
_NP_GATHERS = frozenset({"asarray", "array"})


def _hazard_names(tree: ast.Module) -> Set[str]:
    """Dotted call names that gather to host, under this module's
    import aliases — ``jax.device_get``, ``np.asarray``, a bare
    ``device_get`` imported from jax, ..."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                alias = a.asname or a.name
                if a.name == "jax":
                    names.update(f"{alias}.{g}" for g in _JAX_GATHERS)
                elif a.name in ("numpy", "jax.numpy"):
                    names.update(f"{alias}.{g}" for g in _NP_GATHERS)
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            for a in node.names:
                alias = a.asname or a.name
                if mod == "jax" and a.name in _JAX_GATHERS:
                    names.add(alias)
                elif mod in ("numpy", "jax.numpy") and (
                        a.name in _NP_GATHERS):
                    names.add(alias)
    return names


def _source_call(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call):
        return False
    name = dotted_name(value.func)
    return bool(name) and name.split(".")[-1] in STATE_SOURCES


def _tainted_names(tree: ast.Module) -> Set[str]:
    """Names bound to group state: loop handles from the source calls
    (first element of a ``loop, history = train_sharded(...)``
    unpack), plus names bound to a handle's ``.state``. Two passes in
    line order reach the ``st = loop.state`` one-hop chains a lint
    needs; deeper aliasing is out of scope."""
    tainted: Set[str] = set()
    assigns = [n for n in ast.walk(tree) if isinstance(n, ast.Assign)]
    for _ in range(2):
        for node in assigns:
            for t in node.targets:
                if _source_call(node.value):
                    if isinstance(t, ast.Name):
                        tainted.add(t.id)
                    elif (isinstance(t, ast.Tuple) and t.elts
                          and isinstance(t.elts[0], ast.Name)):
                        tainted.add(t.elts[0].id)
                elif (isinstance(t, ast.Name)
                      and _is_state_expr(node.value, tainted)):
                    tainted.add(t.id)
    return tainted


def _is_state_expr(node: ast.AST, tainted: Set[str]) -> bool:
    """``loop`` / ``loop.state`` / ``st`` for tainted bindings."""
    if isinstance(node, ast.Name):
        return node.id in tainted
    if isinstance(node, ast.Attribute) and node.attr == "state":
        return (isinstance(node.value, ast.Name)
                and node.value.id in tainted)
    return False


@register
class FullGatherHazard(Checker):
    id = "RF019"
    name = "full-gather-hazard"
    severity = "error"
    rationale = ("device_get/np.asarray of a group-sharded train "
                 "state materializes on one host the exact tree the "
                 "sharded lane exists to split — route it through "
                 "rafiki_tpu.shard.checkpoint (save_sharded chunk "
                 "manifests, or gather_state for the completion "
                 "hand-off), or justify-suppress stating why the "
                 "copy is bounded")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.module_name == SANCTIONED_MODULE:
            return []
        hazards = _hazard_names(ctx.tree)
        if not hazards:
            return []
        tainted = _tainted_names(ctx.tree)
        if not tainted:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name or name not in hazards:
                continue
            for arg in node.args:
                if any(_is_state_expr(sub, tainted)
                       for sub in ast.walk(arg)):
                    findings.append(self.finding(
                        ctx, node,
                        f"`{name}` gathers a group-sharded train "
                        f"state whole onto one host — the tree a "
                        f"sharded trial holds is sized for the GROUP, "
                        f"not a chip; use save_sharded's per-shard "
                        f"chunk manifests, or gather_state "
                        f"(rafiki_tpu.shard.checkpoint) for the "
                        f"completion hand-off"))
                    break
        return findings
