"""RF011 unjournaled-decision.

Search-anatomy finding (PR 12, docs/search_anatomy.md): the advisor
decision audit only works if EVERY engine journals its proposals and
feedback — ``obs sweep`` reconciles feedback records against propose
records and fails the whole sweep loudly when a decision escaped the
trail. A new advisor whose ``_propose``/``_feedback`` hook returns
without calling into ``rafiki_tpu.obs.search.audit`` (or the journal
directly) doesn't just lose its own telemetry: it turns every sweep
that uses it into a reconciliation failure, or — worse, if the hook
also skips the ledger — silently corrupts the effective-trials-per-
hour and regret numbers the capacity plane trends.

Flagged inside ``rafiki_tpu/advisor/`` only: a decision hook — any
function named ``_feedback`` or starting with ``_propose`` — whose
body never calls a name imported from ``rafiki_tpu.obs.journal`` or
``rafiki_tpu.obs.search*``. Abstract hooks (a body that only raises,
like ``BaseAdvisor._propose``) are exempt: they decide nothing.
Engines that inherit the base hooks are covered by the base's own
audit calls and define nothing for this rule to inspect.

Legitimate non-journaling hooks (a pure in-memory shim in tests, a
delegating wrapper whose inner engine journals) justify-suppress,
stating which layer carries the record.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name

#: The package whose audit contract this checker enforces.
SCOPE = "rafiki_tpu.advisor"

#: Imports from these module prefixes taint a local name as
#: "audit-capable": a call through any of them inside a hook counts
#: as journaling the decision.
AUDIT_MODULES = ("rafiki_tpu.obs.journal", "rafiki_tpu.obs.search")


def _audit_names(tree: ast.Module) -> Set[str]:
    """Local aliases bound to the journal/audit layer: the module
    object (``from rafiki_tpu.obs.search import audit [as x]``), a
    member (``from ...search.audit import record_propose``), or a
    plain dotted import (``import rafiki_tpu.obs.search.audit as a``)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith(AUDIT_MODULES):
                for a in node.names:
                    names.add(a.asname or a.name)
            elif mod in ("rafiki_tpu.obs", "rafiki_tpu.obs.search"):
                for a in node.names:
                    full = f"{mod}.{a.name}"
                    if full.startswith(AUDIT_MODULES):
                        names.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith(AUDIT_MODULES):
                    # `import rafiki_tpu.obs.search.audit` binds the
                    # top package; calls go through the full chain.
                    names.add(a.asname or a.name.split(".")[0])
    return names


def _is_decision_hook(fn: ast.AST) -> bool:
    return (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
            and (fn.name == "_feedback" or fn.name.startswith("_propose")))


def _body_sans_docstring(fn) -> List[ast.stmt]:
    body = list(fn.body)
    if (body and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)):
        body = body[1:]
    return body


def _calls_audit(fn, audit_names: Set[str]) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name and (name in audit_names
                     or name.split(".")[0] in audit_names):
            return True
    return False


@register
class UnjournaledDecision(Checker):
    id = "RF011"
    name = "unjournaled-decision"
    severity = "error"
    rationale = ("an advisor hook that proposes or ingests feedback "
                 "without journaling through rafiki_tpu.obs.search.audit "
                 "breaks `obs sweep` reconciliation for every sweep the "
                 "engine serves — call the audit helper, or "
                 "justify-suppress a layer whose inner engine journals")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.module_name.startswith(SCOPE):
            return []
        audit_names = _audit_names(ctx.tree)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not _is_decision_hook(node):
                continue
            body = _body_sans_docstring(node)
            if all(isinstance(s, ast.Raise) for s in body):
                continue  # abstract hook: decides nothing
            if not _calls_audit(node, audit_names):
                findings.append(self.finding(
                    ctx, node,
                    f"`{node.name}` makes a search decision without "
                    f"journaling it: no call into "
                    f"rafiki_tpu.obs.search.audit (or the journal) in "
                    f"its body, so `obs sweep` reconciliation will "
                    f"flag every trial this engine serves — emit "
                    f"audit.record_{'feedback' if node.name == '_feedback' else 'propose*'}"
                    f"(...) before returning"))
        return findings
