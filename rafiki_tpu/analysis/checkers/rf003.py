"""RF003 defaultdict-read-leak.

Historical bug (fixed in PR 1, bus/queues.py): ``InProcBus._workers``
was a ``defaultdict(set)`` and the *read* paths — ``heartbeat`` of a
removed worker, ``get_workers`` of a finished job — indexed it
directly, silently materializing an empty set per probed job id: a
slow, unbounded leak on any long-lived bus polled with rotating ids.

Rule: in a class that assigns ``self.X = defaultdict(...)``, a
Load-context subscript ``self.X[k]`` whose result is *not* immediately
mutated (``self.X[k].append(v)`` and friends are the intended
insert-on-first-use idiom) is a read that inserts — use
``self.X.get(k, default)`` instead, or switch to a plain dict.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import parent_map, is_self_attr

# mutating the subscripted entry in place = insertion is the point
_MUTATORS = {"append", "add", "extend", "update", "insert", "setdefault",
             "appendleft", "extendleft", "push", "put"}


def _defaultdict_attrs(cls: ast.ClassDef) -> Set[str]:
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            fn = value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            if name != "defaultdict":
                continue
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                attr = is_self_attr(t)
                if attr:
                    attrs.add(attr)
    return attrs


@register
class DefaultdictReadLeak(Checker):
    id = "RF003"
    name = "defaultdict-read-leak"
    severity = "warning"
    rationale = ("a Load subscript on a defaultdict attribute inserts on "
                 "miss — read paths leak one entry per probed key "
                 "(the PR-1 bus registry leak)")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            dd_attrs = _defaultdict_attrs(cls)
            if not dd_attrs:
                continue
            parents = parent_map(cls)
            for node in ast.walk(cls):
                if not (isinstance(node, ast.Subscript)
                        and isinstance(node.ctx, ast.Load)
                        and is_self_attr(node.value, dd_attrs)):
                    continue
                parent = parents.get(node)
                # self.X[k].append(v): Subscript -> Attribute(mutator) -> Call
                if (isinstance(parent, ast.Attribute)
                        and parent.attr in _MUTATORS
                        and isinstance(parents.get(parent), ast.Call)):
                    continue
                attr = is_self_attr(node.value, dd_attrs)
                findings.append(self.finding(
                    ctx, node,
                    f"read-side subscript of defaultdict attribute "
                    f"`self.{attr}` inserts an empty entry on every probed "
                    f"key (unbounded leak under rotating keys) — use "
                    f"`self.{attr}.get(...)` or a plain dict"))
        return findings
