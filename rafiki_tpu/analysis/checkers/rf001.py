"""RF001 entrypoint-platform-pin.

Historical bug (round 5): ``run_inference_worker_process`` was the one
jax-touching spawn entrypoint that never called
``honor_env_platform()`` — this image's sitecustomize force-registers
the TPU backend regardless of ``JAX_PLATFORMS``, so with the tunnel
down the spawned child hung in backend init forever and the serve-path
test burned its whole 120s registration deadline.

Rule: a *process entrypoint* (module-level ``main``/``serve``,
``run_*_process`` spawn targets, or an ``if __name__ == "__main__"``
block) in a module whose import closure reaches jax must call
``honor_env_platform()`` or ``force_cpu_backend()`` — directly, or via
another function in the same module (``bench.main`` pins through
``_init_backend``) — and the pin must lexically precede the first
direct ``jax.*`` use in that scope. A bare ``import jax`` before the
pin is fine: the hang is in backend *init*, which ``jax.config``
updates still preempt post-import.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional, Set, Tuple

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import (
    dotted_name, dunder_main_block, module_functions)

PIN_CALLS = {"honor_env_platform", "force_cpu_backend"}
ENTRYPOINT_NAME = re.compile(r"^(main|serve|run_\w*_process)$")


def _calls_in(nodes: Iterable[ast.AST]) -> List[ast.Call]:
    out: List[ast.Call] = []
    for n in nodes:
        out.extend(c for c in ast.walk(n) if isinstance(c, ast.Call))
    return out


def _pinning_functions(tree: ast.Module) -> Set[str]:
    """Module functions that (transitively, within this module) call a
    pin — covers bench.py's main -> _init_backend -> honor chain."""
    fns = {f.name: f for f in module_functions(tree)}
    pinning: Set[str] = set()
    changed = True
    while changed:
        changed = False
        for name, fn in fns.items():
            if name in pinning:
                continue
            for call in _calls_in(fn.body):
                target = dotted_name(call.func)
                leaf = target.rsplit(".", 1)[-1]
                if leaf in PIN_CALLS or target in pinning:
                    pinning.add(name)
                    changed = True
                    break
    return pinning


def _first_pin_line(body: List[ast.stmt], pinning: Set[str]) -> Optional[int]:
    lines = [call.lineno for call in _calls_in(body)
             if (lambda t: t.rsplit(".", 1)[-1] in PIN_CALLS or t in pinning)(
                 dotted_name(call.func))]
    return min(lines) if lines else None


def _first_jax_touch(body: List[ast.stmt]) -> Optional[Tuple[int, str]]:
    """First direct ``jax.<...>`` attribute use (``jax.devices()``,
    ``jax.distributed.initialize`` ...). Imports of jax don't count."""
    best: Optional[Tuple[int, str]] = None
    for n in body:
        for node in ast.walk(n):
            if isinstance(node, ast.Attribute):
                name = dotted_name(node)
                if name == "jax" or name.startswith("jax."):
                    if best is None or node.lineno < best[0]:
                        best = (node.lineno, name)
    return best


@register
class EntrypointPlatformPin(Checker):
    id = "RF001"
    name = "entrypoint-platform-pin"
    severity = "error"
    rationale = ("jax-touching process entrypoints must pin the backend "
                 "(honor_env_platform) before first jax use — a spawned "
                 "child that skips it hangs in TPU backend init when the "
                 "tunnel is down")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.project.is_jax_tainted(ctx.module_name):
            return []
        pinning = _pinning_functions(ctx.tree)
        scopes: List[Tuple[str, List[ast.stmt], ast.AST]] = []
        for fn in module_functions(ctx.tree):
            if ENTRYPOINT_NAME.match(fn.name):
                scopes.append((fn.name, fn.body, fn))
        main_block = dunder_main_block(ctx.tree)
        if main_block is not None:
            scopes.append(('__main__ block', main_block.body, main_block))

        findings = []
        for label, body, node in scopes:
            pin_line = _first_pin_line(body, pinning)
            touch = _first_jax_touch(body)
            if pin_line is None:
                findings.append(self.finding(
                    ctx, node,
                    f"entrypoint `{label}` of jax-importing module "
                    f"{ctx.module_name} never pins the platform: call "
                    f"honor_env_platform() (utils.backend) before any jax "
                    f"touch, or the spawned process hangs in TPU backend "
                    f"init when the tunnel is down"))
            elif touch is not None and touch[0] < pin_line:
                findings.append(self.finding(
                    ctx, node,
                    f"entrypoint `{label}` touches `{touch[1]}` at line "
                    f"{touch[0]} before the platform pin at line {pin_line} "
                    f"— move honor_env_platform() ahead of the first jax "
                    f"use"))
        return findings
