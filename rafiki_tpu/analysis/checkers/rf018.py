"""RF018 unaudited-speculation.

Curve-advisor finding (PR 19, docs/early_kill.md): the speculative-
scoring plane keeps the GP's training rows honest through exactly
three audited surfaces — ``_feedback`` (real score), ``_speculate``
(predicted score, journaled so crash-resume can replay it), and
``_correct`` (in-place replacement when the real score lands). A
mutation of the GP training data (``self._X`` / ``self._y``) anywhere
else bypasses the journal: the advisor's posterior diverges from what
``advisor/*`` records can reconstruct, and the PR 15 rehydration
contract (byte-identical post-resume proposals) silently breaks — the
worst kind of break, because nothing fails until a crash-resume
produces different knobs than the unfaulted run would have.

Same story for kill decisions: a function that marks a trial killed
without a lexically-reachable call into the audit layer produces a
kill ``obs sweep`` cannot reconcile and ``search.kills`` never counts.

Flagged inside ``rafiki_tpu/advisor/`` only:

* a function outside the sanctioned surfaces (``__init__``,
  ``_feedback``, ``_speculate``, ``_correct``, ``_propose_batch``,
  ``_fit``) that mutates an attribute named ``_X`` or ``_y`` —
  assignment, ``del``, augmented assignment, subscript store, or a
  mutating method call (``append``/``pop``/``extend``/...);
* a non-abstract function whose name contains ``kill`` that mutates
  state (attribute or subscript store — i.e. it *decides*, it is not
  a pure predicate like ``KillConfig.should_kill``) without calling a
  name imported from ``rafiki_tpu.obs.journal`` or
  ``rafiki_tpu.obs.search*``.

Legitimate exceptions (a test shim, a migration helper that rebuilds
rows from the journal itself) justify-suppress, stating which journal
records make the mutation replayable.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name

#: The package whose speculation contract this checker enforces.
SCOPE = "rafiki_tpu.advisor"

#: Imports from these module prefixes taint a local name as
#: "audit-capable" (same rule as RF011).
AUDIT_MODULES = ("rafiki_tpu.obs.journal", "rafiki_tpu.obs.search")

#: The only functions allowed to touch the GP training rows. Everything
#: here either journals the mutation itself or (constant-liar batch,
#: ``_fit``) operates on rows a journaled surface planted.
TRAINING_DATA_SURFACES = frozenset({
    "__init__", "_feedback", "_speculate", "_correct",
    "_propose_batch", "_fit",
})

#: Attribute names that hold GP training data.
TRAINING_ATTRS = frozenset({"_X", "_y"})

#: List/dict methods that mutate their receiver.
MUTATING_METHODS = frozenset({
    "append", "extend", "insert", "pop", "remove", "clear",
    "setdefault", "update",
})


def _audit_names(tree: ast.Module) -> Set[str]:
    """Local aliases bound to the journal/audit layer (RF011's rule)."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith(AUDIT_MODULES):
                for a in node.names:
                    names.add(a.asname or a.name)
            elif mod in ("rafiki_tpu.obs", "rafiki_tpu.obs.search"):
                for a in node.names:
                    full = f"{mod}.{a.name}"
                    if full.startswith(AUDIT_MODULES):
                        names.add(a.asname or a.name)
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith(AUDIT_MODULES):
                    names.add(a.asname or a.name.split(".")[0])
    return names


def _is_training_attr(node: ast.AST) -> bool:
    """``<anything>._X`` / ``<anything>._y`` (typically ``self.``)."""
    return isinstance(node, ast.Attribute) and node.attr in TRAINING_ATTRS


def _mutates_training_data(fn) -> List[ast.AST]:
    """Statements in ``fn`` that mutate a ``_X``/``_y`` attribute."""
    hits: List[ast.AST] = []
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if _is_training_attr(t):
                    hits.append(node)
                elif (isinstance(t, ast.Subscript)
                      and _is_training_attr(t.value)):
                    hits.append(node)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if _is_training_attr(t) or (
                        isinstance(t, ast.Subscript)
                        and _is_training_attr(t.value)):
                    hits.append(node)
        elif isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.attr in MUTATING_METHODS
                    and _is_training_attr(f.value)):
                hits.append(node)
    return hits


def _mutates_state(fn) -> bool:
    """Any attribute or subscript store — the line between a kill
    *decision* (marks something killed) and a pure predicate."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    return True
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, (ast.Attribute, ast.Subscript)):
                    return True
    return False


def _body_sans_docstring(fn) -> List[ast.stmt]:
    body = list(fn.body)
    if (body and isinstance(body[0], ast.Expr)
            and isinstance(body[0].value, ast.Constant)
            and isinstance(body[0].value.value, str)):
        body = body[1:]
    return body


def _calls_audit(fn, audit_names: Set[str]) -> bool:
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name and (name in audit_names
                     or name.split(".")[0] in audit_names):
            return True
    return False


def _functions(tree: ast.Module):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


@register
class UnauditedSpeculation(Checker):
    id = "RF018"
    name = "unaudited-speculation"
    severity = "error"
    rationale = ("GP training rows mutated outside the journaled "
                 "feedback/speculate/correct surfaces, or a kill "
                 "decision with no reachable audit call, break the "
                 "crash-resume byte-identity contract — route the "
                 "mutation through a sanctioned surface, or "
                 "justify-suppress naming the journal records that "
                 "make it replayable")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.module_name.startswith(SCOPE):
            return []
        audit_names = _audit_names(ctx.tree)
        findings: List[Finding] = []
        for fn in _functions(ctx.tree):
            body = _body_sans_docstring(fn)
            if all(isinstance(s, (ast.Raise, ast.Pass)) for s in body):
                continue  # abstract hook: decides nothing
            if fn.name not in TRAINING_DATA_SURFACES:
                for hit in _mutates_training_data(fn):
                    findings.append(self.finding(
                        ctx, hit,
                        f"`{fn.name}` mutates GP training data "
                        f"(`_X`/`_y`) outside the journaled surfaces "
                        f"({', '.join(sorted(TRAINING_DATA_SURFACES))})"
                        f" — the posterior diverges from what "
                        f"`advisor/*` records can replay, breaking "
                        f"crash-resume byte-identity; route it through "
                        f"_feedback/_speculate/_correct"))
            if ("kill" in fn.name and _mutates_state(fn)
                    and not _calls_audit(fn, audit_names)):
                findings.append(self.finding(
                    ctx, fn,
                    f"`{fn.name}` decides a kill (mutates state) with "
                    f"no lexically-reachable call into "
                    f"rafiki_tpu.obs.search.audit — the kill never "
                    f"reaches the journal, `obs sweep` cannot "
                    f"reconcile it and `search.kills` undercounts; "
                    f"call audit.record_kill(...) at the decision "
                    f"site"))
        return findings
