"""RF006 swallowed-interrupt.

Chaos-plane finding (PR 5): recovery depends on signals ACTUALLY
propagating. A supervise/worker loop that wraps its body in a broad
``except`` and keeps looping eats ``KeyboardInterrupt``/``SystemExit``
(both ``BaseException``) — the process becomes unkillable short of
SIGKILL, drains never finish, and a simulated preemption's SIGTERM
grace expires into a hard kill. The injected faults that exposed this
class: ``scheduler.preempt:term`` against a worker whose loop caught
``BaseException``.

Two tiers:

* **error** — any handler whose clause catches ``BaseException``
  (bare ``except:``, ``except BaseException``, or a tuple naming
  ``BaseException``/``KeyboardInterrupt``/``SystemExit``) and whose
  body neither re-raises nor exits (``return``/``break``/
  ``sys.exit``/``os._exit``). Catching the interrupt hierarchy is
  only ever legitimate as catch-log-REraise.
* **warning** — an ``except Exception`` handler whose body is nothing
  but ``pass``/``continue``, directly inside a ``while`` loop of a
  long-running-loop function (``run``/``serve``/``supervise``/
  ``recover*``/``watch*``/``main``/``*_loop``/``*_beat``): silent
  swallow-and-keep-looping hides every failure the loop will ever
  have, including the chaos plane's injected ones. Log, count, or
  justify with an inline suppression.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name, parent_map

_BASE_NAMES = {"BaseException", "KeyboardInterrupt", "SystemExit",
               "GeneratorExit"}

_LOOP_FN_RE = re.compile(
    r"^(run|serve|supervise|main|recover\w*|watch\w*|\w*_loop|\w*_beat)$")

_EXIT_CALLS = {"sys.exit", "os._exit", "os.abort"}


def _clause_names(handler: ast.ExceptHandler) -> List[str]:
    """Exception names a handler clause catches ('' for bare except)."""
    t = handler.type
    if t is None:
        return [""]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return [dotted_name(e).rsplit(".", 1)[-1] for e in elts]


def _catches_interrupts(handler: ast.ExceptHandler) -> bool:
    return any(n == "" or n in _BASE_NAMES for n in _clause_names(handler))


def _body_escapes(handler: ast.ExceptHandler) -> bool:
    """Does the handler body re-raise or exit (vs. swallow and carry
    on)? Conservative: any raise/return/break anywhere in the body
    counts — conditional re-raise is the catch-log-reraise idiom."""
    for node in ast.walk(handler):
        if isinstance(node, (ast.Raise, ast.Return, ast.Break)):
            return True
        if isinstance(node, ast.Call) and dotted_name(node.func) in _EXIT_CALLS:
            return True
    return False


def _is_silent_swallow(handler: ast.ExceptHandler) -> bool:
    """Body is nothing but pass/continue (and a docstring-less spine):
    the failure leaves no trace at all."""
    return all(isinstance(s, (ast.Pass, ast.Continue)) for s in handler.body)


def _enclosing_function(node: ast.AST, parents) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = parents.get(cur)
    return None


def _inside_while(node: ast.AST, parents, stop_at: ast.AST) -> bool:
    """Is ``node`` (a Try) directly in a while loop's body, walking up
    no further than the enclosing function?"""
    cur = parents.get(node)
    while cur is not None and cur is not stop_at:
        if isinstance(cur, ast.While):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return False
        cur = parents.get(cur)
    return False


@register
class SwallowedInterrupt(Checker):
    id = "RF006"
    name = "swallowed-interrupt"
    severity = "error"
    rationale = ("a broad except that neither re-raises nor exits eats "
                 "KeyboardInterrupt/SystemExit — supervise and worker "
                 "loops become unkillable and recovery paths never run")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        parents = parent_map(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            fn = _enclosing_function(node, parents)
            for handler in node.handlers:
                if _catches_interrupts(handler):
                    if not _body_escapes(handler):
                        clause = ", ".join(n or "bare except"
                                           for n in _clause_names(handler))
                        findings.append(self.finding(
                            ctx, handler,
                            f"handler for `{clause}` swallows the "
                            f"interrupt hierarchy (no re-raise, no "
                            f"return/break/exit) — Ctrl-C, SystemExit and "
                            f"preemption SIGTERM handlers die here; "
                            f"re-raise after cleanup or narrow to "
                            f"Exception"))
                    continue
                if (fn is not None
                        and _LOOP_FN_RE.match(fn.name)
                        and "Exception" in _clause_names(handler)
                        and _is_silent_swallow(handler)
                        and _inside_while(node, parents, fn)):
                    findings.append(self.finding(
                        ctx, handler,
                        f"`except Exception: pass` inside `{fn.name}`'s "
                        f"while loop swallows every failure silently — "
                        f"a long-running loop must log/count what it "
                        f"absorbs (or justify-suppress)",
                        severity="warning"))
        return findings
