"""RF013 undurable-decision.

Crash-safety contract (PR 15, docs/recovery.md): the sweep control
plane is only resumable because every budget-consuming or
work-assigning mutation it makes is preceded by a durable, fsynced
WAL ``intent()`` record — ``resume_sweep`` reconciles the WAL against
the MetaStore to prove "every slot claimed exactly once" before a
fresh process adopts a dead supervisor's job. A scheduler code path
that claims a trial row (``store.create_trial``) or assigns pack work
to a chip (``tasks.put(("pack", ...))`` / ``tasks.put(("resume",
...))``) WITHOUT an intent first is invisible to that reconciliation:
a crash between the bare mutation and completion leaves a row no WAL
claim covers, and resume refuses the whole job (``unlogged_claim``).

Flagged inside ``rafiki_tpu/scheduler/`` only: a function that calls
one of the mutating operations with no lexically preceding ``intent(``
call in the same function. The guarded-WAL idiom (``txn = None if wal
is None else wal.intent(...)``) counts — the intent call is present;
whether it runs is the degraded no-WAL mode recovery handles loudly.

Legitimate undurable mutations (a test double, a path the WAL covers
one frame up) justify-suppress with ``# lint: disable=RF013 — why``,
stating which layer writes the intent.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Optional, Tuple

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register

#: The package whose durability contract this checker enforces.
SCOPE = "rafiki_tpu.scheduler"

#: Method names that claim a budget slot when called on anything.
CLAIMING_ATTRS = ("create_trial",)

#: First elements of a task tuple whose ``.put()`` assigns chip work.
ASSIGNING_TASKS = ("pack", "resume")


def _own_nodes(fn: ast.AST) -> Iterable[ast.AST]:
    """Walk a function's body WITHOUT descending into nested function
    definitions — a closure is its own durability scope (it is flagged
    separately when it mutates without an intent of its own)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _mutation(node: ast.AST) -> Optional[Tuple[str, ast.Call]]:
    """(description, call) when ``node`` is a durable-decision mutation."""
    if not isinstance(node, ast.Call):
        return None
    func = node.func
    if not isinstance(func, ast.Attribute):
        return None
    if func.attr in CLAIMING_ATTRS:
        return f"`.{func.attr}(...)` (budget claim)", node
    if func.attr == "put" and node.args:
        arg = node.args[0]
        if (isinstance(arg, ast.Tuple) and arg.elts
                and isinstance(arg.elts[0], ast.Constant)
                and arg.elts[0].value in ASSIGNING_TASKS):
            return (f'`.put(("{arg.elts[0].value}", ...))` '
                    f"(pack assignment)", node)
    return None


def _is_intent_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr == "intent"
    return isinstance(func, ast.Name) and func.id == "intent"


@register
class UndurableDecision(Checker):
    id = "RF013"
    name = "undurable-decision"
    severity = "error"
    rationale = ("a scheduler mutation (trial claim, pack assignment) "
                 "with no preceding WAL intent() in the same function "
                 "is invisible to resume_sweep's WAL-vs-store "
                 "reconciliation — a crash around it strands the job "
                 "unresumable (`unlogged_claim`); write the intent "
                 "first, or justify-suppress naming the layer that does")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not ctx.module_name.startswith(SCOPE):
            return []
        findings: List[Finding] = []
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            first_intent = None
            for node in _own_nodes(fn):
                if _is_intent_call(node):
                    line = getattr(node, "lineno", None)
                    if line is not None and (first_intent is None
                                             or line < first_intent):
                        first_intent = line
            for node in _own_nodes(fn):
                mut = _mutation(node)
                if mut is None:
                    continue
                desc, call = mut
                if first_intent is None or call.lineno < first_intent:
                    findings.append(self.finding(
                        ctx, call,
                        f"`{fn.name}` executes {desc} with no WAL "
                        f"`intent(...)` written first in this function "
                        f"— the mutation is undurable, and a crash "
                        f"around it makes the job unresumable "
                        f"(resume_sweep reconciliation reports "
                        f"`unlogged_claim`); log the intent before "
                        f"mutating"))
        return findings
