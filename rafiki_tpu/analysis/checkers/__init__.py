"""Built-in checkers, one module per id.

Every module in this package is imported by
``rafiki_tpu.analysis.core.load_builtin_checkers`` (pkgutil walk) and
registers its checker class on import — dropping a new ``rf00x.py``
here IS the plugin mechanism; nothing else to wire up.
"""
