"""Small AST helpers shared by the checkers (not a checker itself —
the plugin loader imports it harmlessly; it registers nothing)."""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set


class LineNode:
    """Line-only stand-in for ``Checker.finding`` when a finding is
    derived from a cross-file join rather than a node in hand."""

    def __init__(self, line: int):
        self.lineno = line
        self.col_offset = 0


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def call_name(node: ast.Call) -> str:
    """Dotted name of a call target: ``jax.jit`` for jax.jit(...),
    ``f`` for f(...); "" when the callee isn't a plain name chain."""
    return dotted_name(node.func)


def dotted_name(node: ast.AST) -> str:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def is_self_attr(node: ast.AST, attrs: Optional[Set[str]] = None) -> Optional[str]:
    """If ``node`` is ``self.<attr>`` (optionally restricted to
    ``attrs``), return the attr name."""
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and (attrs is None or node.attr in attrs)):
        return node.attr
    return None


def module_functions(tree: ast.Module) -> Iterator[ast.FunctionDef]:
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def dunder_main_block(tree: ast.Module) -> Optional[ast.If]:
    """The module's ``if __name__ == "__main__":`` statement, if any."""
    for node in tree.body:
        if not isinstance(node, ast.If):
            continue
        t = node.test
        if (isinstance(t, ast.Compare) and len(t.ops) == 1
                and isinstance(t.ops[0], ast.Eq)
                and isinstance(t.left, ast.Name) and t.left.id == "__name__"
                and len(t.comparators) == 1
                and isinstance(t.comparators[0], ast.Constant)
                and t.comparators[0].value == "__main__"):
            return node
    return None
