"""RF014 journal-kind-contract.

The journal is the only cross-process transcript this system has: the
twin calibrators, sweep reconstruction, advisor rehydration, and chaos
invariant checks all join on ``kind/name`` string pairs that nothing
type-checks. A renamed kind fails *silently* — the writer keeps
writing, the reader's filter matches nothing, and the downstream tool
reports "no data" instead of "contract broken". (The twin calibrator
grew its fail-loud ``REQUIRED_KINDS`` list for exactly this reason;
RF014 generalizes that guard to every reader in the tree.)

Two polarities, one whole-program join
(:mod:`rafiki_tpu.analysis.contracts.journal`):

* **unknown** (error, at the reader site) — a reader expects a
  kind/name no writer emits. This is the loud side of a rename in
  EITHER direction: rename the writer and the old reader expectation
  dangles; rename the reader and the new expectation dangles. The
  message names the kind and the closest writer key with its site, so
  the rename is diagnosable from the finding alone.
* **unread** (warning, at the writer site) — a kind/name is written
  but no reader consumes it by pair, by kind-wholesale filter, or (for
  dynamic-name writers) by kind. Write-only forensic streams are
  legitimate — suppress with a why naming the out-of-band consumer.

Readers over record streams that are NOT the journal (a CLI's JSON
output, a metastore row) are indistinguishable statically — suppress
at the reader site stating the actual source.
"""

from __future__ import annotations

import difflib
from typing import Dict, Iterable, List, Set, Tuple

from rafiki_tpu.analysis.checkers._ast_util import LineNode
from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.contracts import journal_contracts
from rafiki_tpu.analysis.contracts.journal import (
    unknown_reader_keys, unread_writer_keys)


def _closest(key: str, candidates: Dict[str, list]) -> str:
    match = difflib.get_close_matches(key, sorted(candidates), n=1,
                                      cutoff=0.6)
    if not match:
        return ""
    sites = candidates[match[0]]
    first = min(sites, key=lambda s: (s.path, s.line))
    return (f"; closest existing key is '{match[0]}' "
            f"({first.path}:{first.line}) — renamed?")


@register
class JournalKindContract(Checker):
    id = "RF014"
    name = "journal-kind-contract"
    severity = "error"
    rationale = ("a renamed journal kind fails silently: the writer "
                 "keeps writing, the reader matches nothing")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        jc = journal_contracts(ctx.project)
        unknown: Set[str] = set(unknown_reader_keys(jc))
        unread: Set[str] = set(unread_writer_keys(jc))
        writer_pairs = jc.writer_pairs()
        reader_pairs = jc.reader_pairs()
        seen: Set[Tuple[int, str]] = set()
        out: List[Finding] = []
        for r in jc.readers:
            if r.path != ctx.path or r.key not in unknown:
                continue
            if (r.line, r.key) in seen:
                continue
            seen.add((r.line, r.key))
            out.append(self.finding(
                ctx, LineNode(r.line),
                f"reader expects journal kind '{r.key}' "
                f"({r.source}) but no writer emits it"
                + _closest(r.key, writer_pairs)))
        for w in jc.writers:
            key = w.key
            if w.path != ctx.path or key not in unread:
                continue
            if (w.line, key) in seen:
                continue
            seen.add((w.line, key))
            out.append(self.finding(
                ctx, LineNode(w.line),
                f"journal kind '{key}' is written here but no reader "
                f"consumes it" + _closest(key, reader_pairs)
                + " (add a reader, drop the writer, or suppress "
                  "naming the out-of-band consumer)",
                severity="warning"))
        return out
