"""RF010 nondeterministic-sim.

Digital-twin finding (PR 11, docs/twin.md): the twin's contract is
that one seed reproduces a simulation bit-for-bit — the validation
gate, the chaos pre-gate and the fleet search all hash event logs and
diff reruns, so ONE ambient-entropy read anywhere in
``rafiki_tpu/obs/twin/`` — the serving twin AND the ``train/``
subpackage (PR 16), whose sweep simulator makes the same bit-identical
replay promise — silently voids every downstream guarantee.
The failure is nasty precisely because it's invisible: the sim still
runs, the numbers still look plausible, and the nondeterminism only
surfaces as an unreproducible validation flake weeks later.

Flagged inside the twin package only:

* ``random.Random()`` with no arguments — OS-entropy seeding;
* module-level ``random.<fn>()`` calls (``random.random()``,
  ``random.randrange(...)``, …) — the shared global RNG, whose state
  any other import can perturb;
* clock reads: ``time.time/monotonic/perf_counter/…``,
  ``datetime.datetime.now/utcnow``, ``datetime.date.today`` — wall or
  process time leaking into simulated time.

Method calls on an explicitly seeded instance (``self.rng.random()``,
``rng.randrange(n)``) are the sanctioned pattern and are not flagged.
Legitimate ambient reads — e.g. a wall timestamp stamped onto an
artifact as metadata, never fed back into the simulation — justify-
suppress, stating what keeps the value out of the sim state.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name

#: The package whose determinism contract this checker enforces.
SCOPE = "rafiki_tpu.obs.twin"

#: Ambient clock reads (dotted call names).
CLOCK_CALLS = frozenset({
    "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time", "time.time_ns", "time.monotonic_ns",
    "time.perf_counter_ns", "datetime.datetime.now",
    "datetime.datetime.utcnow", "datetime.date.today",
    "datetime.now", "datetime.utcnow", "date.today",
})

#: Module-level functions of the global `random` RNG. Any
#: ``random.<fn>(...)`` call is shared-state; the seeded-instance
#: methods (``rng.<fn>()``) don't match because their dotted name
#: starts with the instance variable, not the module.
GLOBAL_RANDOM_PREFIX = "random."


@register
class NondeterministicSim(Checker):
    id = "RF010"
    name = "nondeterministic-sim"
    severity = "error"
    rationale = ("the twin's replay/validation guarantees hash event "
                 "logs across reruns: unseeded RNG or ambient clock "
                 "reads inside rafiki_tpu/obs/twin/ void determinism "
                 "invisibly — thread a random.Random(seed) through, or "
                 "justify-suppress metadata-only wall stamps")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if SCOPE not in ctx.module_name:
            return []
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if name == "random.Random" and not node.args:
                findings.append(self.finding(
                    ctx, node,
                    "`random.Random()` with no seed draws OS entropy: "
                    "the twin's bit-identical-replay contract needs "
                    "every stream seeded (random.Random(seed) or a "
                    "derived f\"{seed}:stream\" key)"))
            elif (name.startswith(GLOBAL_RANDOM_PREFIX)
                    and name != "random.Random"
                    and name.count(".") == 1):
                findings.append(self.finding(
                    ctx, node,
                    f"`{name}(...)` uses the GLOBAL random stream — any "
                    f"other import can perturb its state between runs; "
                    f"call methods on an explicitly seeded "
                    f"random.Random instance instead"))
            elif name in CLOCK_CALLS:
                findings.append(self.finding(
                    ctx, node,
                    f"`{name}()` reads an ambient clock inside the twin "
                    f"package: simulated time must come from the event "
                    f"heap, not the host — or justify-suppress a "
                    f"metadata-only artifact timestamp"))
        return findings
