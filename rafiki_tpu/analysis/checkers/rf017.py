"""RF017 unbounded-per-tenant-state.

Multi-tenant serving keeps per-tenant ledgers everywhere — admission
slots, accounting stats, residency charges. Tenant ids arrive off the
wire (an HTTP header the gateway forwards verbatim), so any long-lived
mapping keyed by tenant id grows one entry per id EVER probed: a
client rotating ids is an unbounded memory leak in the serving plane.
This is RF003's defaultdict-read-leak generalized to the write side —
inserting per-key state on the request path leaks exactly the same
way whether the insert came from a read or a write.

Rule: in a tenancy-touching module (under ``rafiki_tpu/tenancy/`` or
importing ``rafiki_tpu.tenancy``), a class attribute initialized as a
bare ``{}``/``dict()``/``defaultdict()``/``OrderedDict()`` and written
with a tenant-derived key (``self.X[tenant] = ...`` or
``self.X.setdefault(tenant, ...)``) must show eviction somewhere in
the same class: a ``pop``/``popitem``/``clear`` on the attribute, a
``del self.X[...]``, or a ``len(self.X)`` cap check. The sanctioned
idiom is :class:`rafiki_tpu.tenancy.accounting.BoundedTenantMap`
(LRU cap + an eviction counter), which never matches because it is
not a bare dict.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from rafiki_tpu.analysis.checkers._ast_util import is_self_attr
from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register

_DICT_CTORS = {"dict", "defaultdict", "OrderedDict"}
_EVICTORS = {"pop", "popitem", "clear"}


def _tenancy_scoped(ctx: ModuleContext) -> bool:
    if ctx.module_name.startswith("rafiki_tpu.tenancy"):
        return True
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Import):
            if any(a.name.startswith("rafiki_tpu.tenancy")
                   for a in node.names):
                return True
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith("rafiki_tpu.tenancy"):
                return True
            if mod == "rafiki_tpu" and any(a.name == "tenancy"
                                           for a in node.names):
                return True
    return False


def _dict_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes assigned a bare dict-like container anywhere in the
    class (a BoundedTenantMap assignment deliberately never matches)."""
    attrs: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        is_dict = isinstance(value, ast.Dict)
        if isinstance(value, ast.Call):
            fn = value.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            is_dict = name in _DICT_CTORS
        if not is_dict:
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        for t in targets:
            attr = is_self_attr(t)
            if attr:
                attrs.add(attr)
    return attrs


def _mentions_tenant(node: ast.AST) -> bool:
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "tenant" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "tenant" in n.attr.lower():
            return True
    return False


def _bounded_attrs(cls: ast.ClassDef, attrs: Set[str]) -> Set[str]:
    """Attributes the class demonstrably evicts from or caps."""
    out: Set[str] = set()
    for node in ast.walk(cls):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _EVICTORS):
            a = is_self_attr(node.func.value, attrs)
            if a:
                out.add(a)
        elif isinstance(node, ast.Delete):
            for t in node.targets:
                if isinstance(t, ast.Subscript):
                    a = is_self_attr(t.value, attrs)
                    if a:
                        out.add(a)
        elif (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "len" and node.args):
            a = is_self_attr(node.args[0], attrs)
            if a:
                out.add(a)
    return out


@register
class UnboundedPerTenantState(Checker):
    id = "RF017"
    name = "unbounded-per-tenant-state"
    severity = "warning"
    rationale = ("tenant ids arrive off the wire: a dict keyed by them "
                 "without eviction grows one entry per id ever probed — "
                 "an unbounded leak under rotating ids (RF003's leak, "
                 "write side)")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if not _tenancy_scoped(ctx):
            return []
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(ctx.tree)
                    if isinstance(n, ast.ClassDef)]:
            attrs = _dict_attrs(cls)
            if not attrs:
                continue
            bounded = _bounded_attrs(cls, attrs)
            for node in ast.walk(cls):
                if (isinstance(node, ast.Subscript)
                        and isinstance(node.ctx, ast.Store)):
                    attr = is_self_attr(node.value, attrs)
                    if (attr and attr not in bounded
                            and _mentions_tenant(node.slice)):
                        findings.append(self._leak(ctx, node, attr))
                elif (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr == "setdefault"):
                    attr = is_self_attr(node.func.value, attrs)
                    if (attr and attr not in bounded and node.args
                            and _mentions_tenant(node.args[0])):
                        findings.append(self._leak(ctx, node, attr))
        return findings

    def _leak(self, ctx: ModuleContext, node: ast.AST, attr: str) -> Finding:
        return self.finding(
            ctx, node,
            f"tenant-keyed write into `self.{attr}` with no eviction "
            f"anywhere in the class — wire-supplied tenant ids make "
            f"this an unbounded leak; cap it (pop/len check) or use "
            f"tenancy.accounting.BoundedTenantMap")
