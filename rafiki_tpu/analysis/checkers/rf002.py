"""RF002 platform-literal-gate.

Historical bug (round 5, bench.py:607): the bench's MFU fields were
gated on ``platform == "tpu"``, but this image's PJRT plugin registers
the TPU as platform ``"axon"`` — every green-window run silently
reported ``mfu: null`` and the window's evidence was lost.

Rule: never equality-compare a platform string against the literal
``"tpu"``. The robust gates are ``platform != "cpu"`` (anything that
isn't the host is an accelerator) or a device_kind check
(``"TPU" in jax.devices()[0].device_kind``).
"""

from __future__ import annotations

import ast
from typing import Iterable

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register


@register
class PlatformLiteralGate(Checker):
    id = "RF002"
    name = "platform-literal-gate"
    severity = "error"
    rationale = ('`== "tpu"` misses TPU-backed platforms with other PJRT '
                 'names (this image registers "axon") — gate on != "cpu" '
                 'or device_kind instead')

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Compare):
                continue
            sides = [node.left] + list(node.comparators)
            has_tpu_literal = any(
                # lint: disable=RF002 — the checker must name the literal it hunts
                isinstance(s, ast.Constant) and s.value == "tpu"
                for s in sides)
            if not has_tpu_literal:
                continue
            if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
                continue
            findings.append(self.finding(
                ctx, node,
                'platform compared against the literal "tpu": TPU-backed '
                'PJRT plugins register other names (this image: "axon"), '
                'so the gate silently takes the wrong branch on real '
                'hardware — use != "cpu" or check device_kind for "TPU"'))
        return findings
