"""RF009 wall-clock-duration.

Request-anatomy finding (PR 10, docs/serving_anatomy.md): latency and
duration math must run on the monotonic clock. ``time.time()`` is the
WALL clock — NTP slews it continuously and steps it discontinuously,
so a ``time.time() - start`` delta can be wrong by the full step (and
even negative), silently corrupting latencies, lease math, SLO inputs
and the hop marks the serving waterfall subtracts across processes.
``time.monotonic()`` exists for exactly this subtraction — and on
Linux ``CLOCK_MONOTONIC`` is system-wide, so it also covers the
cross-process hop-mark case.

The flagged shape is ``time.time() - <anything>``: a call on the LEFT
of a subtraction reads as "now minus an earlier instant", i.e. an
elapsed duration. The converse shapes stay legal:

* ``deadline - time.time()`` — a remaining-budget read against a
  wall-clock deadline (mirrors RF007's documented exception);
* ``t0 = time.time()`` alone — a timestamp, not a delta; journals and
  artifacts legitimately carry wall timestamps.

Legitimate wall-clock deltas exist — epoch cutoffs compared against
timestamps persisted across restarts, or beats shared between
processes on a wall basis — and those justify-suppress, stating WHY
the wall clock is the shared clock there.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name


@register
class WallClockDuration(Checker):
    id = "RF009"
    name = "wall-clock-duration"
    severity = "error"
    rationale = ("`time.time() - x` measures a duration on the wall "
                 "clock: NTP slew/steps corrupt latencies, lease math "
                 "and SLO inputs — subtract time.monotonic() instead, "
                 "or justify-suppress a genuine cross-process epoch "
                 "comparison")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if (isinstance(node, ast.BinOp)
                    and isinstance(node.op, ast.Sub)
                    and isinstance(node.left, ast.Call)
                    and dotted_name(node.left.func) == "time.time"):
                findings.append(self.finding(
                    ctx, node,
                    "`time.time() - ...` is a wall-clock duration: NTP "
                    "slew/steps make it wrong (even negative) — use "
                    "time.monotonic() for elapsed time, or "
                    "justify-suppress a cross-process epoch cutoff"))
        return findings
