"""RF015 reader-field-not-written.

The companion to RF014 one level down: the kind/name pair matches, but
the reader projects a *field* no writer site ever passes. The failure
mode is quieter than a dangling kind — ``r.get("fill_ratio")`` just
returns ``None`` and flows into arithmetic or a report as a hole (the
twin calibrator's fill-ratio column went empty for two PRs this way;
the records existed, the field had been renamed at the writer).

Fires only when the joined writer field set is fully static: a writer
with ``**kwargs`` (the audit/span/ledger shape) or a dynamic name
marks the field set open and RF015 stays silent — soundness over
coverage, per docs/static_analysis.md. Implicit record fields
(``ts``/``pid``/``role``/``kind``/``name``/``trace_id``) are always
written by ``Journal.record`` itself and never flagged.
"""

from __future__ import annotations

from typing import Iterable, List

from rafiki_tpu.analysis.checkers._ast_util import LineNode
from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.contracts import journal_contracts
from rafiki_tpu.analysis.contracts.journal import missing_reader_fields


@register
class ReaderFieldNotWritten(Checker):
    id = "RF015"
    name = "reader-field-not-written"
    severity = "error"
    rationale = ("a field read that no writer populates degrades to "
                 "silent Nones, not an error")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        jc = journal_contracts(ctx.project)
        out: List[Finding] = []
        for r, missing in missing_reader_fields(jc):
            if r.path != ctx.path:
                continue
            writers = [w for w in jc.writers if w.kind == r.kind
                       and (r.name is None or w.name == r.name)]
            first = min(writers, key=lambda w: (w.path, w.line))
            out.append(self.finding(
                ctx, LineNode(r.line),
                f"reader of '{r.key}' expects field(s) "
                f"{', '.join(repr(f) for f in missing)} that no writer "
                f"emits (writer: {first.path}:{first.line} writes "
                f"{sorted(first.fields)})"))
        return out
