"""RF012 undamped-actuator.

Elasticity finding (PR 14, docs/autoscale.md): every change to live
capacity must flow through
:class:`rafiki_tpu.autoscale.controller.AutoscaleController`, because
the controller is where hysteresis, per-direction cooldowns, and flap
damping live. Code that calls the actuator surface directly —
``lane.scale_to(n)``, the lane's private spawn/drain steps, or an
``ElasticHandle.request`` delta — bypasses every one of those gates:
it can flap the fleet at sensor frequency, re-scale against a
replica whose drain has not reached the freed state, and none of it
journals an ``autoscale/decision``, so ``obs autoscale`` replays a
history with holes. The ``autoscale-flap-damping`` chaos scenario
shows what an undamped actuator does to a square-wave signal: one
actuation per tick, forever.

Flagged everywhere OUTSIDE ``rafiki_tpu.autoscale`` (the package owns
its own surface): any call to an attribute named ``scale_to``,
``_spawn_one`` or ``_drain_one``, and any ``.request(...)`` on a name
bound to a mesh ``ElasticHandle`` in the same module. Bare
``.request(...)`` on anything else (HTTP sessions, queues) is NOT
flagged — the receiver must provably be an elastic handle.

Legitimate direct callers (a teardown path that must zero a lane the
controller already stopped, a test harness) justify-suppress, stating
why the damping gates don't apply.
"""

from __future__ import annotations

import ast
from typing import Iterable, List, Set

from rafiki_tpu.analysis.core import Checker, Finding, ModuleContext, register
from rafiki_tpu.analysis.checkers._ast_util import dotted_name

#: The package that owns the actuator surface — exempt.
SCOPE = "rafiki_tpu.autoscale"

#: Attribute calls that ARE the surface, wherever the receiver came
#: from: scale_to is the lane contract, the underscored pair are the
#: lane's internal spawn/drain steps.
SURFACE_ATTRS = {"scale_to", "_spawn_one", "_drain_one"}


def _elastic_handle_names(tree: ast.Module) -> Set[str]:
    """Names bound to an ``ElasticHandle(...)`` instantiation in this
    module — the receivers whose ``.request`` is a capacity delta."""
    names: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        callee = dotted_name(value.func)
        if not callee or callee.split(".")[-1] != "ElasticHandle":
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if isinstance(t, ast.Name):
                names.add(t.id)
    return names


@register
class UndampedActuator(Checker):
    id = "RF012"
    name = "undamped-actuator"
    severity = "error"
    rationale = ("a direct call into the scale actuator surface "
                 "(lane.scale_to / ElasticHandle.request) bypasses the "
                 "controller's hysteresis, cooldowns and flap damping "
                 "and journals no autoscale/decision — route capacity "
                 "changes through AutoscaleController, or "
                 "justify-suppress a teardown/test path the gates "
                 "don't apply to")

    def check_module(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.module_name.startswith(SCOPE):
            return []
        handles = _elastic_handle_names(ctx.tree)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if not isinstance(func, ast.Attribute):
                continue
            if func.attr in SURFACE_ATTRS:
                findings.append(self.finding(
                    ctx, node,
                    f"direct `{func.attr}` call on a scale actuator "
                    f"outside rafiki_tpu.autoscale: this bypasses the "
                    f"controller's hysteresis/cooldown/flap-damping "
                    f"gates and journals no autoscale/decision — go "
                    f"through AutoscaleController (docs/autoscale.md)"))
            elif (func.attr == "request"
                  and isinstance(func.value, ast.Name)
                  and func.value.id in handles):
                findings.append(self.finding(
                    ctx, node,
                    f"`{func.value.id}.request(...)` pushes a chip "
                    f"delta into a mesh ElasticHandle directly: the "
                    f"sweep lane's damping gates live in "
                    f"AutoscaleController, not the handle — scale "
                    f"through the controller (docs/autoscale.md)"))
        return findings
