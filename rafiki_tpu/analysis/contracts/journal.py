"""Journal writer↔reader contract extraction.

Writers are ``<...>journal.record(kind, name, field=...)`` call sites;
the kind/name arguments resolve through module-level string constants
(``audit.py``'s ``KIND = "advisor"``). Readers are statically
recognizable *expectations* that some writer produces a kind (or a
kind/name pair, or a field on it):

* filter comparisons — ``r.get("kind") == "mesh"``,
  ``kind != "perf": continue`` guards, ``name in ("hops", "ts")`` —
  including the ``kind, name = r.get("kind"), r.get("name")`` alias
  idiom and comprehension ``if`` clauses;
* ``REQUIRED_KINDS``-style module constants of ``"kind/name"`` strings
  (the twin calibrators' fail-loud lists);
* helper predicates whose parameters flow into a kind/name comparison
  (``_journal_has(recs, "mesh", "repack")``) — each constant-argument
  call site is a reader expectation.

Field expectations are ``r.get("f")``/``r["f"]`` accesses (and the
``{f: r.get(f) for f in ("a", "b")}`` projection idiom) lexically under
an active kind filter. Anything dynamic degrades gracefully: a
non-constant kind at a writer site becomes a ``dynamic_writers`` entry
(manifest-visible, checker-invisible), a constant-kind writer with a
dynamic name becomes a wildcard writer for that kind, and ``**kwargs``
at a writer site marks its field set open so RF015 stays silent on it.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from rafiki_tpu.analysis.checkers._ast_util import dotted_name

#: Fields every record carries regardless of the writer's kwargs
#: (stamped by ``Journal.record`` itself).
IMPLICIT_FIELDS = frozenset(
    {"ts", "pid", "role", "kind", "name", "trace_id"})

_REQUIRED_KINDS_NAME = re.compile(r"^[A-Z_]*KINDS?$")


@dataclass
class WriterSite:
    path: str
    line: int
    kind: Optional[str]          # None: dynamic kind (manifest warning)
    name: Optional[str]          # None: dynamic name (wildcard writer)
    fields: Tuple[str, ...] = ()
    dynamic_fields: bool = False  # **kwargs present: field set is open

    @property
    def key(self) -> Optional[str]:
        if self.kind is None:
            return None
        return f"{self.kind}/{self.name if self.name is not None else '*'}"


@dataclass
class ReaderSite:
    path: str
    line: int
    kind: str
    name: Optional[str]          # None: kind-only filter
    source: str = "filter"       # filter | required-kinds | helper-call
    fields: List[str] = field(default_factory=list)

    @property
    def key(self) -> str:
        return f"{self.kind}/{self.name if self.name is not None else '*'}"


@dataclass
class JournalContracts:
    writers: List[WriterSite] = field(default_factory=list)
    readers: List[ReaderSite] = field(default_factory=list)
    dynamic_writers: List[WriterSite] = field(default_factory=list)

    # -- joined views --------------------------------------------------------

    def writer_pairs(self) -> Dict[str, List[WriterSite]]:
        out: Dict[str, List[WriterSite]] = {}
        for w in self.writers:
            if w.key is not None:
                out.setdefault(w.key, []).append(w)
        return out

    def writer_kinds(self) -> Set[str]:
        return {w.kind for w in self.writers if w.kind is not None}

    def wildcard_kinds(self) -> Set[str]:
        """Kinds written with a dynamic name — any name matches."""
        return {w.kind for w in self.writers
                if w.kind is not None and w.name is None}

    def reader_pairs(self) -> Dict[str, List[ReaderSite]]:
        out: Dict[str, List[ReaderSite]] = {}
        for r in self.readers:
            out.setdefault(r.key, []).append(r)
        return out

    def kinds_read_wholesale(self) -> Set[str]:
        """Kinds some reader consumes without a name filter."""
        return {r.kind for r in self.readers if r.name is None}

    def fields_written(self, kind: str, name: Optional[str]
                       ) -> Optional[Set[str]]:
        """Union of fields at every writer site matching kind (and
        name, when given). None when any matching site has an open
        field set — the sound degrade for **kwargs writers."""
        sites = [w for w in self.writers if w.kind == kind
                 and (name is None or w.name is None or w.name == name)]
        if not sites or any(w.dynamic_fields or w.name is None
                            for w in sites):
            return None
        out: Set[str] = set(IMPLICIT_FIELDS)
        for w in sites:
            out.update(w.fields)
        return out


# ---------------------------------------------------------------------------
# Joins (the substance of RF014/RF015)
# ---------------------------------------------------------------------------


def unread_writer_keys(jc: "JournalContracts") -> List[str]:
    """Writer kind/name keys no reader consumes — by exact pair, by a
    kind-only wholesale filter, or (for dynamic-name writers) by any
    reader of that kind."""
    wholesale = jc.kinds_read_wholesale()
    reader_keys = set(jc.reader_pairs())
    reader_kinds = {r.kind for r in jc.readers}
    out: List[str] = []
    for key in sorted(jc.writer_pairs()):
        kind, _, name = key.partition("/")
        if kind in wholesale or key in reader_keys:
            continue
        if name == "*" and kind in reader_kinds:
            continue
        out.append(key)
    return out


def unknown_reader_keys(jc: "JournalContracts") -> List[str]:
    """Reader expectations no writer satisfies — the loud side of a
    renamed kind, whichever side was renamed."""
    writer_keys = set(jc.writer_pairs())
    kinds = jc.writer_kinds()
    wildcards = jc.wildcard_kinds()
    out: List[str] = []
    for key in sorted(jc.reader_pairs()):
        kind, _, name = key.partition("/")
        if name == "*":
            if kind in kinds:
                continue
        elif key in writer_keys or kind in wildcards:
            continue
        out.append(key)
    return out


def missing_reader_fields(jc: "JournalContracts"
                          ) -> List[Tuple["ReaderSite", List[str]]]:
    """(reader site, fields it expects that no matching writer emits),
    only where every matching writer's field set is fully static."""
    out: List[Tuple[ReaderSite, List[str]]] = []
    for r in jc.readers:
        if not r.fields:
            continue
        written = jc.fields_written(r.kind, r.name)
        if written is None:
            continue
        missing = sorted(f for f in r.fields if f not in written)
        if missing:
            out.append((r, missing))
    return out


# ---------------------------------------------------------------------------
# Extraction
# ---------------------------------------------------------------------------


def _module_str_consts(tree: ast.Module) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)):
            out[node.targets[0].id] = node.value.value
    return out


def _const_str(node: Optional[ast.AST],
               consts: Dict[str, str]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    return None


def _const_str_seq(node: ast.AST,
                   consts: Dict[str, str]) -> Optional[List[str]]:
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        vals = [_const_str(e, consts) for e in node.elts]
        if vals and all(v is not None for v in vals):
            return vals  # type: ignore[return-value]
    return None


def _is_journal_record(call: ast.Call) -> bool:
    parts = dotted_name(call.func).split(".")
    return (len(parts) >= 2 and parts[-1] == "record"
            and "journal" in parts[-2])


def _extract_writers(path: str, tree: ast.Module,
                     consts: Dict[str, str], out: JournalContracts) -> None:
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call) and _is_journal_record(node)):
            continue
        args: List[Optional[ast.AST]] = list(node.args[:2])
        while len(args) < 2:
            args.append(None)
        kw = {k.arg: k.value for k in node.keywords if k.arg}
        kind_node = args[0] if args[0] is not None else kw.get("kind")
        name_node = args[1] if args[1] is not None else kw.get("name")
        kind = _const_str(kind_node, consts)
        name = _const_str(name_node, consts)
        fields = tuple(sorted(k.arg for k in node.keywords
                              if k.arg and k.arg not in ("kind", "name")
                              and k.arg not in IMPLICIT_FIELDS))
        dynamic_fields = any(k.arg is None for k in node.keywords)
        site = WriterSite(path=path, line=node.lineno, kind=kind,
                          name=name, fields=fields,
                          dynamic_fields=dynamic_fields)
        if kind is None:
            out.dynamic_writers.append(site)
        else:
            out.writers.append(site)


def _extract_required_kinds(path: str, tree: ast.Module,
                            out: JournalContracts) -> None:
    for node in tree.body:
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and _REQUIRED_KINDS_NAME.match(node.targets[0].id)):
            continue
        vals = _const_str_seq(node.value, {})
        if not vals or not all("/" in v for v in vals):
            continue
        for v in vals:
            kind, _, name = v.partition("/")
            out.readers.append(ReaderSite(
                path=path, line=node.lineno, kind=kind,
                name=name if name != "*" else None,
                source="required-kinds"))


# -- reader filter analysis --------------------------------------------------


@dataclass
class _Constraint:
    role: str                    # "kind" | "name"
    basevar: Optional[str]
    values: List[str]
    positive: bool


class _Scope:
    """One function (or the module body) being scanned for reader
    expectations. Aliases are collected flow-insensitively first —
    ``kind, name = r.get("kind"), r.get("name")`` is the common idiom
    and always precedes its comparisons in practice."""

    def __init__(self, path: str, consts: Dict[str, str],
                 params: Sequence[str], out: JournalContracts):
        self.path = path
        self.consts = consts
        self.params = set(params)
        self.out = out
        self.aliases: Dict[str, Tuple[str, Optional[str]]] = {}
        #: param name -> "kind" | "name" (helper predicate detection)
        self.param_roles: Dict[str, str] = {}

    # -- alias collection ----------------------------------------------------

    def collect_aliases(self, body: Sequence[ast.stmt]) -> None:
        for node in ast.walk(ast.Module(body=list(body), type_ignores=[])):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            tgt, val = node.targets[0], node.value
            pairs: List[Tuple[ast.AST, ast.AST]] = []
            if (isinstance(tgt, ast.Tuple) and isinstance(val, ast.Tuple)
                    and len(tgt.elts) == len(val.elts)):
                pairs = list(zip(tgt.elts, val.elts))
            else:
                pairs = [(tgt, val)]
            for t, v in pairs:
                got = self._record_expr(v, allow_alias=False)
                if got is not None and isinstance(t, ast.Name):
                    self.aliases[t.id] = got

    # -- expression classification -------------------------------------------

    def _record_expr(self, node: ast.AST, allow_alias: bool = True
                     ) -> Optional[Tuple[str, Optional[str]]]:
        """``(role, basevar)`` when ``node`` reads the kind/name of a
        journal record: ``r.get("kind")``, ``r["kind"]`` or an alias."""
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args
                and isinstance(node.func.value, ast.Name)):
            key = _const_str(node.args[0], {})
            if key in ("kind", "name"):
                return key, node.func.value.id
        if (isinstance(node, ast.Subscript)
                and isinstance(node.value, ast.Name)):
            key = _const_str(node.slice, {})
            if key in ("kind", "name"):
                return key, node.value.id
        if allow_alias and isinstance(node, ast.Name):
            return self.aliases.get(node.id)
        return None

    def _field_access(self, node: ast.AST,
                      constloops: Dict[str, List[str]]
                      ) -> Optional[Tuple[str, List[str]]]:
        """``(basevar, fields)`` for ``r.get("f")``/``r["f"]``; the
        projection idiom ``r.get(f)`` with ``f`` looping a constant
        tuple yields every looped field."""
        base: Optional[str] = None
        keynode: Optional[ast.AST] = None
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute)
                and node.func.attr == "get" and node.args
                and isinstance(node.func.value, ast.Name)):
            base, keynode = node.func.value.id, node.args[0]
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.value, ast.Name)
              and isinstance(node.ctx, ast.Load)):
            base, keynode = node.value.id, node.slice
        if base is None or keynode is None:
            return None
        k = _const_str(keynode, self.consts)
        if k is not None:
            return base, [k]
        if isinstance(keynode, ast.Name) and keynode.id in constloops:
            return base, list(constloops[keynode.id])
        return None

    def _comparisons(self, test: ast.AST) -> List[_Constraint]:
        out: List[_Constraint] = []
        for node in ast.walk(test):
            if not (isinstance(node, ast.Compare) and len(node.ops) == 1):
                continue
            left, op, right = node.left, node.ops[0], node.comparators[0]
            got = self._record_expr(left)
            if got is None:  # reversed operand order
                got = self._record_expr(right)
                left, right = right, left
            if got is None:
                continue
            role, basevar = got
            if isinstance(op, (ast.Eq, ast.NotEq)):
                v = _const_str(right, self.consts)
                if v is not None:
                    out.append(_Constraint(role, basevar, [v],
                                           isinstance(op, ast.Eq)))
                elif (isinstance(right, ast.Name)
                      and right.id in self.params):
                    self.param_roles.setdefault(right.id, role)
            elif isinstance(op, (ast.In, ast.NotIn)):
                vs = _const_str_seq(right, self.consts)
                if vs:
                    out.append(_Constraint(role, basevar, vs,
                                           isinstance(op, ast.In)))
        return out

    # -- context-carrying walk -----------------------------------------------

    def walk(self, body: Sequence[ast.stmt]) -> None:
        self._walk_block(body, _Ctx())

    def _refine(self, ctx: "_Ctx", cons: List[_Constraint],
                line: int, source: str = "filter") -> "_Ctx":
        kinds = sorted({v for c in cons if c.role == "kind"
                        for v in c.values})
        names = sorted({v for c in cons if c.role == "name"
                        for v in c.values})
        basevars = {c.basevar for c in cons if c.basevar}
        if not kinds and not names:
            return ctx
        new = _Ctx(kinds=kinds or ctx.kinds,
                   names=names or None,
                   basevars=ctx.basevars | basevars,
                   sites=list(ctx.sites))
        if not new.kinds:
            return new  # a name filter with no kind in scope: untracked
        fresh: List[ReaderSite] = []
        if names:
            for k in new.kinds:
                for n in names:
                    fresh.append(ReaderSite(self.path, line, k, n,
                                            source=source))
        elif kinds:
            for k in kinds:
                fresh.append(ReaderSite(self.path, line, k, None,
                                        source=source))
        self.out.readers.extend(fresh)
        new.sites = list(ctx.sites) + fresh
        return new

    def _walk_block(self, stmts: Sequence[ast.stmt], ctx: "_Ctx") -> None:
        for st in stmts:
            if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.ClassDef)):
                continue  # nested scopes are processed on their own
            if isinstance(st, ast.If):
                cons = self._comparisons(st.test)
                self._scan_expr(st.test, ctx)
                pos = [c for c in cons if c.positive]
                body_ctx = self._refine(ctx, pos, st.test.lineno)
                self._walk_block(st.body, body_ctx)
                self._walk_block(st.orelse, ctx)
                neg = [c for c in cons if not c.positive]
                if neg and _terminates(st.body):
                    flipped = [_Constraint(c.role, c.basevar, c.values,
                                           True) for c in neg]
                    ctx = self._refine(ctx, flipped, st.test.lineno)
                continue
            if isinstance(st, (ast.For, ast.AsyncFor)):
                self._scan_expr(st.iter, ctx)
                self._walk_block(st.body, ctx)
                self._walk_block(st.orelse, ctx)
                continue
            if isinstance(st, ast.While):
                self._scan_expr(st.test, ctx)
                self._walk_block(st.body, ctx)
                continue
            if isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._scan_expr(item.context_expr, ctx)
                self._walk_block(st.body, ctx)
                continue
            if isinstance(st, ast.Try):
                self._walk_block(st.body, ctx)
                for h in st.handlers:
                    self._walk_block(h.body, ctx)
                self._walk_block(st.orelse, ctx)
                self._walk_block(st.finalbody, ctx)
                continue
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._scan_expr(child, ctx)

    def _scan_expr(self, node: ast.AST, ctx: "_Ctx",
                   constloops: Optional[Dict[str, List[str]]] = None
                   ) -> None:
        constloops = constloops or {}
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            gen_ctx = ctx
            loops = dict(constloops)
            for comp in node.generators:
                self._scan_expr(comp.iter, gen_ctx, loops)
                vals = _const_str_seq(comp.iter, self.consts)
                if vals is not None and isinstance(comp.target, ast.Name):
                    loops[comp.target.id] = vals
                for if_ in comp.ifs:
                    cons = self._comparisons(if_)
                    gen_ctx = self._refine(
                        gen_ctx, [c for c in cons if c.positive],
                        if_.lineno)
                    self._scan_expr(if_, gen_ctx, loops)
            if isinstance(node, ast.DictComp):
                self._scan_expr(node.key, gen_ctx, loops)
                self._scan_expr(node.value, gen_ctx, loops)
            else:
                # a bare comparison element (the ``any(... == ...)``
                # predicate shape) is itself a reader expectation
                cons = self._comparisons(node.elt)
                elt_ctx = self._refine(
                    gen_ctx, [c for c in cons if c.positive],
                    node.elt.lineno)
                self._scan_expr(node.elt, elt_ctx, loops)
            return
        got = self._field_access(node, constloops)
        if got is not None and ctx.kinds and got[0] in ctx.basevars:
            for f in got[1]:
                if f in IMPLICIT_FIELDS:
                    continue
                for site in ctx.sites:
                    if f not in site.fields:
                        site.fields.append(f)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.expr, ast.comprehension,
                                  ast.keyword)):
                self._scan_expr(child, ctx, constloops)


@dataclass
class _Ctx:
    kinds: List[str] = field(default_factory=list)
    names: Optional[List[str]] = None
    basevars: Set[str] = field(default_factory=set)
    sites: List[ReaderSite] = field(default_factory=list)


def _terminates(body: Sequence[ast.stmt]) -> bool:
    return bool(body) and isinstance(
        body[-1], (ast.Continue, ast.Return, ast.Raise, ast.Break))


def _functions(tree: ast.Module) -> Iterable[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def extract_journal(modules) -> JournalContracts:
    """Whole-tree journal contracts from ModuleContext-likes (need
    ``.path`` and ``.tree``)."""
    out = JournalContracts()
    #: (module path, helper fn name) -> {param index: role}
    helpers: Dict[Tuple[str, str], Dict[int, str]] = {}
    mods = sorted(modules, key=lambda m: m.path)
    for m in mods:
        consts = _module_str_consts(m.tree)
        _extract_writers(m.path, m.tree, consts, out)
        _extract_required_kinds(m.path, m.tree, out)
        # module body + each function is its own reader scope
        scope = _Scope(m.path, consts, (), out)
        scope.collect_aliases(m.tree.body)
        scope.walk(m.tree.body)
        for fn in _functions(m.tree):
            params = [a.arg for a in fn.args.args + fn.args.kwonlyargs]
            fscope = _Scope(m.path, consts, params, out)
            fscope.collect_aliases(fn.body)
            fscope.walk(fn.body)
            if fscope.param_roles:
                idx = {name: i for i, name in enumerate(params)}
                helpers[(m.path, fn.name)] = {
                    idx[p]: role for p, role in fscope.param_roles.items()
                    if p in idx}
    _extract_helper_calls(mods, helpers, out)
    out.writers.sort(key=lambda w: (w.path, w.line, w.key or ""))
    out.dynamic_writers.sort(key=lambda w: (w.path, w.line))
    out.readers.sort(key=lambda r: (r.path, r.line, r.key))
    return out


def _extract_helper_calls(mods, helpers, out: JournalContracts) -> None:
    """Constant-argument calls to detected helper predicates — a
    ``_journal_has(recs, "mesh", "repack")`` site expects mesh/repack.
    Same-module resolution only (the live helpers are private)."""
    by_name: Dict[Tuple[str, str], Dict[int, str]] = helpers
    if not by_name:
        return
    for m in mods:
        consts = _module_str_consts(m.tree)
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = dotted_name(node.func).rsplit(".", 1)[-1]
            roles = by_name.get((m.path, leaf))
            if not roles:
                continue
            kind = name = None
            for i, role in roles.items():
                if i < len(node.args):
                    v = _const_str(node.args[i], consts)
                    if role == "kind":
                        kind = v
                    elif role == "name":
                        name = v
            if kind is not None:
                out.readers.append(ReaderSite(
                    m.path, node.lineno, kind, name,
                    source="helper-call"))
    out.readers.sort(key=lambda r: (r.path, r.line, r.key))
