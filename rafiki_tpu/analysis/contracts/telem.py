"""Telemetry-name registry extraction.

Write sites are ``telemetry.inc/set_gauge/add_gauge/observe/span``
calls with a constant first argument; an f-string name records a
*dynamic site* with its constant prefix (``gateway.shed_{reason}`` →
``gateway.shed_``). Collector registrations
(``register_collector("goodput", ...)``) are extracted too — they
explain whole prom-family prefixes the static name set can't.

Joins (pure functions over file contents, so the extractor itself
stays I/O-free):

* :func:`documented_names` parses the docs/telemetry.md table —
  backticked tokens, ``{a,b}`` brace groups expanded, ``<...>``
  placeholders to wildcards, and the ``/ `_suffix``` shorthand resolved
  against the preceding full name;
* :func:`join_prom_golden` maps ``# TYPE rafiki_<name> <type>``
  families back onto the static registry and reports the families
  nothing explains — the drift a renamed metric leaves behind.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from rafiki_tpu.analysis.checkers._ast_util import dotted_name

_APIS = {"inc": "counter", "set_gauge": "gauge", "add_gauge": "gauge",
         "observe": "histogram", "span": "span"}
_SAN_RE = re.compile(r"[^a-zA-Z0-9_]")
_TYPE_LINE = re.compile(r"^# TYPE rafiki_(\w+) (counter|gauge|summary)$")
_BACKTICK = re.compile(r"`([^`]+)`")
_BRACE = re.compile(r"\{([^{}]+)\}")


@dataclass
class MetricSite:
    path: str
    line: int
    name: str
    api: str                     # counter | gauge | histogram | span


@dataclass
class DynamicMetricSite:
    path: str
    line: int
    prefix: str                  # constant f-string head ("" if none)
    api: str


@dataclass
class TelemetryContracts:
    sites: List[MetricSite] = field(default_factory=list)
    dynamic_sites: List[DynamicMetricSite] = field(default_factory=list)
    collectors: List[MetricSite] = field(default_factory=list)

    def names(self) -> Dict[str, List[MetricSite]]:
        out: Dict[str, List[MetricSite]] = {}
        for s in self.sites:
            out.setdefault(s.name, []).append(s)
        return out


def _telemetry_call(call: ast.Call) -> Optional[str]:
    parts = dotted_name(call.func).split(".")
    if len(parts) >= 2 and parts[-1] in _APIS and (
            parts[-2] == "telemetry" or parts[-2].endswith("telemetry")):
        return _APIS[parts[-1]]
    return None


def extract_telemetry(modules) -> TelemetryContracts:
    out = TelemetryContracts()
    for m in sorted(modules, key=lambda m: m.path):
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            parts = dotted_name(node.func).split(".")
            if (parts[-1] == "register_collector" and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                out.collectors.append(MetricSite(
                    m.path, node.lineno, node.args[0].value, "collector"))
                continue
            api = _telemetry_call(node)
            if api is None or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                out.sites.append(MetricSite(m.path, node.lineno,
                                            arg.value, api))
            elif isinstance(arg, ast.IfExp):  # "a" if cold else "b"
                for side in (arg.body, arg.orelse):
                    if (isinstance(side, ast.Constant)
                            and isinstance(side.value, str)):
                        out.sites.append(MetricSite(
                            m.path, node.lineno, side.value, api))
            elif isinstance(arg, ast.JoinedStr):
                head = ""
                if (arg.values and isinstance(arg.values[0], ast.Constant)
                        and isinstance(arg.values[0].value, str)):
                    head = arg.values[0].value
                out.dynamic_sites.append(DynamicMetricSite(
                    m.path, node.lineno, head, api))
            else:
                out.dynamic_sites.append(DynamicMetricSite(
                    m.path, node.lineno, "", api))
    out.sites.sort(key=lambda s: (s.name, s.path, s.line))
    out.dynamic_sites.sort(key=lambda s: (s.path, s.line))
    out.collectors.sort(key=lambda s: (s.name, s.path, s.line))
    return out


# ---------------------------------------------------------------------------
# docs/telemetry.md join
# ---------------------------------------------------------------------------


def documented_names(docs_text: str) -> Tuple[Set[str], Set[str]]:
    """(exact names, wildcard patterns) from the instrumentation table.
    Only table rows count (lines starting ``|``) so prose backticks
    don't leak in."""
    exact: Set[str] = set()
    wild: Set[str] = set()
    for line in docs_text.splitlines():
        if not line.startswith("|"):
            continue
        first_col = line.split("|")[1] if line.count("|") >= 2 else ""
        prev = ""
        for tok in _BACKTICK.findall(first_col):
            tok = tok.strip()
            m = _BRACE.search(tok)
            toks = ([tok[:m.start()] + alt.strip() + tok[m.end():]
                     for alt in m.group(1).split(",")] if m else [tok])
            for t in toks:
                short = t.startswith((".", "_"))
                if short and prev:
                    # `a.b_c` / `_d` means a.b_d: resolve against the
                    # row's first FULL name, not a prior expansion
                    sep = t[0]
                    cut = prev.rfind(sep)
                    t = (prev[:cut] if cut > 0 else prev) + t
                if "<" in t:
                    wild.add(re.sub(r"<[^<>]*>", "*", t))
                else:
                    exact.add(t)
                    if not short:
                        prev = t
    return exact, wild


def is_documented(name: str, exact: Set[str], wild: Set[str]) -> bool:
    return name in exact or any(fnmatch.fnmatchcase(name, w) for w in wild)


# ---------------------------------------------------------------------------
# prom golden join
# ---------------------------------------------------------------------------


def _san(name: str) -> str:
    out = _SAN_RE.sub("_", name)
    return out if not out[:1].isdigit() else "_" + out


def join_prom_golden(golden_text: str, contracts: TelemetryContracts
                     ) -> Dict[str, List[str]]:
    """Classify every golden family: ``matched`` (a static write site
    sanitizes to it), ``explained`` (span machinery, a registered
    collector's flattened prefix, or a dynamic-site prefix), or
    ``unexplained`` — the reviewable drift bucket."""
    static = {_san(s.name) for s in contracts.sites}
    collector_prefixes = [_san(c.name) + "_" for c in contracts.collectors]
    collector_names = {_san(c.name) for c in contracts.collectors}
    dynamic_prefixes = [_san(d.prefix) for d in contracts.dynamic_sites
                        if d.prefix]
    matched: List[str] = []
    explained: List[str] = []
    unexplained: List[str] = []
    for line in golden_text.splitlines():
        m = _TYPE_LINE.match(line.strip())
        if not m:
            continue
        fam = m.group(1)
        if fam in static:
            matched.append(fam)
        elif (fam.startswith("span_")
              or fam in collector_names
              or any(fam.startswith(p) for p in collector_prefixes)
              or any(fam.startswith(p) for p in dynamic_prefixes if p)):
            explained.append(fam)
        else:
            unexplained.append(fam)
    return {"matched": sorted(matched), "explained": sorted(explained),
            "unexplained": sorted(unexplained)}
