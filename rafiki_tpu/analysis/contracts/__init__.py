"""Whole-program contract extraction (docs/static_analysis.md).

The per-file checkers (RF001–RF013) each encode a single-file failure
class. The contracts layer is different in kind: it extracts every
cross-process *contract surface* from the full analyzed tree —

* **journal contracts** (:mod:`.journal`) — every
  ``journal.record(kind, name, field=...)`` writer site joined against
  reader-side expectations (kind/name filters in ``obs/`` readers, the
  twin calibrators' ``REQUIRED_KINDS`` lists, ``search/reconstruct``,
  ``advisor/rehydrate``, chaos reconstruction checks);
* **env-knob registry** (:mod:`.envknobs`) — every ``RAFIKI_*`` read
  with its statically-derivable default and parse type, plus subprocess
  spawn sites and the env keys they propagate;
* **telemetry-name registry** (:mod:`.telem`) — counter/gauge/histogram
  names at ``inc``/``set_gauge``/``add_gauge``/``observe`` sites joined
  against the prom golden and the docs/telemetry.md table.

and joins them into one machine-readable **manifest**
(:mod:`.manifest`). RF014–RF016 surface violations through the normal
lint CLI; ``python -m rafiki_tpu.analysis --contracts`` emits the
manifest, whose committed golden (tests/data/contracts_manifest.json)
turns any contract drift into a reviewable diff.

Extraction is memoized per analysis run via ``ProjectContext.fact`` so
the three checkers share one walk of the tree.
"""

from __future__ import annotations

from rafiki_tpu.analysis.contracts.envknobs import (  # noqa: F401
    EnvContracts, KnobRead, SpawnSite, extract_env)
from rafiki_tpu.analysis.contracts.journal import (  # noqa: F401
    IMPLICIT_FIELDS, JournalContracts, ReaderSite, WriterSite,
    extract_journal)
from rafiki_tpu.analysis.contracts.knobdocs import (  # noqa: F401
    KNOB_DOCS, generate_knobs_md)
from rafiki_tpu.analysis.contracts.manifest import (  # noqa: F401
    build_manifest, dump_manifest, manifest_for_paths)
from rafiki_tpu.analysis.contracts.telem import (  # noqa: F401
    TelemetryContracts, extract_telemetry)

FACT_JOURNAL = "contracts.journal"
FACT_ENV = "contracts.env"


def journal_contracts(project) -> "JournalContracts":
    """The run-wide journal contract surface, computed once."""
    return project.fact(
        FACT_JOURNAL, lambda p: extract_journal(p.modules.values()))


def env_contracts(project) -> "EnvContracts":
    """The run-wide env-knob registry, computed once."""
    return project.fact(
        FACT_ENV, lambda p: extract_env(p.modules.values()))
