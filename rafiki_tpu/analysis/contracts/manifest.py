"""Deterministic contracts-manifest assembly.

``manifest_for_paths`` parses the analyzed tree once, runs the three
extractors, joins in the prom golden and docs/telemetry.md when the
repo root carries them, and returns a plain-dict manifest.
``dump_manifest`` serializes it byte-deterministically (sorted keys,
two-space indent, trailing newline) — the committed golden at
tests/data/contracts_manifest.json is diffed against this exact byte
stream by scripts/check_lint.sh, so any contract drift (a renamed
journal kind, a new env knob, a dropped metric) shows up as a
reviewable diff, not a silent divergence.

All paths in the manifest are repo-root-relative with ``/`` separators
regardless of how the analyzed paths were spelled on the command line.
"""

from __future__ import annotations

import ast
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from rafiki_tpu.analysis.core import _collect_py_files, module_name_for
from rafiki_tpu.analysis.contracts.envknobs import (
    EnvContracts, extract_env)
from rafiki_tpu.analysis.contracts.journal import (
    JournalContracts, extract_journal, missing_reader_fields,
    unknown_reader_keys, unread_writer_keys)
from rafiki_tpu.analysis.contracts.telem import (
    TelemetryContracts, documented_names, extract_telemetry,
    is_documented, join_prom_golden)

MANIFEST_VERSION = 1

#: Repo-root-relative locations the telemetry join reads when present.
PROM_GOLDEN = os.path.join("tests", "data", "prom_golden.txt")
TELEMETRY_DOCS = os.path.join("docs", "telemetry.md")


@dataclass
class _Mod:
    path: str
    module_name: str
    tree: ast.Module


def _site(path: str, line: int) -> str:
    return f"{path}:{line}"


def _rel(path: str, root: Optional[str]) -> str:
    if root:
        path = os.path.relpath(os.path.abspath(path), os.path.abspath(root))
    return path.replace(os.sep, "/")


def _load_modules(paths: Sequence[str], root: Optional[str]) -> List[_Mod]:
    mods: List[_Mod] = []
    for path in _collect_py_files(paths):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                source = fh.read()
            tree = ast.parse(source, filename=path)
        except (OSError, SyntaxError):
            continue  # lint proper reports parse errors; manifest skips
        mods.append(_Mod(path=_rel(path, root),
                         module_name=module_name_for(path), tree=tree))
    mods.sort(key=lambda m: m.path)
    return mods


# ---------------------------------------------------------------------------
# Section builders
# ---------------------------------------------------------------------------


def _journal_section(jc: JournalContracts) -> dict:
    writers: Dict[str, dict] = {}
    for key, sites in sorted(jc.writer_pairs().items()):
        fields = sorted({f for w in sites for f in w.fields})
        writers[key] = {
            "fields": fields,
            "open_fields": any(w.dynamic_fields or w.name is None
                               for w in sites),
            "sites": sorted(_site(w.path, w.line) for w in sites),
        }
    readers: Dict[str, dict] = {}
    for key, sites in sorted(jc.reader_pairs().items()):
        readers[key] = {
            "fields": sorted({f for r in sites for f in r.fields}),
            "sources": sorted({r.source for r in sites}),
            "sites": sorted(_site(r.path, r.line) for r in sites),
        }
    return {
        "writers": writers,
        "readers": readers,
        "dynamic_writers": sorted(_site(w.path, w.line)
                                  for w in jc.dynamic_writers),
        "unread": unread_writer_keys(jc),
        "unknown": unknown_reader_keys(jc),
        "missing_fields": sorted(
            ({"site": _site(r.path, r.line), "key": r.key, "fields": miss}
             for r, miss in missing_reader_fields(jc)),
            key=lambda d: (d["site"], d["key"])),
    }


def _env_section(env: EnvContracts) -> dict:
    divergent = set(env.divergent())
    knobs: Dict[str, dict] = {}
    for knob, reads in sorted(env.by_knob().items()):
        knobs[knob] = {
            "parse": sorted({r.parse for r in reads}),
            "defaults": sorted({str(r.manifest_default()) for r in reads}),
            "sites": sorted(_site(r.path, r.line) for r in reads),
            "divergent": knob in divergent,
        }
    spawns = [{
        "site": _site(s.path, s.line),
        "target": s.target_module,
        "inherits_environ": s.inherits_environ,
        "explicit_keys": sorted(k for k in s.explicit_keys
                                if k.startswith("RAFIKI_")),
    } for s in env.spawns]
    return {"knobs": knobs, "spawns": spawns}


def _telemetry_section(tc: TelemetryContracts,
                       docs_text: Optional[str],
                       golden_text: Optional[str]) -> dict:
    metrics: Dict[str, dict] = {}
    exact, wild = documented_names(docs_text) if docs_text else (set(), set())
    for name, sites in sorted(tc.names().items()):
        entry = {
            "api": sorted({s.api for s in sites}),
            "sites": sorted(_site(s.path, s.line) for s in sites),
        }
        if docs_text is not None:
            entry["documented"] = is_documented(name, exact, wild)
        metrics[name] = entry
    out = {
        "metrics": metrics,
        "dynamic_sites": [{"site": _site(d.path, d.line),
                           "prefix": d.prefix, "api": d.api}
                          for d in tc.dynamic_sites],
        "collectors": {c.name: sorted(_site(s.path, s.line)
                                      for s in tc.collectors
                                      if s.name == c.name)
                       for c in tc.collectors},
    }
    if golden_text is not None:
        out["prom_golden"] = join_prom_golden(golden_text, tc)
    return out


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------


def build_manifest(modules, docs_text: Optional[str] = None,
                   golden_text: Optional[str] = None) -> dict:
    """Manifest dict from already-parsed module-likes (``.path``,
    ``.tree``). Pure — no filesystem access — so tests can feed
    synthetic trees."""
    jc = extract_journal(modules)
    env = extract_env(modules)
    tc = extract_telemetry(modules)
    return {
        "version": MANIFEST_VERSION,
        "journal": _journal_section(jc),
        "env": _env_section(env),
        "telemetry": _telemetry_section(tc, docs_text, golden_text),
    }


def manifest_for_paths(paths: Sequence[str],
                       root: Optional[str] = None) -> dict:
    """Parse ``paths`` and build the manifest, joining the prom golden
    and telemetry docs found under ``root`` (default: cwd)."""
    root = root or os.getcwd()
    mods = _load_modules(paths, root)

    def _read(rel: str) -> Optional[str]:
        p = os.path.join(root, rel)
        try:
            with open(p, "r", encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return None

    return build_manifest(mods, docs_text=_read(TELEMETRY_DOCS),
                          golden_text=_read(PROM_GOLDEN))


def dump_manifest(manifest: dict) -> str:
    """The byte-deterministic serialization the golden is diffed
    against."""
    return json.dumps(manifest, indent=2, sort_keys=True) + "\n"
