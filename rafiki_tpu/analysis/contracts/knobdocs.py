"""docs/knobs.md generator — the env-knob registry rendered as docs.

The table is *derived*, not hand-maintained: ``python -m
rafiki_tpu.analysis --contracts --docs`` regenerates it from the same
extraction the manifest uses, so knob name / default / parse-type
drift between code and docs is structurally impossible — the only
hand-written content is the one-line description per knob in
:data:`KNOB_DOCS`. A knob read in code but missing from that dict
renders as *undocumented* (and scripts/check_lint.sh fails on the
marker), which is the "undocumented knob" cross-check: adding an env
read forces adding its one-liner here in the same change.
"""

from __future__ import annotations

from typing import List

from rafiki_tpu.analysis.contracts.envknobs import EnvContracts

UNDOCUMENTED = "**undocumented** (add a one-liner to " \
    "rafiki_tpu/analysis/contracts/knobdocs.py)"

#: Hand-written one-liners; everything else in the table is extracted.
KNOB_DOCS = {
    "RAFIKI_AUTOSCALE": "autoscale controller spec; empty disables the "
        "elasticity loop (docs/autoscale.md)",
    "RAFIKI_AUTOSCALE_DAMPING": "flap damping; off exists ONLY so "
        "tests/smoke can demonstrate the flapping it prevents",
    "RAFIKI_AUTOSCALE_DOWN_COOLDOWN_S": "cooldown after a scale-down "
        "actuation",
    "RAFIKI_AUTOSCALE_DOWN_THRESHOLD": "hysteresis band lower edge "
        "(pressure below it scales down)",
    "RAFIKI_AUTOSCALE_FLAP_BACKOFF": "direction-flip guard growth per "
        "excess flip",
    "RAFIKI_AUTOSCALE_FLAP_FLIPS": "direction flips inside the window "
        "before backoff engages",
    "RAFIKI_AUTOSCALE_FLAP_GUARD_CAP_S": "cap of the direction-flip "
        "guard",
    "RAFIKI_AUTOSCALE_FLAP_GUARD_S": "base of the direction-flip guard",
    "RAFIKI_AUTOSCALE_FLAP_WINDOW_S": "window for counting direction "
        "flips",
    "RAFIKI_AUTOSCALE_MAX": "lane size upper bound",
    "RAFIKI_AUTOSCALE_MIN": "lane size lower bound",
    "RAFIKI_AUTOSCALE_PREWARM": "pre-warm compiled packs at job "
        "admission (docs/autoscale.md)",
    "RAFIKI_AUTOSCALE_SEED": "controller seed; decisions are "
        "deterministic given clock+seed+sensors",
    "RAFIKI_AUTOSCALE_STEP": "replicas per actuation",
    "RAFIKI_AUTOSCALE_TARGET_EPH": "sweep-lane target effective-trials"
        "/hour; 0 (the default) holds the sweep lane",
    "RAFIKI_AUTOSCALE_TICK_S": "controller reconcile interval",
    "RAFIKI_AUTOSCALE_UP_COOLDOWN_S": "cooldown after a scale-up "
        "actuation",
    "RAFIKI_AUTOSCALE_UP_THRESHOLD": "hysteresis band upper edge "
        "(pressure above it scales up)",
    "RAFIKI_BACKEND_INIT_TIMEOUT_S": "worker gives up on jax backend "
        "init after this many seconds",
    "RAFIKI_BENCH_DEADLINE_S": "bench.py wall-clock budget before the "
        "run is declared hung",
    "RAFIKI_BENCH_PLATFORM": "force the bench platform (cpu/tpu) "
        "instead of auto-detecting",
    "RAFIKI_BENCH_SELFTEST_DEGRADED": "bench self-test hook: report a "
        "degraded run (CI polarity check)",
    "RAFIKI_BENCH_SELFTEST_FAIL": "bench self-test hook: fail "
        "deliberately (CI polarity check)",
    "RAFIKI_BENCH_SELFTEST_SLEEP_S": "bench self-test hook: sleep to "
        "trip the deadline gate",
    "RAFIKI_BENCH_TOP1_TARGET": "override the per-scale top-1 accuracy "
        "gate (calibrated default per platform)",
    "RAFIKI_BENCH_TRIALS": "override trial count for both bench scales "
        "(unset: 3 on cpu smoke, 30 on tpu)",
    "RAFIKI_BUS_REAP_FACTOR": "multiplier on queue TTL before an "
        "abandoned entry is reaped",
    "RAFIKI_CAS_CHUNK_KB": "content-addressed params store chunk size",
    "RAFIKI_CHAOS": "fault-injection spec for the chaos plane; unset "
        "means every hook is inert (docs/chaos.md)",
    "RAFIKI_CHECKPOINT_EVERY": "checkpoint cadence in epochs; 0 "
        "disables mid-trial checkpoints",
    "RAFIKI_COLLECTIVE_INIT_BACKOFF_S": "sleep between multi-process "
        "collective init retries",
    "RAFIKI_COLLECTIVE_INIT_RETRIES": "multi-process collective init "
        "attempts before the worker dies",
    "RAFIKI_COORDINATOR_ADDRESS": "jax distributed coordinator "
        "host:port (leader sets it for followers)",
    "RAFIKI_CURVE_KILL": "learning-curve early-kill switch "
        "(docs/early_kill.md); off by default — today's loops run "
        "bit-exactly",
    "RAFIKI_CURVE_KILL_MARGIN": "kill rule slack: a trial dies only "
        "when its credible band's upper edge sits below best-so-far "
        "minus this margin",
    "RAFIKI_CURVE_KILL_MIN_OBS": "curve points required before the "
        "extrapolator may condemn a trial",
    "RAFIKI_CURVE_KILL_WARMUP": "epochs every trial is immune from "
        "the early-kill rule",
    "RAFIKI_CURVE_SPECULATE": "speculative scoring switch: feed the "
        "advisor predicted scores for in-flight stragglers so "
        "propose_batch never blocks (docs/early_kill.md)",
    "RAFIKI_DEVICE_DATASET_MAX_MB": "cap on device-resident dataset "
        "size before falling back to host streaming",
    "RAFIKI_EVENTS_DIR": "control-plane event bus directory "
        "(docs/recovery.md)",
    "RAFIKI_EXEMPLAR_N": "serving exemplar reservoir size per window",
    "RAFIKI_EXEMPLAR_WINDOW_S": "serving exemplar sampling window",
    "RAFIKI_FOLLOWER_EXIT_GRACE_S": "follower wait for the leader's "
        "exit signal before exiting itself",
    "RAFIKI_HEALTH": "0/off disables numerics-divergence detection and "
        "capsules (docs/health.md)",
    "RAFIKI_HEALTH_CAPSULE": "0/off skips divergence snapshots and "
        "capsule writes",
    "RAFIKI_HEALTH_HYSTERESIS": "consecutive exploding epochs before "
        "the detector trips",
    "RAFIKI_HEALTH_K": "explosion multiplier over the running grad-norm "
        "median",
    "RAFIKI_HEALTH_WARMUP": "clean epochs before the explosion detector "
        "arms",
    "RAFIKI_JOURNAL_MAX": "per-process in-memory journal ring size",
    "RAFIKI_LEADER_SERVICE_ID": "leader's serving registration id, "
        "exported to followers for stacked serving",
    "RAFIKI_LEADER_WORKER_ID": "leader's worker id, exported to "
        "followers of a multi-process mesh",
    "RAFIKI_LOG_DIR": "journal directory; unset disables durable "
        "journaling (docs/observability.md)",
    "RAFIKI_MESH_CHIPS_PER_HOST": "override detected chips per host "
        "when planning mesh packing",
    "RAFIKI_MESH_FORM_GRACE_S": "mesh formation deadline before the "
        "supervisor declares the pack failed",
    "RAFIKI_MESH_INIT_BACKOFF_S": "sleep between mesh init retries",
    "RAFIKI_MESH_INIT_RETRIES": "mesh init attempts before giving up "
        "on a pack",
    "RAFIKI_NUM_PROCESSES": "process count of a multi-process mesh "
        "(spawner sets it; workers require it)",
    "RAFIKI_PARAMS_CAS": "enable the content-addressed params store "
        "backend",
    "RAFIKI_PERF_COST_CAPTURE": "capture per-program XLA cost models "
        "for the MFU join; on by default",
    "RAFIKI_PERF_K": "timing-anomaly threshold in MADs from the EWMA "
        "baseline",
    "RAFIKI_PERF_WARMUP": "timing samples before the anomaly detector "
        "arms",
    "RAFIKI_PROCESS_ID": "this process's rank within the mesh "
        "(spawner-assigned, required in workers)",
    "RAFIKI_PROFILE_DIR": "write jax profiler traces for each trial "
        "here; unset disables profiling",
    "RAFIKI_RESUME_POLL_S": "resume-reaper poll cadence "
        "(docs/recovery.md)",
    "RAFIKI_RESUME_STALE_S": "supervisor heartbeat age before a job is "
        "adoptable by resume (docs/recovery.md)",
    "RAFIKI_SHARD_HBM_CEILING": "per-chip HBM fraction the shard "
        "planner fits a group member under (docs/sharding.md)",
    "RAFIKI_SHARD_MAX_WIDTH": "cap on the solved group width even "
        "when the HBM estimate wants more chips",
    "RAFIKI_SHARD_WIDTH": "pin the group width (tests/smokes); 0 "
        "solves it from the HBM estimate",
    "RAFIKI_SLO": "SLO spec overrides as JSON; empty keeps the "
        "defaults (docs/slo.md)",
    "RAFIKI_SLO_TICK_S": "SLO burn-rate evaluation cadence",
    "RAFIKI_STACKED_SERVING": "serve from training hosts (stacked) "
        "instead of a dedicated pool; on by default",
    "RAFIKI_SUPERVISOR_HEARTBEAT_S": "supervisor liveness heartbeat "
        "cadence in the MetaStore",
    "RAFIKI_TENANT_BATCH_WEIGHT": "batch-tier admission weight "
        "(docs/multitenancy.md)",
    "RAFIKI_TENANT_DEFAULT_TIER": "QoS tier for tenants absent from "
        "RAFIKI_TENANT_TIERS (docs/multitenancy.md)",
    "RAFIKI_TENANT_GOLD_WEIGHT": "gold-tier admission weight "
        "(docs/multitenancy.md)",
    "RAFIKI_TENANT_HBM_BUDGET_MB": "co-host HBM residency budget per "
        "worker; 0 disables the cap (docs/multitenancy.md)",
    "RAFIKI_TENANT_MAX_TENANTS": "bound on tracked per-tenant "
        "admission/accounting state before LRU eviction",
    "RAFIKI_TENANT_QUOTA_FRAC": "per-tenant cap as a fraction of "
        "gateway inflight/queue capacity",
    "RAFIKI_TENANT_STD_WEIGHT": "std-tier admission weight "
        "(docs/multitenancy.md)",
    "RAFIKI_TENANT_TIERS": "tenant→tier map, e.g. "
        "\"alice=gold,bob=batch\" (docs/multitenancy.md)",
    "RAFIKI_TENANT_UNWEIGHTED": "polarity knob: disable weighted "
        "admission and quotas (tenancy smoke's doctored run)",
    "RAFIKI_TPU_DATA_DIR": "root for all durable state (stores, "
        "journals, caches)",
    "RAFIKI_TRACE_ID": "trace id stamped on every journal record of "
        "this process (spawner-propagated)",
    "RAFIKI_TRIAL_PACK": "trial-packing width k; 1 = off "
        "(docs/trial_packing.md)",
    "RAFIKI_TWIN_PLACEMENT": "consult the training twin for placement "
        "advisories at pack formation (docs/twin.md)",
    "RAFIKI_WAL_DIR": "sweep write-ahead-log directory; empty keeps "
        "the WAL beside the MetaStore (docs/recovery.md)",
    "RAFIKI_WORKER_ADOPT_SERVICE_ID": "serving registration the "
        "restarted worker should adopt instead of re-registering",
    "RAFIKI_WORKER_ADVISOR_ID": "advisor identity for this worker's "
        "trial proposals",
    "RAFIKI_WORKER_ADVISOR_SECRET": "shared secret for advisor calls",
    "RAFIKI_WORKER_ADVISOR_URL": "advisor service endpoint the worker "
        "proposes/reports against",
    "RAFIKI_WORKER_DB": "MetaStore path handed to a spawned worker",
    "RAFIKI_WORKER_ID": "worker identity; empty derives one from "
        "pid/host",
    "RAFIKI_WORKER_MAX_RESTARTS": "per-worker restart budget before "
        "the scheduler gives up on it",
    "RAFIKI_WORKER_PARAMS_DIR": "ParamsStore path handed to a spawned "
        "worker",
    "RAFIKI_WORKER_RESTART_BACKOFF_S": "sleep before restarting a "
        "crashed worker",
    "RAFIKI_WORKER_SERVICE_ID": "serving registration id assigned to "
        "the spawned worker",
    "RAFIKI_WORKER_SUB_JOB_ID": "sub-train-job the spawned worker "
        "executes",
    "RAFIKI_XLA_CACHE_DIR": "XLA compilation cache directory "
        "(docs/compile_cache.md)",
    "RAFIKI_XLA_CACHE_MIN_S": "minimum compile time before a program "
        "is worth caching",
}

_HEADER = """\
# Environment knobs

<!-- GENERATED FILE — do not edit the table by hand.
     Regenerate with:  python -m rafiki_tpu.analysis --contracts --docs
     Descriptions live in rafiki_tpu/analysis/contracts/knobdocs.py;
     names, defaults, parse types, and read sites are extracted from
     the code (docs/static_analysis.md, "Contracts"). -->

Every `RAFIKI_*` environment variable the code reads, extracted by the
contracts pass. `<required>` means the read raises when the variable
is unset (spawner-provided); `<dynamic>` means the fallback is
computed at runtime; `<none>` means the reader handles absence itself.

| knob | type | default(s) | read at | what it does |
|---|---|---|---|---|
"""


def generate_knobs_md(env: EnvContracts) -> str:
    rows: List[str] = []
    for knob, reads in sorted(env.by_knob().items()):
        parse = "/".join(sorted({r.parse for r in reads}))
        defaults = ", ".join(
            sorted({str(r.manifest_default()) for r in reads}))
        sites = "<br>".join(
            f"`{s}`" for s in sorted({f"{r.path}:{r.line}"
                                      for r in reads}))
        desc = KNOB_DOCS.get(knob, UNDOCUMENTED)
        rows.append(f"| `{knob}` | {parse} | `{defaults}` | {sites} "
                    f"| {desc} |")
    return _HEADER + "\n".join(rows) + "\n"
