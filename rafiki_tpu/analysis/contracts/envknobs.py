"""Env-knob registry extraction: every ``RAFIKI_*`` read, its default,
its parse type, and the subprocess spawn sites that would (or would
not) propagate it.

A *read* is ``os.environ.get("RAFIKI_X", default)`` / ``os.getenv`` /
``os.environ["RAFIKI_X"]``. The parse type is inferred from the
immediately enclosing call (``int(...)``/``float(...)``/``Path(...)``);
a non-constant default (``f"pw-{os.getpid()}"``) is recorded as dynamic
and excluded from divergence checking — only two *constant* defaults
can statically disagree.

A *spawn site* is a ``subprocess.Popen``/``run``/``check_output`` call
whose argv contains ``"-m", "<module>"``. Its env provenance is traced
within the enclosing function: ``dict(os.environ)`` /
``os.environ.copy()`` marks it inheriting (every knob rides along);
otherwise the explicitly assigned keys (``env["K"] = ...``,
``env.update({...})``) are the propagation set, and a knob read in the
spawned module's import closure but missing from that set is an RF016
unpropagated-knob violation.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from rafiki_tpu.analysis.checkers._ast_util import dotted_name, parent_map

PREFIX = "RAFIKI_"

_SPAWN_LEAVES = {"Popen", "run", "check_output", "check_call", "call"}


@dataclass
class KnobRead:
    path: str
    line: int
    knob: str
    default: Optional[str]       # repr of a constant default; None: none
    dynamic_default: bool = False  # a default exists but isn't constant
    required: bool = False       # subscript read: raises when unset
    parse: str = "str"           # int | float | str | path | flag

    def manifest_default(self) -> str:
        if self.required:
            return "<required>"
        if self.dynamic_default:
            return "<dynamic>"
        return self.default if self.default is not None else "<none>"


@dataclass
class SpawnSite:
    path: str
    line: int
    target_module: Optional[str]  # "-m" argv target, when constant
    inherits_environ: bool
    explicit_keys: Tuple[str, ...] = ()


@dataclass
class EnvContracts:
    reads: List[KnobRead] = field(default_factory=list)
    spawns: List[SpawnSite] = field(default_factory=list)

    def by_knob(self) -> Dict[str, List[KnobRead]]:
        out: Dict[str, List[KnobRead]] = {}
        for r in self.reads:
            out.setdefault(r.knob, []).append(r)
        return out

    def divergent(self) -> Dict[str, List[KnobRead]]:
        """Knobs read with more than one distinct *constant* default."""
        out: Dict[str, List[KnobRead]] = {}
        for knob, reads in self.by_knob().items():
            consts = [r for r in reads
                      if r.default is not None and not r.dynamic_default
                      and not r.required]
            if len({r.default for r in consts}) > 1:
                out[knob] = consts
        return out


# ---------------------------------------------------------------------------


def _module_consts(tree: ast.Module) -> Dict[str, object]:
    """Module-level ``NAME = <constant>`` bindings — the ``ENV_VAR =
    "RAFIKI_CHAOS"`` indirection idiom resolves through these, for
    both the knob name and the default."""
    out: Dict[str, object] = {}
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)):
            out[node.targets[0].id] = node.value.value
    return out


def _knob_name(node: Optional[ast.AST],
               consts: Dict[str, object]) -> Optional[str]:
    v: object = None
    if isinstance(node, ast.Constant):
        v = node.value
    elif isinstance(node, ast.Name):
        v = consts.get(node.id)
    elif isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        # ``ENV_PREFIX + "TIERS"`` — fold one level of constant
        # concatenation, mirroring what the helper resolver already
        # does for prefixed wrapper functions.
        left = _knob_name_part(node.left, consts)
        right = _knob_name_part(node.right, consts)
        if left is not None and right is not None:
            v = left + right
    return v if isinstance(v, str) and v.startswith(PREFIX) else None


def _knob_name_part(node: ast.AST,
                    consts: Dict[str, object]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name) and isinstance(consts.get(node.id), str):
        return str(consts[node.id])
    return None


def _env_read(node: ast.AST, consts: Dict[str, object]
              ) -> Optional[Tuple[str, Optional[ast.AST], bool]]:
    """``(knob, default_node, required)`` when ``node`` reads a
    RAFIKI_* env var."""
    if isinstance(node, ast.Call):
        dn = dotted_name(node.func)
        if dn.endswith("environ.get") or dn in ("os.getenv", "getenv"):
            knob = _knob_name(node.args[0] if node.args else None, consts)
            if knob is not None:
                default = node.args[1] if len(node.args) > 1 else None
                if default is None:
                    for k in node.keywords:
                        if k.arg == "default":
                            default = k.value
                return knob, default, False
    elif isinstance(node, ast.Subscript):
        if (dotted_name(node.value).endswith("environ")
                and isinstance(node.ctx, ast.Load)):
            knob = _knob_name(node.slice, consts)
            if knob is not None:
                return knob, None, True
    return None


_PARSE_LEAVES = {"int": "int", "float": "float", "Path": "path",
                 "bool": "flag"}


def _parse_type(node: ast.AST, parents: Dict[ast.AST, ast.AST]) -> str:
    """Walk up a couple of wrapper levels looking for int()/float()/
    Path(); ``.lower() in (...)`` membership marks a flag."""
    cur, hops = node, 0
    while cur in parents and hops < 4:
        p = parents[cur]
        if isinstance(p, ast.Call):
            # p.func.attr (not dotted_name) so chains rooted at the env
            # call itself — environ.get(...).lower() — still resolve
            leaf = (p.func.attr if isinstance(p.func, ast.Attribute)
                    else dotted_name(p.func).rsplit(".", 1)[-1])
            if leaf in _PARSE_LEAVES and p.args and p.args[0] is cur:
                return _PARSE_LEAVES[leaf]
            if leaf == "lower":
                cur, hops = p, hops + 1
                continue
        if (isinstance(p, ast.Compare) and len(p.ops) == 1
                and isinstance(p.ops[0], (ast.In, ast.NotIn))):
            return "flag"
        if isinstance(p, (ast.BinOp, ast.BoolOp, ast.Compare,
                          ast.Attribute)):
            cur, hops = p, hops + 1
            continue
        break
    return "str"


def _default_repr(node: Optional[ast.AST], consts: Dict[str, object]
                  ) -> Tuple[Optional[str], bool]:
    """(constant repr, dynamic?) for a default expression; module-level
    constants count as constant."""
    if node is None:
        return None, False
    if isinstance(node, ast.Constant):
        return repr(node.value), False
    if isinstance(node, ast.Name) and node.id in consts:
        return repr(consts[node.id]), False
    return None, True


# -- env-read helper functions ----------------------------------------------
#
# autoscale/health/perf wrap their reads in module-private helpers
# (``_env_float("TICK_S", 1.0)`` with the prefix concatenated inside,
# or ``_env_float(ENV_K, DEFAULT_K)`` with full-name constants). The
# helper body names a *parameter* so the direct pass can't see the
# knob; resolving constant-argument call sites recovers it — same
# technique as journal helper predicates.


@dataclass
class _EnvHelper:
    prefix: str                      # "" or the concatenated constant
    has_default_param: bool          # 2nd parameter supplies the default
    internal_default: Optional[str]  # env call's own constant default
    parse: str


def _helper_parse(fn: ast.FunctionDef) -> str:
    for node in ast.walk(fn):
        if (isinstance(node, ast.Compare) and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.In, ast.NotIn))):
            return "flag"
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            leaf = dotted_name(node.func).rsplit(".", 1)[-1]
            if leaf in _PARSE_LEAVES:
                return _PARSE_LEAVES[leaf]
    return "str"


def _env_helpers(tree: ast.Module, consts: Dict[str, object]
                 ) -> Dict[str, _EnvHelper]:
    out: Dict[str, _EnvHelper] = {}
    for fn in tree.body:
        if not isinstance(fn, ast.FunctionDef) or not fn.args.args:
            continue
        name_param = fn.args.args[0].arg
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if not (dn.endswith("environ.get")
                    or dn in ("os.getenv", "getenv")):
                continue
            arg = node.args[0] if node.args else None
            prefix: Optional[str] = None
            if isinstance(arg, ast.Name) and arg.id == name_param:
                prefix = ""
            elif (isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add)
                    and isinstance(arg.right, ast.Name)
                    and arg.right.id == name_param):
                left = arg.left
                if (isinstance(left, ast.Constant)
                        and isinstance(left.value, str)):
                    prefix = left.value
                elif (isinstance(left, ast.Name)
                        and isinstance(consts.get(left.id), str)):
                    prefix = str(consts[left.id])
            if prefix is None:
                continue
            internal = None
            if (len(node.args) > 1
                    and isinstance(node.args[1], ast.Constant)
                    and node.args[1].value not in (None, "")):
                internal = repr(node.args[1].value)
            out[fn.name] = _EnvHelper(
                prefix=prefix,
                has_default_param=len(fn.args.args) > 1,
                internal_default=internal,
                parse=_helper_parse(fn))
            break
    return out


def _helper_read(node: ast.AST, helpers: Dict[str, _EnvHelper],
                 consts: Dict[str, object]) -> Optional[KnobRead]:
    if not (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
            and node.func.id in helpers):
        return None
    h = helpers[node.func.id]
    a0 = node.args[0] if node.args else None
    name: Optional[str] = None
    if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
        name = a0.value
    elif isinstance(a0, ast.Name) and isinstance(consts.get(a0.id), str):
        name = str(consts[a0.id])
    if name is None:                 # dynamic name: degrade silently
        return None
    knob = h.prefix + name
    if not knob.startswith(PREFIX):
        return None
    if h.has_default_param and len(node.args) > 1:
        default, dynamic = _default_repr(node.args[1], consts)
    elif h.internal_default is not None:
        default, dynamic = h.internal_default, False
    else:
        default, dynamic = None, False
    return KnobRead(path="", line=node.lineno, knob=knob, default=default,
                    dynamic_default=dynamic, required=False, parse=h.parse)


# -- spawn-site env provenance ----------------------------------------------


def _argv_module(call: ast.Call) -> Optional[str]:
    if not call.args or not isinstance(call.args[0], (ast.List, ast.Tuple)):
        return None
    elts = call.args[0].elts
    for i, e in enumerate(elts[:-1]):
        if (isinstance(e, ast.Constant) and e.value == "-m"
                and isinstance(elts[i + 1], ast.Constant)
                and isinstance(elts[i + 1].value, str)):
            return elts[i + 1].value
    return None


def _env_provenance(fn_body: Sequence[ast.stmt], env_var: str
                    ) -> Tuple[bool, Tuple[str, ...]]:
    """(inherits_environ, explicit keys) for ``env_var`` assignments
    within the enclosing function."""
    inherits = False
    keys: Set[str] = set()
    for node in ast.walk(ast.Module(body=list(fn_body), type_ignores=[])):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == env_var:
                    v = node.value
                    dn = dotted_name(v.func) if isinstance(v, ast.Call) else ""
                    if ((dn == "dict" and v.args
                         and dotted_name(v.args[0]).endswith("environ"))
                            or dn.endswith("environ.copy")):
                        inherits = True
                    elif isinstance(v, ast.Dict):
                        keys.update(k.value for k in v.keys
                                    if isinstance(k, ast.Constant)
                                    and isinstance(k.value, str))
                elif (isinstance(t, ast.Subscript)
                      and isinstance(t.value, ast.Name)
                      and t.value.id == env_var
                      and isinstance(t.slice, ast.Constant)
                      and isinstance(t.slice.value, str)):
                    keys.add(t.slice.value)
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "update"
              and isinstance(node.func.value, ast.Name)
              and node.func.value.id == env_var):
            for a in node.args:
                if isinstance(a, ast.Dict):
                    keys.update(k.value for k in a.keys
                                if isinstance(k, ast.Constant)
                                and isinstance(k.value, str))
    return inherits, tuple(sorted(keys))


def _extract_spawns(path: str, tree: ast.Module,
                    out: EnvContracts) -> None:
    parents = parent_map(tree)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        parts = dotted_name(node.func).split(".")
        if parts[-1] not in _SPAWN_LEAVES:
            continue
        if parts[-1] != "Popen" and "subprocess" not in parts[:-1]:
            continue  # bare run()/call() that isn't subprocess's
        target = _argv_module(node)
        if target is None:
            continue
        env_kw = next((k.value for k in node.keywords if k.arg == "env"),
                      None)
        if env_kw is None:
            out.spawns.append(SpawnSite(path, node.lineno, target,
                                        inherits_environ=True))
            continue
        dn = dotted_name(env_kw) if not isinstance(env_kw, ast.Call) else \
            dotted_name(env_kw.func)
        if (isinstance(env_kw, ast.Call)
                and ((dn == "dict" and env_kw.args
                      and dotted_name(env_kw.args[0]).endswith("environ"))
                     or dn.endswith("environ.copy"))):
            out.spawns.append(SpawnSite(path, node.lineno, target,
                                        inherits_environ=True))
            continue
        if isinstance(env_kw, ast.Dict):
            keys = tuple(sorted(
                k.value for k in env_kw.keys
                if isinstance(k, ast.Constant) and isinstance(k.value, str)))
            out.spawns.append(SpawnSite(path, node.lineno, target,
                                        inherits_environ=False,
                                        explicit_keys=keys))
            continue
        if isinstance(env_kw, ast.Name):
            fn = node
            while fn in parents and not isinstance(
                    fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn = parents[fn]
            body = fn.body if isinstance(
                fn, (ast.FunctionDef, ast.AsyncFunctionDef)) else tree.body
            inherits, keys = _env_provenance(body, env_kw.id)
            out.spawns.append(SpawnSite(path, node.lineno, target,
                                        inherits_environ=inherits,
                                        explicit_keys=keys))
            continue
        # unknown provenance: assume inheriting (no false positives)
        out.spawns.append(SpawnSite(path, node.lineno, target,
                                    inherits_environ=True))


def extract_env(modules) -> EnvContracts:
    out = EnvContracts()
    # knob-name constants travel across modules (``from ...recovery
    # import ENV_RESUME_POLL_S``): build a project-wide fallback table
    # of unambiguous RAFIKI_*-valued string constants. Local constants
    # always win; a name bound to two distinct values resolves nowhere.
    global_consts: Dict[str, object] = {}
    ambiguous: Set[str] = set()
    for m in modules:
        for name, value in _module_consts(m.tree).items():
            if not (isinstance(value, str) and value.startswith(PREFIX)):
                continue
            if name in global_consts and global_consts[name] != value:
                ambiguous.add(name)
            global_consts[name] = value
    for name in ambiguous:
        del global_consts[name]
    for m in sorted(modules, key=lambda m: m.path):
        parents = parent_map(m.tree)
        consts = dict(global_consts)
        consts.update(_module_consts(m.tree))
        helpers = _env_helpers(m.tree, consts)
        for node in ast.walk(m.tree):
            hr = _helper_read(node, helpers, consts)
            if hr is not None:
                hr.path = m.path
                out.reads.append(hr)
                continue
            got = _env_read(node, consts)
            if got is None:
                continue
            knob, default_node, required = got
            default, dynamic = _default_repr(default_node, consts)
            out.reads.append(KnobRead(
                path=m.path, line=node.lineno, knob=knob,
                default=default, dynamic_default=dynamic,
                required=required,
                parse=_parse_type(node, parents)))
        _extract_spawns(m.path, m.tree, out)
    out.reads.sort(key=lambda r: (r.knob, r.path, r.line))
    out.spawns.sort(key=lambda s: (s.path, s.line))
    return out


def knobs_in_closure(project_modules: Dict[str, "object"],
                     imports_of, target_module: str,
                     env: EnvContracts) -> Dict[str, List[KnobRead]]:
    """Knob reads reachable from ``target_module`` through the analyzed
    import graph (the spawned child's static read set)."""
    closure: Set[str] = set()
    frontier = [target_module]
    while frontier:
        name = frontier.pop()
        if name in closure or name not in project_modules:
            continue
        closure.add(name)
        for imp in imports_of(project_modules[name].tree):
            # an import of rafiki_tpu.x.y also pulls rafiki_tpu.x
            parts = imp.split(".")
            for i in range(1, len(parts) + 1):
                frontier.append(".".join(parts[:i]))
    paths = {m.path for name, m in project_modules.items()
             if name in closure}
    out: Dict[str, List[KnobRead]] = {}
    for r in env.reads:
        if r.path in paths:
            out.setdefault(r.knob, []).append(r)
    return out
