import sys

from rafiki_tpu.analysis.cli import main

sys.exit(main())
