"""``python -m rafiki_tpu.analysis [paths] [--format json|text]
[--select RF001,RF002] [--show-suppressed]``.

Exit code 0 when every finding is suppressed (with justification), 1
when unsuppressed findings remain, 2 on usage/parse errors —
scripts/check_lint.sh turns that into the tier-1 gate.

``--contracts`` switches to contract-extraction mode: instead of
findings it emits the whole-program contracts manifest (journal
writer/reader joins, env-knob registry, telemetry names) as
byte-deterministic JSON; with ``--docs`` it emits the generated
docs/knobs.md instead. check_lint.sh diffs both against the committed
copies (tests/data/contracts_manifest.json, docs/knobs.md), so
contract drift fails the gate as a reviewable diff.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional, Sequence

from rafiki_tpu.analysis.core import (
    REGISTRY, AnalysisResult, analyze_paths, load_builtin_checkers)

DEFAULT_PATHS = ["rafiki_tpu", "bench.py", "scripts"]


def _format_text(result: AnalysisResult, show_suppressed: bool) -> List[str]:
    out = []
    for f in result.findings:
        if f.suppressed and not show_suppressed:
            continue
        tag = " (suppressed: %s)" % f.justification if f.suppressed else ""
        out.append(f"{f.path}:{f.line}:{f.col}: {f.checker_id} "
                   f"[{f.severity}] {f.message}{tag}")
    n = len(result.unsuppressed)
    n_sup = len(result.findings) - n
    out.append(f"{result.files_analyzed} files analyzed: {n} finding(s), "
               f"{n_sup} suppressed")
    return out


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m rafiki_tpu.analysis",
        description="rafiki-tpu repo-specific static analysis")
    parser.add_argument("paths", nargs="*", default=None,
                        help=f"files/dirs to analyze (default: "
                             f"{' '.join(DEFAULT_PATHS)})")
    parser.add_argument("--format", choices=("text", "json"), default="text")
    parser.add_argument("--select", default=None,
                        help="comma-separated checker ids to run")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="include suppressed findings in text output")
    parser.add_argument("--list-checkers", action="store_true")
    parser.add_argument("--contracts", action="store_true",
                        help="emit the whole-program contracts manifest "
                             "(deterministic JSON) instead of findings")
    parser.add_argument("--docs", action="store_true",
                        help="with --contracts: emit the generated "
                             "docs/knobs.md instead of the manifest")
    args = parser.parse_args(argv)

    if args.docs and not args.contracts:
        print("--docs requires --contracts", file=sys.stderr)
        return 2
    if args.contracts:
        from rafiki_tpu.analysis.contracts import generate_knobs_md
        from rafiki_tpu.analysis.contracts.envknobs import extract_env
        from rafiki_tpu.analysis.contracts.manifest import (
            _load_modules, dump_manifest, manifest_for_paths)

        paths = args.paths or DEFAULT_PATHS
        if args.docs:
            import os
            env = extract_env(_load_modules(paths, root=os.getcwd()))
            sys.stdout.write(generate_knobs_md(env))
        else:
            sys.stdout.write(dump_manifest(manifest_for_paths(paths)))
        return 0

    load_builtin_checkers()
    if args.list_checkers:
        for cid in sorted(REGISTRY):
            cls = REGISTRY[cid]
            print(f"{cid} {cls.name} [{cls.severity}] — {cls.rationale}")
        return 0

    select = ([s.strip() for s in args.select.split(",") if s.strip()]
              if args.select else None)
    if select:
        unknown = [s for s in select if s not in REGISTRY]
        if unknown:
            print(f"unknown checker id(s): {', '.join(unknown)}",
                  file=sys.stderr)
            return 2
    result = analyze_paths(args.paths or DEFAULT_PATHS, select=select)

    if args.format == "json":
        print(json.dumps({
            "files_analyzed": result.files_analyzed,
            "parse_errors": result.parse_errors,
            "findings": [f.to_dict() for f in result.findings],
            "unsuppressed": len(result.unsuppressed),
        }, indent=2))
    else:
        for line in _format_text(result, args.show_suppressed):
            print(line)
        for err in result.parse_errors:
            print(f"parse error: {err}", file=sys.stderr)
    if result.parse_errors:
        return 2
    return 1 if result.unsuppressed else 0


if __name__ == "__main__":
    sys.exit(main())
