"""Repo-specific static analysis (``python -m rafiki_tpu.analysis``).

Each checker encodes a failure class this repo actually shipped; see
docs/static_analysis.md for the catalog. Import surface:

    from rafiki_tpu.analysis import analyze_paths, load_builtin_checkers
    load_builtin_checkers()
    result = analyze_paths(["rafiki_tpu"])

NOTE: this package must stay importable without jax — it runs in CI
paths where the TPU tunnel (and thus backend init) may be down.
"""

from rafiki_tpu.analysis.core import (  # noqa: F401
    REGISTRY, AnalysisResult, Checker, Finding, ModuleContext,
    ProjectContext, analyze_paths, load_builtin_checkers, register)
