"""Client: the user-facing SDK over the admin REST API.

Reference parity: rafiki/client/client.py (unverified — SURVEY.md §2):
`Client` with login, create_user, create_model (uploads the model .py),
create_train_job, get_train_job, get_best_trials_of_train_job,
get_trial_logs, create_inference_job, stop_* — same verb names here so
reference user scripts translate 1:1.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Dict, List, Optional

import requests


class ClientError(Exception):
    def __init__(self, status: int, message: str):
        super().__init__(f"HTTP {status}: {message}")
        self.status = status
        self.message = message


class Client:
    def __init__(self, admin_host: str = "127.0.0.1", admin_port: int = 3000):
        self._base = f"http://{admin_host}:{admin_port}"
        self._token: Optional[str] = None
        self._session = requests.Session()

    # -- plumbing ------------------------------------------------------------

    def _headers(self) -> Dict[str, str]:
        return {"Authorization": f"Bearer {self._token}"} if self._token else {}

    def _request(self, method: str, path: str, **kwargs) -> Any:
        resp = self._session.request(method, self._base + path,
                                     headers=self._headers(), **kwargs)
        if resp.status_code >= 400:
            try:
                message = resp.json().get("error", resp.text)
            except (ValueError, AttributeError):
                message = resp.text
            raise ClientError(resp.status_code, message)
        ctype = resp.headers.get("Content-Type", "")
        return resp.json() if "json" in ctype else resp.content

    def _get(self, path: str, params: Optional[dict] = None) -> Any:
        return self._request("GET", path, params=params)

    def _post(self, path: str, body: Optional[dict] = None,
              files: Optional[dict] = None, data: Optional[dict] = None) -> Any:
        if files is not None:
            return self._request("POST", path, files=files, data=data)
        return self._request("POST", path, json=body or {})

    # -- auth / users --------------------------------------------------------

    def login(self, email: str, password: str) -> Dict[str, Any]:
        out = self._post("/tokens", {"email": email, "password": password})
        self._token = out["token"]
        return out

    def logout(self) -> None:
        self._token = None

    def create_user(self, email: str, password: str, user_type: str) -> dict:
        return self._post("/users", {"email": email, "password": password,
                                     "user_type": user_type})

    def get_users(self) -> List[dict]:
        return self._get("/users")

    def ban_user(self, email: str) -> dict:
        return self._request("DELETE", "/users", json={"email": email})

    # -- models --------------------------------------------------------------

    def create_model(self, name: str, task: str, model_file_path: str | Path,
                     model_class: str, dependencies: Optional[dict] = None,
                     access_right: str = "PRIVATE", docs: str = "") -> dict:
        """Upload a model template .py (multipart, like the reference)."""
        with open(model_file_path, "rb") as f:
            return self._post(
                "/models",
                files={"model_file": (Path(model_file_path).name, f)},
                data={"name": name, "task": task, "model_class": model_class,
                      "dependencies": json.dumps(dependencies or {}),
                      "access_right": access_right, "docs": docs})

    def get_models(self, task: Optional[str] = None) -> List[dict]:
        return self._get("/models", params={"task": task} if task else None)

    def get_model(self, name: str) -> dict:
        return self._get(f"/models/{name}")

    def download_model_file(self, name: str) -> bytes:
        return self._get(f"/models/{name}/file")

    # -- train jobs ----------------------------------------------------------

    def create_train_job(self, app: str, task: str, train_dataset_uri: str,
                         val_dataset_uri: str, budget: Dict[str, Any],
                         model_names: Optional[List[str]] = None,
                         advisor_kind: str = "gp",
                         devices_per_trial: int = 1) -> dict:
        return self._post("/train_jobs", {
            "app": app, "task": task, "train_dataset_uri": train_dataset_uri,
            "val_dataset_uri": val_dataset_uri, "budget": budget,
            "model_names": model_names, "advisor_kind": advisor_kind,
            "devices_per_trial": devices_per_trial})

    def get_train_jobs(self) -> List[dict]:
        return self._get("/train_jobs")

    def _vpath(self, prefix: str, app: str, app_version: int, suffix: str = "") -> str:
        """-1 (or 0) means "latest version" — the server resolves it."""
        if app_version > 0:
            return f"{prefix}/{app}/{app_version}{suffix}"
        return f"{prefix}/{app}{suffix}"

    def get_train_job(self, app: str, app_version: int = -1) -> dict:
        return self._get(self._vpath("/train_jobs", app, app_version))

    def stop_train_job(self, app: str, app_version: int = -1) -> dict:
        return self._post(self._vpath("/train_jobs", app, app_version, "/stop"))

    def wait_until_train_job_has_stopped(self, app: str, app_version: int = -1,
                                         timeout: float = 3600.0,
                                         poll_s: float = 1.0) -> dict:
        """Poll until the job leaves STARTED/RUNNING (reference clients
        poll the same way)."""
        deadline = time.monotonic() + timeout
        while True:
            job = self.get_train_job(app, app_version)
            if job["status"] not in ("STARTED", "RUNNING"):
                return job
            if time.monotonic() > deadline:
                raise TimeoutError(f"Train job {app} still {job['status']}")
            time.sleep(poll_s)

    # -- trials --------------------------------------------------------------

    def get_trials_of_train_job(self, app: str, app_version: int = -1) -> List[dict]:
        return self._get(self._vpath("/train_jobs", app, app_version, "/trials"))

    def get_best_trials_of_train_job(self, app: str, app_version: int = -1,
                                     max_count: int = 2) -> List[dict]:
        return self._get(self._vpath("/train_jobs", app, app_version, "/trials"),
                         params={"type": "best", "max_count": max_count})

    def get_trial(self, trial_id: str) -> dict:
        return self._get(f"/trials/{trial_id}")

    def get_trial_logs(self, trial_id: str) -> List[dict]:
        return self._get(f"/trials/{trial_id}/logs")

    def get_trial_parameters(self, trial_id: str) -> bytes:
        return self._get(f"/trials/{trial_id}/parameters")

    # -- inference jobs ------------------------------------------------------

    def create_inference_job(self, app: str, app_version: int = -1,
                             max_models: int = 2,
                             gateway: Optional[dict] = None) -> dict:
        """``gateway`` carries per-job serving-gateway overrides —
        routing policy and admission limits (docs/serving.md)."""
        body = {"app": app, "app_version": app_version,
                "max_models": max_models}
        if gateway is not None:
            body["gateway"] = gateway
        return self._post("/inference_jobs", body)

    def get_inference_job(self, app: str, app_version: int = -1) -> dict:
        return self._get(self._vpath("/inference_jobs", app, app_version))

    def stop_inference_job(self, app: str, app_version: int = -1) -> dict:
        return self._post(self._vpath("/inference_jobs", app, app_version, "/stop"))

    def predict(self, app: str, queries: List[Any],
                app_version: int = -1) -> List[Any]:
        out = self._post(f"/predict/{app}",
                         {"queries": queries, "app_version": app_version})
        return out["predictions"]

    def predict_via_predictor(self, predictor_host: str,
                              queries: List[Any]) -> List[Any]:
        """POST straight to an inference job's published predictor
        endpoint (``get_inference_job()['predictor_host']``) — the
        reference's per-job predictor port, bypassing the admin."""
        resp = self._session.post(f"http://{predictor_host}/predict",
                                  json={"queries": queries}, timeout=60)
        if resp.status_code >= 400:
            try:
                message = resp.json().get("error", resp.text)
            except ValueError:
                message = resp.text
            raise ClientError(resp.status_code, message)
        return resp.json()["predictions"]
