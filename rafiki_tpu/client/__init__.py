"""Client SDK for the rafiki-tpu control plane.

Reference parity: rafiki/client/ (unverified — SURVEY.md §1 L7).
"""

from rafiki_tpu.client.client import Client, ClientError

__all__ = ["Client", "ClientError"]
