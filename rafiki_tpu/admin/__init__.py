"""Control plane: admin business logic, services manager, REST app.

Reference parity: rafiki/admin/ (unverified — SURVEY.md §1 L4):
`Admin` business-logic class + Flask REST app + `ServicesManager`
translating jobs into Docker Swarm services. Here the "services" are
in-host threads/processes over the TPU chips — no containers needed.
"""

from rafiki_tpu.admin.admin import Admin
from rafiki_tpu.admin.services_manager import ServicesManager

__all__ = ["Admin", "ServicesManager"]
