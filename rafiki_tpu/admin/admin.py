"""Admin: the control-plane business logic.

Reference parity: rafiki/admin/admin.py (unverified — SURVEY.md §2):
user/model/job lifecycle — create_user, create_model (validated on
upload), create_train_job (budget validation, model selection for the
task), stop_train_job, create_inference_job over the top-k best trials,
trial queries, superadmin seeding. The REST app (app.py) is a thin
shim over this class; it is equally usable in-process (tests, single-
host deployments drive it directly — no HTTP needed for parity).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from rafiki_tpu.admin.services_manager import ServicesManager
from rafiki_tpu.config import Config, get_config
from rafiki_tpu.constants import (
    BudgetType,
    InferenceJobStatus,
    TrainJobStatus,
    UserType,
)
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.model.knobs import serialize_knob_config
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.auth import (
    AuthError,
    generate_token,
    hash_password,
    verify_password,
)

_VALID_BUDGET_KEYS = {b.value for b in BudgetType}


class NotFoundError(KeyError):
    """Entity lookup failed (distinct from a missing-request-field
    KeyError so the REST layer can map them to 404 vs 400)."""

    def __str__(self) -> str:  # KeyError repr-quotes its arg; keep it readable
        return self.args[0] if self.args else "Not found"


class Admin:
    def __init__(self, config: Optional[Config] = None,
                 store: Optional[MetaStore] = None,
                 params_store: Optional[ParamsStore] = None,
                 services: Optional[ServicesManager] = None):
        self.config = (config or get_config()).ensure_dirs()
        self.store = store or MetaStore(self.config.db_path)
        self.params_store = params_store or ParamsStore(self.config.params_dir)
        self.services = services or ServicesManager(
            self.store, self.params_store, config=self.config)
        # Serializes inference-job creation per process: the duplicate
        # check below is check-then-act and the REST server is threaded.
        self._inference_lock = threading.Lock()
        from rafiki_tpu.utils.events import events

        events.configure(self.config.logs_dir)
        self._seed_superadmin()

    def _seed_superadmin(self) -> None:
        if self.store.get_user_by_email(self.config.superadmin_email) is None:
            self.store.create_user(
                self.config.superadmin_email,
                hash_password(self.config.superadmin_password),
                UserType.SUPERADMIN.value)

    # -- auth / users --------------------------------------------------------

    def authenticate_user(self, email: str, password: str) -> Dict[str, Any]:
        """Check credentials; returns a dict with a JWT ``token``."""
        user = self.store.get_user_by_email(email)
        if user is None or not verify_password(password, user["password_hash"]):
            raise AuthError("Invalid email or password")
        if user["banned"]:
            raise AuthError("User is banned")
        token = generate_token(
            {"user_id": user["id"], "user_type": user["user_type"]},
            self.config.jwt_secret, ttl_s=self.config.jwt_ttl_hours * 3600)
        return {"user_id": user["id"], "user_type": user["user_type"], "token": token}

    def create_user(self, email: str, password: str, user_type: str) -> Dict[str, Any]:
        if user_type not in {u.value for u in UserType}:
            raise ValueError(f"Invalid user type {user_type!r}")
        if self.store.get_user_by_email(email) is not None:
            raise ValueError(f"User {email!r} already exists")
        user = self.store.create_user(email, hash_password(password), user_type)
        return _public_user(user)

    def get_users(self) -> List[Dict[str, Any]]:
        return [_public_user(u) for u in self.store.get_users()]

    def ban_user(self, email: str) -> Dict[str, Any]:
        user = self.store.get_user_by_email(email)
        if user is None:
            raise NotFoundError(f"No user {email!r}")
        self.store.ban_user(user["id"])
        return _public_user({**user, "banned": 1})

    # -- models --------------------------------------------------------------

    def create_model(self, user_id: Optional[str], name: str, task: str,
                     model_file: bytes, model_class: str,
                     dependencies: Optional[Dict[str, str]] = None,
                     access_right: str = "PRIVATE", docs: str = "") -> Dict[str, Any]:
        """Validate the template on upload (the reference does the same):
        the class must load and its knob config must serialize."""
        try:
            cls = load_model_class(model_file, model_class)
            serialize_knob_config(cls.get_knob_config())
        except Exception as e:
            raise ValueError(f"Invalid model template: {e}") from e
        row = self.store.create_model(name, task, user_id, model_file, model_class,
                                      dependencies, access_right, docs)
        return _public_model(row)

    def get_model(self, name: str) -> Dict[str, Any]:
        row = self.store.get_model_by_name(name)
        if row is None:
            raise NotFoundError(f"No model {name!r}")
        return _public_model(row)

    def get_model_file(self, name: str, requester_id: Optional[str] = None,
                       requester_type: Optional[str] = None) -> bytes:
        """Template source download. PRIVATE models are readable only by
        their owner (or an admin); pass requester_* from the auth layer
        — ``None`` means a trusted in-process caller."""
        row = self.store.get_model_by_name(name)
        if row is None:
            raise NotFoundError(f"No model {name!r}")
        if (requester_type is not None
                and requester_type not in (UserType.SUPERADMIN.value,
                                           UserType.ADMIN.value)
                and row["access_right"] == "PRIVATE"
                and row["user_id"] is not None
                and row["user_id"] != requester_id):
            raise AuthError(f"Model {name!r} is private")
        return row["model_file"]

    def get_models(self, task: Optional[str] = None) -> List[Dict[str, Any]]:
        if task:
            return [_public_model(m) for m in self.store.get_models_of_task(task)]
        return [_public_model(m) for m in self.store.get_models()]

    # -- train jobs ----------------------------------------------------------

    def create_train_job(self, user_id: Optional[str], app: str, task: str,
                         train_dataset_uri: str, val_dataset_uri: str,
                         budget: Dict[str, Any],
                         model_names: Optional[List[str]] = None,
                         advisor_kind: str = "gp",
                         devices_per_trial: int = 1,
                         start: bool = True) -> Dict[str, Any]:
        bad = set(budget) - _VALID_BUDGET_KEYS
        if bad:
            raise ValueError(f"Unknown budget keys {sorted(bad)}; valid: "
                             f"{sorted(_VALID_BUDGET_KEYS)}")
        if not budget:
            raise ValueError("Budget must not be empty "
                             "(e.g. {'MODEL_TRIAL_COUNT': 5})")

        if model_names:
            models = []
            for n in model_names:
                m = self.store.get_model_by_name(n)
                if m is None:
                    raise NotFoundError(f"No model {n!r}")
                models.append(m)
        else:
            models = self.store.get_models_of_task(task)
        if not models:
            raise ValueError(f"No models available for task {task!r}")

        job = self.store.create_train_job(app, task, user_id, train_dataset_uri,
                                          val_dataset_uri, budget)
        for m in models:
            self.store.create_sub_train_job(job["id"], m["id"])
        if start:
            self.services.create_train_services(
                job["id"], advisor_kind=advisor_kind,
                devices_per_trial=devices_per_trial)
        return _public_train_job(job)

    def get_train_job(self, app: str, app_version: int = -1,
                      user_id: Optional[str] = None) -> Dict[str, Any]:
        job = self.store.get_train_job_by_app(app, app_version, user_id)
        if job is None:
            raise NotFoundError(f"No train job for app {app!r}")
        out = _public_train_job(job)
        out["sub_train_jobs"] = [
            {"id": s["id"], "model_id": s["model_id"], "status": s["status"]}
            for s in self.store.get_sub_train_jobs(job["id"])]
        out["services"] = self.store.get_services_of_job(job["id"])
        return out

    def get_train_jobs(self, user_id: Optional[str] = None) -> List[Dict[str, Any]]:
        return [_public_train_job(j) for j in self.store.get_train_jobs(user_id)]

    def stop_train_job(self, app: str, app_version: int = -1,
                       user_id: Optional[str] = None) -> Dict[str, Any]:
        job = self.store.get_train_job_by_app(app, app_version, user_id)
        if job is None:
            raise NotFoundError(f"No train job for app {app!r}")
        self.services.stop_train_services(job["id"])
        return _public_train_job(self.store.get_train_job(job["id"]))

    def wait_train_job(self, app: str, app_version: int = -1,
                       timeout: Optional[float] = None) -> Dict[str, Any]:
        """Convenience (not in the reference's REST surface): block until
        the job finishes — tests and scripts poll less this way."""
        job = self.store.get_train_job_by_app(app, app_version)
        if job is None:
            raise NotFoundError(f"No train job for app {app!r}")
        self.services.wait_train_job(job["id"], timeout=timeout)
        return self.get_train_job(app, app_version)

    # -- trials --------------------------------------------------------------

    def get_trials_of_train_job(self, app: str, app_version: int = -1) -> List[dict]:
        job = self.store.get_train_job_by_app(app, app_version)
        if job is None:
            raise NotFoundError(f"No train job for app {app!r}")
        return [_public_trial(t) for t in self.store.get_trials_of_train_job(job["id"])]

    def get_best_trials_of_train_job(self, app: str, app_version: int = -1,
                                     max_count: int = 2) -> List[dict]:
        job = self.store.get_train_job_by_app(app, app_version)
        if job is None:
            raise NotFoundError(f"No train job for app {app!r}")
        return [_public_trial(t) for t in
                self.store.get_best_trials_of_train_job(job["id"], limit=max_count)]

    def get_trial(self, trial_id: str) -> dict:
        t = self.store.get_trial(trial_id)
        if t is None:
            raise NotFoundError(f"No trial {trial_id!r}")
        return _public_trial(t)

    def get_trial_logs(self, trial_id: str) -> List[dict]:
        return self.store.get_trial_logs(trial_id)

    def get_trial_parameters(self, trial_id: str) -> bytes:
        t = self.store.get_trial(trial_id)
        if t is None or not t.get("params_id"):
            raise NotFoundError(f"No parameters for trial {trial_id!r}")
        return self.params_store.load(t["params_id"])

    # -- inference jobs ------------------------------------------------------

    def create_inference_job(self, user_id: Optional[str], app: str,
                             app_version: int = -1,
                             max_models: int = 2,
                             gateway: Optional[Dict[str, Any]] = None
                             ) -> Dict[str, Any]:
        job = self.store.get_train_job_by_app(app, app_version, user_id)
        if job is None:
            raise NotFoundError(f"No train job for app {app!r}")
        if job["status"] not in (TrainJobStatus.COMPLETED.value,
                                 TrainJobStatus.STOPPED.value):
            raise ValueError(
                f"Train job for {app!r} is {job['status']}; wait for it to finish")
        with self._inference_lock:
            existing = self.store.get_inference_job_of_train_job(job["id"])
            if existing is not None:
                raise ValueError(f"App {app!r} already has a running inference job")
            best = self.store.get_best_trials_of_train_job(job["id"], limit=max_models)
            if not best:
                raise ValueError(f"No completed trials for app {app!r}")
            inf = self.store.create_inference_job(job["id"], user_id)
            try:
                self.services.create_inference_services(
                    inf["id"], best, gateway_overrides=gateway)
            except Exception:
                self.store.update_inference_job(inf["id"],
                                                status=InferenceJobStatus.ERRORED.value)
                raise
        return self.get_inference_job(app, app_version)

    def get_inference_job(self, app: str, app_version: int = -1,
                          user_id: Optional[str] = None) -> Dict[str, Any]:
        job = self.store.get_train_job_by_app(app, app_version, user_id)
        if job is None:
            raise NotFoundError(f"No train job for app {app!r}")
        inf = self.store.get_inference_job_of_train_job(job["id"])
        if inf is None:
            raise NotFoundError(f"No running inference job for app {app!r}")
        return {**inf, "app": app, "app_version": job["app_version"]}

    def stop_inference_job(self, app: str, app_version: int = -1,
                           user_id: Optional[str] = None) -> Dict[str, Any]:
        inf = self.get_inference_job(app, app_version, user_id)
        self.services.stop_inference_services(inf["id"])
        return {**inf, "status": InferenceJobStatus.STOPPED.value}

    def predict(self, app: str, queries: List[Any],
                app_version: int = -1) -> List[Any]:
        """Route queries to the app's live predictor (in-proc path; the
        HTTP path hits the predictor app directly). Goes through the
        serving gateway so the in-proc path gets the same admission
        control and quorum gather as external HTTP traffic."""
        inf = self.get_inference_job(app, app_version)
        gateway = self.services.get_gateway(inf["id"])
        if gateway is None:
            raise RuntimeError(f"Inference job {inf['id']} has no live predictor "
                               "in this process")
        return gateway.predict(queries)

    # -- recovery ------------------------------------------------------------

    def recover_trials(self, stale_after_s: Optional[float] = None,
                       wait: bool = True) -> List[dict]:
        """Sweep for orphaned RUNNING trials (dead/silent workers) and
        re-run them, resuming from mid-trial checkpoints when present.

        ``wait=False`` detects and claims the orphans, then re-runs
        them in a background thread (re-training can take minutes —
        too long for an HTTP request); the returned rows are the
        adopted trials, freshly RUNNING."""
        from rafiki_tpu.scheduler.recovery import recover_orphaned_trials

        stale = stale_after_s if stale_after_s is not None \
            else self.config.worker_stale_after_s
        orphans = self.store.get_orphaned_trials(stale)
        if not orphans:
            return []
        if wait:
            return [_public_trial(t) for t in
                    recover_orphaned_trials(self.store, self.params_store,
                                            stale_after_s=stale,
                                            orphans=orphans)]
        threading.Thread(
            target=recover_orphaned_trials,
            args=(self.store, self.params_store),
            kwargs={"stale_after_s": stale, "orphans": orphans},
            name="recovery-sweep", daemon=True).start()
        return [_public_trial(t) for t in orphans]

    # -- lifecycle -----------------------------------------------------------

    def stop(self) -> None:
        self.services.stop_all()
        self.store.close()


# -- row shapers (strip secrets/blobs from API responses) ---------------------


def _public_user(u: dict) -> dict:
    return {"id": u["id"], "email": u["email"], "user_type": u["user_type"],
            "banned": bool(u["banned"])}


def _public_model(m: dict) -> dict:
    return {k: m[k] for k in
            ("id", "name", "task", "user_id", "model_class", "dependencies",
             "access_right", "docs", "created_at")}


def _public_train_job(j: dict) -> dict:
    return {k: j[k] for k in
            ("id", "app", "app_version", "task", "user_id", "train_dataset_uri",
             "val_dataset_uri", "budget", "status", "created_at", "stopped_at")}


def _public_trial(t: dict) -> dict:
    return {k: t[k] for k in
            ("id", "no", "model_name", "knobs", "status", "score", "params_id",
             "worker_id", "error", "started_at", "stopped_at")}
