"""Admin REST app: HTTP surface over the Admin business logic.

Reference parity: rafiki/admin/app.py (unverified — SURVEY.md §2):
Flask routes mapping REST verbs onto `Admin`, with a JWT auth
decorator per route and multipart model upload. This environment has
no Flask, so the app is a small werkzeug WSGI application (werkzeug is
Flask's own HTTP core, so request/response semantics are identical).

Route table (mirrors the reference's client verbs):
  POST /tokens                       login → JWT
  POST /users                        create user            (admin)
  GET  /users                        list users             (admin)
  DELETE /users                      ban user               (admin)
  POST /models                       upload model template  (model dev)
  GET  /models                       list models
  GET  /models/<name>                model detail
  GET  /models/<name>/file           download template bytes
  POST /train_jobs                   create train job       (app dev)
  GET  /train_jobs                   list my train jobs
  GET  /train_jobs/<app>            latest job of app
  GET  /train_jobs/<app>/<v>        specific version
  POST /train_jobs/<app>/<v>/stop   stop job
  GET  /train_jobs/<app>/<v>/trials  trials (?type=best&max_count=k)
  GET  /trials/<id>                  trial detail
  GET  /trials/<id>/logs             trial logs
  GET  /trials/<id>/parameters       trained params blob
  POST /inference_jobs               deploy app             (app dev)
  GET  /inference_jobs/<app>/<v>     inference job detail
  POST /inference_jobs/<app>/<v>/stop
  POST /predict/<app>                run queries through the ensemble
  GET  /advisors/<id>/propose, POST /advisors/<id>/feedback
                                     (for process-per-chip workers)
  GET  /                             web admin UI (static SPA)
  GET  /healthz                      liveness
  GET  /metrics                      telemetry snapshot (read-only JSON;
                                     ?format=prom for Prometheus text)
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from werkzeug.exceptions import HTTPException, NotFound
from werkzeug.routing import Map, Rule
from werkzeug.wrappers import Request, Response

from rafiki_tpu.admin.admin import Admin, NotFoundError
from rafiki_tpu.constants import UserType
from rafiki_tpu.utils.auth import AuthError, check_user_type, decode_token
from rafiki_tpu.utils.jsonable import jsonable as _jsonable

_WEB_DIR = Path(__file__).resolve().parent.parent / "web"


def _json(data: Any, status: int = 200) -> Response:
    return Response(json.dumps(data), status=status, mimetype="application/json")


class AdminApp:
    """WSGI app. ``werkzeug.serving.make_server(host, port, app)`` to run."""

    def __init__(self, admin: Admin):
        self.admin = admin
        self.url_map = Map([
            Rule("/", endpoint="web_index", methods=["GET"]),
            Rule("/healthz", endpoint="healthz", methods=["GET"]),
            Rule("/metrics", endpoint="metrics", methods=["GET"]),
            Rule("/tokens", endpoint="login", methods=["POST"]),
            Rule("/users", endpoint="create_user", methods=["POST"]),
            Rule("/users", endpoint="get_users", methods=["GET"]),
            Rule("/users", endpoint="ban_user", methods=["DELETE"]),
            Rule("/models", endpoint="create_model", methods=["POST"]),
            Rule("/models", endpoint="get_models", methods=["GET"]),
            Rule("/models/<name>", endpoint="get_model", methods=["GET"]),
            Rule("/models/<name>/file", endpoint="get_model_file", methods=["GET"]),
            Rule("/train_jobs", endpoint="create_train_job", methods=["POST"]),
            Rule("/train_jobs", endpoint="get_train_jobs", methods=["GET"]),
            Rule("/train_jobs/<app>", endpoint="get_train_job", methods=["GET"]),
            Rule("/train_jobs/<app>/<int:app_version>", endpoint="get_train_job",
                 methods=["GET"]),
            Rule("/train_jobs/<app>/stop", endpoint="stop_train_job",
                 methods=["POST"]),
            Rule("/train_jobs/<app>/<int:app_version>/stop",
                 endpoint="stop_train_job", methods=["POST"]),
            Rule("/train_jobs/<app>/trials", endpoint="get_trials",
                 methods=["GET"]),
            Rule("/train_jobs/<app>/<int:app_version>/trials",
                 endpoint="get_trials", methods=["GET"]),
            Rule("/trials/<trial_id>", endpoint="get_trial", methods=["GET"]),
            Rule("/trials/<trial_id>/logs", endpoint="get_trial_logs", methods=["GET"]),
            Rule("/trials/<trial_id>/parameters", endpoint="get_trial_parameters",
                 methods=["GET"]),
            Rule("/inference_jobs", endpoint="create_inference_job", methods=["POST"]),
            Rule("/inference_jobs/<app>", endpoint="get_inference_job",
                 methods=["GET"]),
            Rule("/inference_jobs/<app>/<int:app_version>",
                 endpoint="get_inference_job", methods=["GET"]),
            Rule("/inference_jobs/<app>/stop", endpoint="stop_inference_job",
                 methods=["POST"]),
            Rule("/inference_jobs/<app>/<int:app_version>/stop",
                 endpoint="stop_inference_job", methods=["POST"]),
            Rule("/predict/<app>", endpoint="predict", methods=["POST"]),
            Rule("/recovery", endpoint="recover", methods=["POST"]),
            Rule("/advisors/<advisor_id>/propose", endpoint="advisor_propose",
                 methods=["GET"]),
            Rule("/advisors/<advisor_id>/feedback", endpoint="advisor_feedback",
                 methods=["POST"]),
        ])

    # -- wsgi ----------------------------------------------------------------

    def __call__(self, environ, start_response):
        request = Request(environ)
        try:
            adapter = self.url_map.bind_to_environ(environ)
            endpoint, args = adapter.match()
            response = getattr(self, f"ep_{endpoint}")(request, **args)
        except NotFound:
            response = _json({"error": "Not found"}, 404)
        except HTTPException as e:
            response = _json({"error": e.description}, e.code or 500)
        except AuthError as e:
            response = _json({"error": str(e)}, 401)
        except NotFoundError as e:
            response = _json({"error": str(e)}, 404)
        except ValueError as e:
            response = _json({"error": str(e)}, 400)
        except Exception as e:  # don't leak stack traces to clients
            response = _json({"error": f"Internal error: {type(e).__name__}: {e}"}, 500)
        return response(environ, start_response)

    # -- auth helper ---------------------------------------------------------

    def _auth(self, request: Request,
              user_types: Optional[List[str]] = None) -> Dict[str, Any]:
        header = request.headers.get("Authorization", "")
        if not header.startswith("Bearer "):
            raise AuthError("Missing Bearer token")
        payload = decode_token(header[len("Bearer "):], self.admin.config.jwt_secret)
        if user_types is not None:
            check_user_type(payload.get("user_type", ""), user_types)
        return payload

    @staticmethod
    def _scope(user: Dict[str, Any]) -> Optional[str]:
        """Ownership scope for mutations: admins act on any user's jobs,
        developers only on their own."""
        if user.get("user_type") in (UserType.SUPERADMIN.value, UserType.ADMIN.value):
            return None
        return user.get("user_id")

    @staticmethod
    def _field(body: Dict[str, Any], key: str) -> Any:
        """Required request field; absence is the caller's fault (400)."""
        if key not in body:
            raise ValueError(f"Missing required field: {key}")
        return body[key]

    @staticmethod
    def _body(request: Request) -> Dict[str, Any]:
        if request.mimetype == "application/json":
            return request.get_json(force=True, silent=True) or {}
        # multipart/form-urlencoded: values arrive as strings; JSON-decode
        # the ones the API defines as structured.
        out: Dict[str, Any] = dict(request.form)
        for key in ("budget", "dependencies", "model_names", "queries", "knobs"):
            if key in out and isinstance(out[key], str):
                try:
                    out[key] = json.loads(out[key])
                except json.JSONDecodeError:
                    pass
        return out

    # -- endpoints -----------------------------------------------------------

    def ep_healthz(self, request: Request) -> Response:
        return _json({"status": "ok"})

    def ep_metrics(self, request: Request) -> Response:
        # Read-only process introspection, unauthenticated like
        # /healthz: the snapshot carries timings and counts, never
        # trial data or credentials. ?format=prom serves the same
        # snapshot in Prometheus text exposition for scrapers.
        from rafiki_tpu import telemetry

        if request.args.get("format") == "prom":
            from rafiki_tpu.obs import prom

            return Response(prom.to_prometheus(telemetry.snapshot()),
                            mimetype="text/plain; version=0.0.4")
        return _json(telemetry.snapshot())

    def ep_web_index(self, request: Request) -> Response:
        index = _WEB_DIR / "index.html"
        if index.exists():
            return Response(index.read_text(), mimetype="text/html")
        return _json({"service": "rafiki-tpu admin", "docs": "/healthz"})

    def ep_login(self, request: Request) -> Response:
        body = self._body(request)
        return _json(self.admin.authenticate_user(
            body.get("email", ""), body.get("password", "")))

    def ep_create_user(self, request: Request) -> Response:
        self._auth(request, [UserType.ADMIN.value])
        body = self._body(request)
        return _json(self.admin.create_user(
            self._field(body, "email"), self._field(body, "password"),
            self._field(body, "user_type")), 201)

    def ep_get_users(self, request: Request) -> Response:
        self._auth(request, [UserType.ADMIN.value])
        return _json(self.admin.get_users())

    def ep_ban_user(self, request: Request) -> Response:
        self._auth(request, [UserType.ADMIN.value])
        return _json(self.admin.ban_user(self._field(self._body(request), "email")))

    def ep_create_model(self, request: Request) -> Response:
        user = self._auth(request, [UserType.MODEL_DEVELOPER.value])
        body = self._body(request)
        if "model_file" in request.files:
            model_file = request.files["model_file"].read()
        else:
            model_file = body.get("model_file", "").encode()
        return _json(self.admin.create_model(
            user["user_id"], self._field(body, "name"), self._field(body, "task"),
            model_file, self._field(body, "model_class"),
            body.get("dependencies") or {},
            body.get("access_right", "PRIVATE"), body.get("docs", "")), 201)

    def ep_get_models(self, request: Request) -> Response:
        self._auth(request)
        return _json(self.admin.get_models(request.args.get("task")))

    def ep_get_model(self, request: Request, name: str) -> Response:
        self._auth(request)
        return _json(self.admin.get_model(name))

    def ep_get_model_file(self, request: Request, name: str) -> Response:
        user = self._auth(request, [UserType.MODEL_DEVELOPER.value])
        return Response(self.admin.get_model_file(name,
                                                  requester_id=user.get("user_id"),
                                                  requester_type=user.get("user_type")),
                        mimetype="application/octet-stream")

    def ep_create_train_job(self, request: Request) -> Response:
        user = self._auth(request, [UserType.APP_DEVELOPER.value])
        body = self._body(request)
        return _json(self.admin.create_train_job(
            user["user_id"], self._field(body, "app"), self._field(body, "task"),
            self._field(body, "train_dataset_uri"),
            self._field(body, "val_dataset_uri"), self._field(body, "budget"),
            model_names=body.get("model_names"),
            advisor_kind=body.get("advisor_kind", "gp"),
            devices_per_trial=int(body.get("devices_per_trial", 1))), 201)

    def ep_get_train_jobs(self, request: Request) -> Response:
        user = self._auth(request)
        return _json(self.admin.get_train_jobs(user["user_id"]))

    def ep_get_train_job(self, request: Request, app: str,
                         app_version: int = -1) -> Response:
        self._auth(request)
        return _json(self.admin.get_train_job(app, app_version))

    def ep_stop_train_job(self, request: Request, app: str,
                          app_version: int = -1) -> Response:
        user = self._auth(request, [UserType.APP_DEVELOPER.value])
        return _json(self.admin.stop_train_job(app, app_version,
                                               user_id=self._scope(user)))

    def ep_get_trials(self, request: Request, app: str,
                      app_version: int = -1) -> Response:
        self._auth(request)
        if request.args.get("type") == "best":
            max_count = int(request.args.get("max_count", 2))
            return _json(self.admin.get_best_trials_of_train_job(
                app, app_version, max_count))
        return _json(self.admin.get_trials_of_train_job(app, app_version))

    def ep_get_trial(self, request: Request, trial_id: str) -> Response:
        self._auth(request)
        return _json(self.admin.get_trial(trial_id))

    def ep_get_trial_logs(self, request: Request, trial_id: str) -> Response:
        self._auth(request)
        return _json(self.admin.get_trial_logs(trial_id))

    def ep_get_trial_parameters(self, request: Request, trial_id: str) -> Response:
        self._auth(request)
        return Response(self.admin.get_trial_parameters(trial_id),
                        mimetype="application/octet-stream")

    def ep_create_inference_job(self, request: Request) -> Response:
        user = self._auth(request, [UserType.APP_DEVELOPER.value])
        body = self._body(request)
        gateway = body.get("gateway")
        if gateway is not None and not isinstance(gateway, dict):
            raise ValueError("gateway must be an object of gateway-config "
                             "overrides (e.g. {\"policy\": \"least-loaded\"})")
        return _json(self.admin.create_inference_job(
            self._scope(user), self._field(body, "app"),
            int(body.get("app_version", -1)),
            max_models=int(body.get("max_models", 2)),
            gateway=gateway), 201)

    def ep_get_inference_job(self, request: Request, app: str,
                             app_version: int = -1) -> Response:
        self._auth(request)
        return _json(self.admin.get_inference_job(app, app_version))

    def ep_stop_inference_job(self, request: Request, app: str,
                              app_version: int = -1) -> Response:
        user = self._auth(request, [UserType.APP_DEVELOPER.value])
        return _json(self.admin.stop_inference_job(app, app_version,
                                                   user_id=self._scope(user)))

    def ep_predict(self, request: Request, app: str) -> Response:
        # No auth on predict: the reference's predictor frontend is an
        # unauthenticated app-facing endpoint.
        body = self._body(request)
        queries = body.get("queries", [])
        preds = self.admin.predict(app, queries,
                                   int(body.get("app_version", -1)))
        return _json({"predictions": _jsonable(preds)})

    def ep_recover(self, request: Request) -> Response:
        self._auth(request, [UserType.ADMIN.value])
        body = self._body(request)
        stale = body.get("stale_after_s")
        # Default async: re-training orphans can outlive any HTTP timeout.
        wait = bool(body.get("wait", False))
        return _json(self.admin.recover_trials(
            float(stale) if stale is not None else None, wait=wait))

    def ep_advisor_propose(self, request: Request, advisor_id: str) -> Response:
        self._auth(request)
        try:
            knobs = self.admin.services.advisors.propose(advisor_id)
        except KeyError:
            raise NotFoundError(f"No advisor {advisor_id!r}")
        return _json({"knobs": knobs})

    def ep_advisor_feedback(self, request: Request, advisor_id: str) -> Response:
        self._auth(request)
        body = self._body(request)
        try:
            self.admin.services.advisors.feedback(
                advisor_id, float(self._field(body, "score")),
                self._field(body, "knobs"))
        except KeyError:
            raise NotFoundError(f"No advisor {advisor_id!r}")
        return _json({"ok": True})


def make_admin_app(admin: Optional[Admin] = None) -> AdminApp:
    return AdminApp(admin or Admin())


def serve(host: Optional[str] = None, port: Optional[int] = None,
          admin: Optional[Admin] = None):
    """Blocking server entry point (scripts/start_admin.py uses this)."""
    from werkzeug.serving import make_server

    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()  # JAX_PLATFORMS=cpu must survive sitecustomize
    admin = admin or Admin()
    app = AdminApp(admin)
    host = host or admin.config.admin_host
    port = port or admin.config.admin_port
    server = make_server(host, port, app, threaded=True)
    print(f"rafiki-tpu admin listening on http://{host}:{port}")
    try:
        server.serve_forever()
    finally:
        admin.stop()
