"""Services manager: jobs → running services on the TPU host.

Reference parity: rafiki/admin/services_manager.py (unverified —
SURVEY.md §2): translates a train job into one advisor + N train-worker
services and an inference job into one predictor + one inference worker
per chosen trial, writing Service rows as it goes. The reference
materialises services as Docker Swarm containers; here a "service" is a
supervised thread (or, via ProcessScheduler, a subprocess pinned to a
chip) on the TPU host — chips are a host-local resource, so container
orchestration buys nothing and costs startup latency.

Train jobs run asynchronously: ``create_train_services`` returns
immediately and the scheduler drives the job to budget exhaustion in a
background thread (stoppable via ``stop_train_services``).

Inference jobs: per top-k trial, the trial's model class is re-loaded,
its knobs re-applied and its trained parameters restored, then an
InferenceWorker thread serves it off the bus; a Predictor fronts them
(optionally over HTTP — see rafiki_tpu/predictor/app.py).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

from rafiki_tpu.advisor import AdvisorService
from rafiki_tpu.bus import InProcBus
from rafiki_tpu.config import Config, get_config
from rafiki_tpu.constants import (
    InferenceJobStatus,
    ServiceStatus,
    ServiceType,
    TrainJobStatus,
)
from rafiki_tpu.gateway import Gateway, GatewayConfig
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.predictor.predictor import Predictor
from rafiki_tpu.scheduler.local import LocalScheduler
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events
from rafiki_tpu.worker.inference import InferenceWorker


class _TrainJobHandle:
    def __init__(self, thread: threading.Thread, stop_event: threading.Event):
        self.thread = thread
        self.stop_event = stop_event
        self.result = None
        self.error: Optional[BaseException] = None


class _InferenceJobHandle:
    def __init__(self):
        self.stop_event = threading.Event()
        self.worker_threads: List[threading.Thread] = []
        self.workers: List[InferenceWorker] = []
        self.predictor: Optional[Predictor] = None
        self.gateway: Optional[Gateway] = None
        self.http_server = None  # set when an HTTP frontend is attached
        # Autoscale attachment (docs/autoscale.md): the serving shape a
        # scale-up replica must reproduce, and the live controller.
        self.best_trials: List[dict] = []
        self.batch_size: int = 0
        self.stacked_route: bool = False
        self.autoscaler = None  # AutoscaleController when attached


class ServicesManager:
    def __init__(self, store: MetaStore, params_store: ParamsStore,
                 bus: Optional[InProcBus] = None,
                 advisor_service: Optional[AdvisorService] = None,
                 config: Optional[Config] = None):
        self.store = store
        self.params_store = params_store
        self.bus = bus or InProcBus()
        self.advisors = advisor_service or AdvisorService()
        self.config = config or get_config()
        self._train_jobs: Dict[str, _TrainJobHandle] = {}
        self._inference_jobs: Dict[str, _InferenceJobHandle] = {}
        self._lock = threading.Lock()
        # Fleet-level tenant arbitration (docs/multitenancy.md): when a
        # JobAdmissionGate is attached, create_inference_services runs
        # every NEW job's forecast through the serving twin and refuses
        # jobs whose load would breach an existing tenant's SLO.
        self.job_gate = None
        # Crash-recovery reaper state (docs/recovery.md).
        self._reaper_thread: Optional[threading.Thread] = None
        self._reaper_stop: Optional[threading.Event] = None
        self._resuming: set = set()

    # -- train services ------------------------------------------------------

    def create_train_services(self, job_id: str, n_workers: Optional[int] = None,
                              devices: Optional[List[Any]] = None,
                              devices_per_trial: int = 1,
                              advisor_kind: str = "gp") -> None:
        """Start the job's worker fleet in the background and return."""
        with self._lock:
            if job_id in self._train_jobs and self._train_jobs[job_id].thread.is_alive():
                raise ValueError(f"Train job {job_id} already has running services")
        scheduler = LocalScheduler(self.store, self.params_store, self.advisors)
        stop_event = threading.Event()

        def run():
            try:
                from rafiki_tpu.autoscale import controller as _asc

                if _asc.prewarm_enabled():
                    # Admission-time compile pre-warm (docs/autoscale.md):
                    # build each model's packed program (and persist the
                    # XLA artifacts) BEFORE the sweep starts, so a later
                    # scale-up lands on a warm compile. Best-effort by
                    # contract — admission never fails on it.
                    from rafiki_tpu.autoscale import prewarm as _prewarm

                    try:
                        _prewarm.prewarm_train_job(self.store, job_id)
                    except Exception:
                        from rafiki_tpu import telemetry

                        telemetry.inc("autoscale.prewarm_errors")
                handle.result = scheduler.run_train_job(
                    job_id, n_workers=n_workers, devices=devices,
                    devices_per_trial=devices_per_trial,
                    advisor_kind=advisor_kind, stop_event=stop_event)
            except BaseException as e:  # surfaced via wait_train_job
                handle.error = e
                self.store.update_train_job_status(job_id, TrainJobStatus.ERRORED.value)
                if not isinstance(e, Exception):
                    # Interrupts (SystemExit, KeyboardInterrupt) must
                    # keep propagating after being recorded: absorbing
                    # them here would leave the process undrainable
                    # (RF006).
                    raise

        thread = threading.Thread(target=run, name=f"train-job-{job_id[:8]}", daemon=True)
        handle = _TrainJobHandle(thread, stop_event)
        with self._lock:
            self._train_jobs[job_id] = handle
        thread.start()

    def stop_train_services(self, job_id: str, wait: bool = True,
                            timeout: float = 60.0) -> None:
        with self._lock:
            handle = self._train_jobs.get(job_id)
        if handle is None:
            # No live services in this process (e.g. admin restarted):
            # mark the job stopped — but never clobber a terminal state.
            job = self.store.get_train_job(job_id)
            if job is not None and job["status"] in (TrainJobStatus.STARTED.value,
                                                     TrainJobStatus.RUNNING.value):
                self.store.update_train_job_status(job_id,
                                                   TrainJobStatus.STOPPED.value)
            return
        handle.stop_event.set()
        if wait:
            handle.thread.join(timeout=timeout)

    def wait_train_job(self, job_id: str, timeout: Optional[float] = None):
        """Block until the job's services finish; returns TrainJobResult
        (None when the job already finished outside this process)."""
        with self._lock:
            handle = self._train_jobs.get(job_id)
        if handle is None:
            job = self.store.get_train_job(job_id)
            if job is not None and job["status"] in (TrainJobStatus.STARTED.value,
                                                     TrainJobStatus.RUNNING.value):
                raise RuntimeError(
                    f"Train job {job_id} is {job['status']} but has no services "
                    "in this process (created with start=False, or the admin "
                    "restarted); start it with create_train_services first")
            return None
        handle.thread.join(timeout=timeout)
        if handle.thread.is_alive():
            raise TimeoutError(f"Train job {job_id} still running after {timeout}s")
        if handle.error is not None:
            raise handle.error
        return handle.result

    # -- crash recovery (docs/recovery.md) -----------------------------------

    def start_resume_reaper(self, poll_s: Optional[float] = None,
                            stale_after_s: Optional[float] = None) -> None:
        """Watch for RUNNING jobs whose sweep supervisor stopped
        heartbeating (a crashed/SIGKILLed supervisor process leaves its
        SUPERVISOR service row going stale) and adopt them via
        ``resume_sweep``. Poll cadence from ``RAFIKI_RESUME_POLL_S``,
        liveness cutoff from ``RAFIKI_RESUME_STALE_S`` unless given
        explicitly. Idempotent: a second start while the reaper runs is
        a no-op, and a job being resumed (here or by a racing resumer —
        the CAS adoption settles that) is never picked up twice."""
        from rafiki_tpu.scheduler.recovery import (
            ENV_RESUME_POLL_S,
            ENV_RESUME_STALE_S,
            resume_sweep,
        )

        if self._reaper_thread is not None and self._reaper_thread.is_alive():
            return
        poll = float(poll_s if poll_s is not None
                     else os.environ.get(ENV_RESUME_POLL_S, "10"))
        stale = float(stale_after_s if stale_after_s is not None
                      else os.environ.get(ENV_RESUME_STALE_S, "30"))
        stop = threading.Event()

        def loop():
            while not stop.wait(poll):
                try:
                    dead = self.store.get_jobs_with_dead_supervisor(stale)
                except Exception:
                    continue  # transient store error: next tick retries
                for job in dead:
                    jid = job["id"]
                    with self._lock:
                        handle = self._train_jobs.get(jid)
                        if handle is not None and handle.thread.is_alive():
                            # Our own live services — the job is not
                            # actually abandoned, its heartbeat is.
                            continue
                        if jid in self._resuming:
                            continue
                        self._resuming.add(jid)
                    _journal.record("recovery", "reaper_detected",
                                    job_id=jid, stale_after_s=stale)
                    events.emit("supervisor_dead_detected", job_id=jid)
                    try:
                        resume_sweep(self.store, self.params_store, jid,
                                     stale_after_s=stale,
                                     advisor_service=self.advisors)
                    except Exception as e:
                        # A failed resume must not kill the reaper: the
                        # job stays adoptable and the next pass (or a
                        # manual `sweep_proc resume`) retries.
                        _journal.record("recovery", "reaper_resume_failed",
                                        job_id=jid, error=repr(e))
                    finally:
                        with self._lock:
                            self._resuming.discard(jid)

        self._reaper_stop = stop
        self._reaper_thread = threading.Thread(target=loop,
                                               name="resume-reaper",
                                               daemon=True)
        self._reaper_thread.start()

    def stop_resume_reaper(self, timeout: float = 10.0) -> None:
        if self._reaper_stop is not None:
            self._reaper_stop.set()
        if self._reaper_thread is not None:
            self._reaper_thread.join(timeout=timeout)
        self._reaper_thread = None
        self._reaper_stop = None

    # -- inference services --------------------------------------------------

    def attach_job_gate(self, gate) -> None:
        """Attach a :class:`~rafiki_tpu.tenancy.arbiter.
        JobAdmissionGate`: from now on every new inference job that
        declares a tenant is forecast through the twin first, and a
        job whose load would breach an existing tenant's p99 budget
        raises ``JobRejected`` instead of starting services."""
        self.job_gate = gate

    def create_inference_services(self, inference_job_id: str,
                                  best_trials: List[dict],
                                  batch_size: Optional[int] = None,
                                  serve_http: bool = True,
                                  gateway_overrides: Optional[Dict[str, Any]]
                                  = None,
                                  tenancy=None,
                                  tenant: Optional[str] = None,
                                  tier: Optional[str] = None,
                                  expected_qps: float = 0.0) -> Predictor:
        """One inference worker per trial + a predictor over the bus
        fronted by a serving Gateway (admission control, quorum
        fan-out, breakers — docs/serving.md), plus (by default) a
        published HTTP frontend whose host:port is recorded on the
        inference-job row — the reference's per-job predictor port.

        ``gateway_overrides`` lets a job pick its own routing policy
        and limits (e.g. ``{"policy": "least-loaded",
        "max_inflight": 4}``) over the framework-config defaults.

        Tenancy (docs/multitenancy.md): pass a ``TenantFabric`` as
        ``tenancy`` for a tenant-aware gateway (weighted-fair
        admission + per-tenant accounting). ``tenant``/``tier``/
        ``expected_qps`` declare whose load this job is — with a job
        gate attached, the declared load is twin-forecast against the
        fleet and the job can be REJECTED before any service starts."""
        if not best_trials:
            raise ValueError("No completed trials to serve")
        if self.job_gate is not None and tenant is not None:
            from rafiki_tpu.tenancy.qos import DEFAULT_TIER

            # Raises JobRejected (journaling tenancy/arbiter) when the
            # forecast breaches an existing tenant's budget.
            self.job_gate.admit_job(inference_job_id, tenant,
                                    tier or DEFAULT_TIER, expected_qps)
        handle = _InferenceJobHandle()
        batch_size = batch_size or self.config.inference_batch_size
        try:
            return self._start_inference(handle, inference_job_id, best_trials,
                                         batch_size, serve_http,
                                         gateway_overrides or {}, tenancy)
        except Exception:
            # Tear down whatever already started — otherwise worker
            # threads (each pinning a trained model) leak unreachably.
            handle.stop_event.set()
            for th in handle.worker_threads:
                if th.ident is not None:  # join only threads that started
                    th.join(timeout=5)
            if handle.http_server is not None:
                handle.http_server.shutdown()
                handle.http_server.server_close()
            raise

    def _start_inference(self, handle: "_InferenceJobHandle",
                         inference_job_id: str, best_trials: List[dict],
                         batch_size: int, serve_http: bool,
                         gateway_overrides: Dict[str, Any],
                         tenancy=None) -> Predictor:
        models = [self._load_trial_model(t) for t in best_trials]

        # Same-architecture top-k → ONE worker running a stacked vmapped
        # forward (k models, one XLA program); otherwise the
        # reference-shaped fallback of one worker per trial.
        # RAFIKI_STACKED_SERVING=0 forces the replicated route (ops
        # escape hatch + the A/B knob bench_serving drives).
        from rafiki_tpu.parallel.serving import build_stacked

        stacked, route_reason = None, "disabled-by-env"
        if os.environ.get("RAFIKI_STACKED_SERVING", "1").lower() not in (
                "0", "false", "no", "off"):
            stacked, route_reason = build_stacked(best_trials, models,
                                                  batch_size=batch_size)
        serve_models = [stacked] if stacked is not None else models
        handle.best_trials = list(best_trials)
        handle.batch_size = batch_size
        handle.stacked_route = stacked is not None
        warmup_s = None
        if stacked is not None:
            # Pre-warm: the stacked program's XLA compile is paid HERE,
            # at service creation, never by the first live request.
            warmup_s = round(stacked.warmup(), 6)
            events.emit("inference_stacked", job_id=inference_job_id,
                        k=len(best_trials))
        # Route decision is journal-worthy: a post-mortem (and the
        # twin's calibration extractor) must see WHICH serving shape
        # this job got and why (docs/serving.md).
        _journal.record("serving", "route", job_id=inference_job_id,
                        route=("stacked" if stacked is not None
                               else "replicated"),
                        reason=route_reason, k=len(best_trials),
                        workers=len(serve_models), warmup_s=warmup_s)

        for i, model in enumerate(serve_models):
            worker_id = f"{inference_job_id[:8]}-iw{i}"
            service = self.store.create_service(
                ServiceType.INFERENCE_WORKER.value, job_id=inference_job_id,
                worker_index=i)
            worker = InferenceWorker(self.bus, inference_job_id, worker_id, model,
                                     batch_size=batch_size,
                                     stop_event=handle.stop_event)
            th = threading.Thread(target=self._run_inference_worker,
                                  args=(worker, service["id"]),
                                  name=worker_id, daemon=True)
            handle.workers.append(worker)
            handle.worker_threads.append(th)

        self.store.create_service(ServiceType.PREDICTOR.value, job_id=inference_job_id)
        handle.predictor = Predictor(self.bus, inference_job_id,
                                     timeout_s=self.config.predict_timeout_s)
        handle.gateway = Gateway(handle.predictor,
                                 GatewayConfig.from_config(
                                     self.config, **gateway_overrides),
                                 tenancy=tenancy)
        for th in handle.worker_threads:
            th.start()
        # Wait for workers to register so the first query doesn't race them.
        deadline = 5.0
        import time
        t0 = time.monotonic()
        while (len(self.bus.get_workers(inference_job_id)) < len(serve_models)
               # lint: disable=RF007 — bounded startup wait, not traced
               and time.monotonic() - t0 < deadline):
            time.sleep(0.01)
        predictor_host = None
        if serve_http:
            from rafiki_tpu.predictor.app import start_predictor_server

            handle.http_server, predictor_host = start_predictor_server(
                handle.gateway, host=self.config.admin_host)
            # A wildcard bind address is unroutable for clients: advertise
            # a reachable address instead.
            bind_host, _, port = predictor_host.rpartition(":")
            if bind_host in ("0.0.0.0", "::", ""):
                import socket

                try:
                    advertise = socket.gethostbyname(socket.gethostname())
                except OSError:
                    advertise = "127.0.0.1"
                predictor_host = f"{advertise}:{port}"
        self.store.update_inference_job(inference_job_id,
                                        status=InferenceJobStatus.RUNNING.value,
                                        predictor_host=predictor_host)
        events.emit("inference_job_started", job_id=inference_job_id,
                    n_workers=len(best_trials), predictor_host=predictor_host)
        with self._lock:
            self._inference_jobs[inference_job_id] = handle
        return handle.predictor

    # -- co-hosted serving (docs/multitenancy.md) ----------------------------

    def _make_program_loader(self, trials: List[dict], batch_size: int):
        """A lazy model loader for one co-hosted job: runs on residency
        MISS (first query, or re-activation after an LRU eviction),
        never at service creation — a cold job costs zero HBM until it
        is actually queried."""
        def load():
            models = [self._load_trial_model(t) for t in trials]
            if len(models) == 1:
                return models[0]
            from rafiki_tpu.parallel.serving import build_stacked

            stacked, _ = build_stacked(trials, models,
                                       batch_size=batch_size)
            return stacked if stacked is not None else models[0]

        return load

    def create_cohosted_inference_services(
            self, job_trials: Dict[str, List[dict]],
            batch_size: Optional[int] = None,
            gateway_overrides: Optional[Dict[str, Any]] = None,
            tenancy_for: Optional[Dict[str, Any]] = None,
            hbm_budget_bytes: Optional[int] = None) -> Dict[str, Predictor]:
        """ONE inference worker serving EVERY job in ``job_trials``
        behind a :class:`~rafiki_tpu.tenancy.hosting.ProgramHost`:
        models swap in and out of a shared HBM byte budget by LRU
        residency (journaled ``tenancy/residency``) instead of each
        job pinning a dedicated worker — the k-models-many-jobs
        generalization of the stacked route. Each job keeps its OWN
        Predictor + Gateway (admission, QoS and metrics stay per-job);
        the predictor tags queries with the job's program id and the
        host routes them. ``tenancy_for`` maps job id → TenantFabric
        for jobs that want tenant-aware gateways.

        Returns ``{job_id: Predictor}``. The shared worker is owned by
        the FIRST job's handle; the cohort shares one stop event, so
        stopping ANY co-hosted job stops serving for all of them —
        co-hosting trades blast-radius isolation for HBM efficiency
        and that trade is explicit here."""
        if not job_trials:
            raise ValueError("No jobs to co-host")
        from rafiki_tpu.tenancy.hosting import ProgramHost, ProgramSpec
        from rafiki_tpu.tenancy.residency import ResidencyManager

        batch_size = batch_size or self.config.inference_batch_size
        job_ids = list(job_trials)
        specs = []
        for job_id, trials in job_trials.items():
            if not trials:
                raise ValueError(f"Job {job_id} has no completed trials")
            # HBM charge estimate: the params blobs' on-disk bytes
            # (floored — an estimate of 0 would make eviction free).
            size = sum(self.params_store.size(t["params_id"])
                       for t in trials if t.get("params_id"))
            specs.append(ProgramSpec(
                program_id=job_id,
                loader=self._make_program_loader(list(trials), batch_size),
                size_bytes=max(size, 1 << 20)))
        host = ProgramHost(specs,
                           residency=ResidencyManager(hbm_budget_bytes))
        primary, extras = job_ids[0], job_ids[1:]
        worker_id = f"cohost-{primary[:8]}-iw0"
        stop_event = threading.Event()
        worker = InferenceWorker(self.bus, primary, worker_id, host,
                                 batch_size=batch_size,
                                 stop_event=stop_event,
                                 extra_job_ids=extras)
        service = self.store.create_service(
            ServiceType.INFERENCE_WORKER.value, job_id=primary,
            worker_index=0)
        th = threading.Thread(target=self._run_inference_worker,
                              args=(worker, service["id"]),
                              name=worker_id, daemon=True)
        th.start()
        _journal.record("tenancy", "cohost", worker_id=worker_id,
                        jobs=list(job_ids),
                        budget_bytes=host.residency.budget_bytes)
        # Wait for the worker to register under every co-hosted job id
        # so the first query doesn't race registration.
        import time
        t0 = time.monotonic()
        while (any(worker_id not in self.bus.get_workers(j)
                   for j in job_ids)
               # lint: disable=RF007 — bounded startup wait, not traced
               and time.monotonic() - t0 < 5.0):
            time.sleep(0.01)
        predictors: Dict[str, Predictor] = {}
        fabrics = tenancy_for or {}
        for job_id in job_ids:
            handle = _InferenceJobHandle()
            handle.stop_event = stop_event  # cohort-shared by design
            if job_id == primary:
                handle.workers.append(worker)
                handle.worker_threads.append(th)
            handle.best_trials = list(job_trials[job_id])
            handle.batch_size = batch_size
            self.store.create_service(ServiceType.PREDICTOR.value,
                                      job_id=job_id)
            handle.predictor = Predictor(
                self.bus, job_id, timeout_s=self.config.predict_timeout_s,
                program=job_id)
            handle.gateway = Gateway(handle.predictor,
                                     GatewayConfig.from_config(
                                         self.config,
                                         **(gateway_overrides or {})),
                                     tenancy=fabrics.get(job_id))
            self.store.update_inference_job(
                job_id, status=InferenceJobStatus.RUNNING.value,
                predictor_host=None)
            events.emit("inference_job_started", job_id=job_id,
                        n_workers=1, predictor_host=None)
            with self._lock:
                self._inference_jobs[job_id] = handle
            predictors[job_id] = handle.predictor
        return predictors

    def _run_inference_worker(self, worker: InferenceWorker, service_id: str) -> None:
        self.store.update_service(service_id, status=ServiceStatus.RUNNING.value)
        try:
            worker.run()
            self.store.update_service(service_id, status=ServiceStatus.STOPPED.value)
        except Exception:
            self.store.update_service(service_id, status=ServiceStatus.ERRORED.value)

    def _load_trial_model(self, trial: dict):
        """Rebuild a trained model from its trial row: class + knobs + params."""
        sub = self.store.get_sub_train_job(trial["sub_train_job_id"])
        if sub is None:  # data-integrity failure, not a caller mistake
            raise RuntimeError(f"Trial {trial['id']} has no sub train job")
        model_row = self.store.get_model(sub["model_id"])
        model_cls = load_model_class(model_row["model_file"], model_row["model_class"])
        model = model_cls(**trial["knobs"])
        if trial.get("params_id"):
            model.load_parameters(self.params_store.load(trial["params_id"]))
        return model

    def get_predictor(self, inference_job_id: str) -> Optional[Predictor]:
        with self._lock:
            handle = self._inference_jobs.get(inference_job_id)
        return handle.predictor if handle else None

    def get_gateway(self, inference_job_id: str) -> Optional[Gateway]:
        with self._lock:
            handle = self._inference_jobs.get(inference_job_id)
        return handle.gateway if handle else None

    # -- autoscale (docs/autoscale.md) ---------------------------------------

    def _spawn_scale_replica(self, handle: "_InferenceJobHandle",
                             inference_job_id: str, index: int):
        """Build one scale-up replica of the job's serving shape: the
        stacked ensemble when that route was taken (one worker = whole
        ensemble; its compile is warm via the stacked warmup + the
        persistent XLA cache), otherwise the best trial's model. Own
        stop event — the autoscaler drains replicas one at a time,
        never through the job-wide event."""
        if handle.stacked_route:
            from rafiki_tpu.parallel.serving import build_stacked

            models = [self._load_trial_model(t) for t in handle.best_trials]
            stacked, _ = build_stacked(handle.best_trials, models,
                                       batch_size=handle.batch_size)
            model = stacked if stacked is not None else models[0]
            if stacked is not None:
                stacked.warmup()
        else:
            model = self._load_trial_model(handle.best_trials[0])
        worker_id = f"{inference_job_id[:8]}-as{index}"
        service = self.store.create_service(
            ServiceType.INFERENCE_WORKER.value, job_id=inference_job_id,
            worker_index=1000 + index)
        worker = InferenceWorker(self.bus, inference_job_id, worker_id,
                                 model, batch_size=handle.batch_size)
        th = threading.Thread(target=self._run_inference_worker,
                              args=(worker, service["id"]),
                              name=worker_id, daemon=True)
        th.start()
        handle.workers.append(worker)
        handle.worker_threads.append(th)
        return worker_id, worker, th

    def attach_autoscaler(self, inference_job_id: str,
                          min_workers: Optional[int] = None,
                          max_workers: Optional[int] = None,
                          tick_s: Optional[float] = None,
                          pregate_fn=None, start: bool = True,
                          **controller_kwargs):
        """Close the loop over a running inference job: SLO burn +
        gateway sensors in, worker spawn/drain out, every decision
        journaled. The baseline fleet is the floor by default — the
        controller only drains replicas it spawned (they carry their
        own stop events; the original workers share the job-wide one).
        Returns the started :class:`AutoscaleController`."""
        from rafiki_tpu.autoscale import actuators as _actuators
        from rafiki_tpu.autoscale import controller as _asc

        with self._lock:
            handle = self._inference_jobs.get(inference_job_id)
        if handle is None:
            raise ValueError(f"Inference job {inference_job_id} has no "
                             "running services in this process")
        baseline = [(w.worker_id, w, None) for w in handle.workers]
        lane = _actuators.InferenceWorkerLane(
            self.bus, inference_job_id,
            spawn_fn=lambda i: self._spawn_scale_replica(
                handle, inference_job_id, i),
            initial=baseline)
        overrides: Dict[str, Any] = {
            "min_size": (len(baseline) if min_workers is None
                         else min_workers)}
        if max_workers is not None:
            overrides["max_size"] = max_workers
        spec = _asc.LaneSpec.from_env("inference", **overrides)
        if (handle.gateway is not None
                and getattr(handle.gateway, "tenancy", None) is not None):
            # Tenant-aware fleet (docs/multitenancy.md): the lane
            # scales on the WORST of the classic inference pressure
            # and the tenant aggregates (worst per-tenant burn /
            # tenant shed rate) — one tenant burning its p99 budget is
            # a capacity signal even while the global queue is calm.
            import dataclasses as _dc

            from rafiki_tpu.tenancy.arbiter import tenant_pressure

            base_fn = spec.pressure_fn

            def _tenant_aware(sensors, _base=base_fn):
                bp, breason = _base(sensors)
                tp, treason = tenant_pressure(sensors)
                if bp is None or (tp is not None and tp > bp):
                    return tp, treason
                return bp, breason

            spec = _dc.replace(spec, pressure_fn=_tenant_aware)
        controller = _asc.AutoscaleController(
            lanes=[spec],
            sensor_fn=lambda: _asc.read_sensors(gateway=handle.gateway),
            actuators={"inference": lane},
            tick_s=tick_s, pregate_fn=pregate_fn, **controller_kwargs)
        handle.autoscaler = controller
        if start:
            controller.start()
        return controller

    def attach_http_server(self, inference_job_id: str, server) -> None:
        with self._lock:
            handle = self._inference_jobs.get(inference_job_id)
        if handle is not None:
            handle.http_server = server

    def stop_inference_services(self, inference_job_id: str,
                                timeout: float = 10.0) -> None:
        with self._lock:
            handle = self._inference_jobs.pop(inference_job_id, None)
        if handle is None:
            self.store.update_inference_job(inference_job_id,
                                            status=InferenceJobStatus.STOPPED.value)
            return
        if handle.autoscaler is not None:
            # The control loop stops FIRST: a controller reacting to
            # the drain's shed spike would fight the teardown.
            handle.autoscaler.stop()
        if handle.gateway is not None:
            # Graceful drain BEFORE the workers stop: in-flight requests
            # finish against live workers; new arrivals shed immediately.
            handle.gateway.drain(timeout=min(timeout, 5.0))
        handle.stop_event.set()
        for th in handle.worker_threads:
            th.join(timeout=timeout)
        if handle.http_server is not None:
            handle.http_server.shutdown()
            handle.http_server.server_close()  # release the listening FD now
        self.store.update_inference_job(inference_job_id,
                                        status=InferenceJobStatus.STOPPED.value)
        events.emit("inference_job_stopped", job_id=inference_job_id)

    # -- teardown ------------------------------------------------------------

    def stop_all(self) -> None:
        self.stop_resume_reaper()
        with self._lock:
            train_ids = list(self._train_jobs)
            inf_ids = list(self._inference_jobs)
        for jid in train_ids:
            self.stop_train_services(jid, wait=False)
        for jid in inf_ids:
            self.stop_inference_services(jid)
        for jid in train_ids:
            self.stop_train_services(jid, wait=True)
