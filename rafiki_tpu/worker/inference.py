"""Inference worker: serves one trained trial.

Reference parity: rafiki/worker/inference.py (unverified — SURVEY.md
§3.2): load the trial's params, register as running in the bus, then
loop: pop a query batch from this worker's queue → model.predict →
push predictions keyed by query id.

TPU note: ``pop_queries`` drains the queue after the first query
arrives, so concurrent requests are micro-batched into one forward
pass — the device sees large batches, not query-at-a-time traffic.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

import numpy as np

from rafiki_tpu.model.base import BaseModel


class InferenceWorker:
    def __init__(self, bus, job_id: str, worker_id: str, model: BaseModel,
                 batch_size: int = 64, stop_event: Optional[threading.Event] = None):
        self.bus = bus
        self.job_id = job_id
        self.worker_id = worker_id
        self.model = model
        self.batch_size = batch_size
        self._stop = stop_event or threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        self.bus.add_worker(self.job_id, self.worker_id)
        try:
            while not self._stop.is_set():
                items = self.bus.pop_queries(self.worker_id, max_n=self.batch_size,
                                             timeout=0.1)
                if not items:
                    continue
                qids = [qid for qid, _ in items]
                queries = [q for _, q in items]
                try:
                    preds = self._predict(queries)
                except Exception as e:  # a bad query batch must not kill the worker
                    preds = [{"error": str(e)}] * len(queries)
                for qid, pred in zip(qids, preds):
                    self.bus.put_prediction(qid, self.worker_id, pred)
        finally:
            self.bus.remove_worker(self.job_id, self.worker_id)

    def _predict(self, queries: List[Any]) -> List[Any]:
        # Always the contract API: predict() owns query semantics
        # (classification probs, tag sequences, ...). JaxModel.predict
        # already batches the device forward internally, so the whole
        # popped micro-batch still runs as one XLA program.
        return self.model.predict(queries)
