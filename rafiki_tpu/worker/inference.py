"""Inference worker: serves one trained trial.

Reference parity: rafiki/worker/inference.py (unverified — SURVEY.md
§3.2): load the trial's params, register as running in the bus, then
loop: pop a query batch from this worker's queue → model.predict →
push predictions keyed by query id.

TPU note: ``pop_queries`` drains the queue after the first query
arrives, so concurrent requests are micro-batched into one forward
pass — the device sees large batches, not query-at-a-time traffic.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, List, Optional

import numpy as np

from rafiki_tpu import chaos, telemetry
from rafiki_tpu.model.base import BaseModel
from rafiki_tpu.obs import context as trace_context
from rafiki_tpu.obs.anatomy import hops as _hops
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.predictor.predictor import BATCH_KEY


class InferenceWorker:
    def __init__(self, bus, job_id: str, worker_id: str, model: BaseModel,
                 batch_size: int = 64, stop_event: Optional[threading.Event] = None,
                 extra_job_ids: Optional[List[str]] = None):
        self.bus = bus
        self.job_id = job_id
        # Co-hosted serving (docs/multitenancy.md): one worker process
        # can serve SEVERAL jobs' models behind a ProgramHost. The
        # worker registers (and heartbeats) under every co-hosted job
        # id with the SAME worker id — each job's predictor fans out to
        # the same queue, and the program tag on each query routes it.
        self.job_ids = [job_id] + [j for j in (extra_job_ids or [])
                                   if j != job_id]
        self.worker_id = worker_id
        self.model = model
        self.batch_size = batch_size
        self._stop = stop_event or threading.Event()
        # Drain contract (docs/autoscale.md): set only after the serve
        # loop exited AND the bus registration is gone — every popped
        # query has had its prediction published and the lease cannot
        # route new work here. The autoscale drain path waits on this
        # before counting the slot freed.
        self.drained = threading.Event()
        # First successful forward on this worker pays the compile; the
        # hop chain splits it out as forward_cold vs forward so a cold
        # hit cannot masquerade as a warm-path tail.
        self._warm = False

    HEARTBEAT_S = 0.5

    def stop(self) -> None:
        self._stop.set()

    def _beat(self) -> None:
        """Liveness lease refresher. A separate daemon thread, not the
        serve loop: model.predict can hold the loop for seconds (first
        forward pays the XLA compile) and the lease must stay fresh
        through it. XLA/numpy release the GIL, so this thread runs even
        mid-forward; SIGKILL stops it with the process — which is
        exactly the signal the predictor's max_age_s filter consumes."""
        while not self._stop.wait(self.HEARTBEAT_S):
            try:
                for job_id in self.job_ids:
                    self.bus.heartbeat(job_id, self.worker_id)
            except Exception:  # manager teardown mid-beat: exit quietly
                return

    def run(self) -> None:
        for job_id in self.job_ids:
            self.bus.add_worker(job_id, self.worker_id)
        threading.Thread(target=self._beat, name=f"beat-{self.worker_id}",
                         daemon=True).start()
        try:
            while not self._stop.is_set():
                items = self.bus.pop_queries(self.worker_id, max_n=self.batch_size,
                                             timeout=0.1)
                if not items:
                    continue
                # Envelopes are (qid, query) or traced (qid, query, trace)
                # — see bus/queues.py. A micro-batch can mix traces; the
                # forward span binds to the first one, and every traced
                # query gets its own journal hop so each trace stitches.
                qids = [item[0] for item in items]
                queries = [item[1] for item in items]
                traces = [item[2] if len(item) > 2 else None
                          for item in items]
                lead = next((t for t in traces if t), None)
                # Hop chains (docs/serving_anatomy.md): continue each
                # traced query's envelope marks with this worker's leg.
                # Batch-shared marks (deq/fwds/forward end) are stamped
                # once and appended to every chain in the micro-batch.
                deq = _hops.mark("deq")
                chains = [list(tr["hops"]) + [deq]
                          if tr and tr.get("hops") else None
                          for tr in traces]
                for qid, tr in zip(qids, traces):
                    if tr:
                        _journal.record(
                            "bus", "pop_query", query_id=qid,
                            worker_id=self.worker_id,
                            trace_id=tr.get("trace_id"),
                            parent_span=tr.get("parent_span"))
                bind = (trace_context.trace(lead.get("trace_id")) if lead
                        else contextlib.nullcontext())
                # fwds opens the forward segment BEFORE the chaos hook:
                # an injected inference.forward delay must land inside
                # the forward hop, where tail attribution can see it.
                fwds = _hops.mark("fwds")
                was_cold = not self._warm
                # Microbatch envelopes (predictor.BATCH_KEY) carry a
                # whole gateway batch as ONE query: expand them into the
                # flat forward batch, then regroup so a batch envelope
                # gets a per-query prediction LIST back while plain
                # envelopes keep their scalar reply shape.
                flat: List[Any] = []
                spans = []  # (offset, n, is_batch) per envelope
                for q in queries:
                    if isinstance(q, dict) and BATCH_KEY in q:
                        group = list(q[BATCH_KEY])
                        spans.append((len(flat), len(group), True))
                        flat.extend(group)
                    else:
                        spans.append((len(flat), 1, False))
                        flat.append(q)
                try:
                    # Chaos: a delay here is a latency spike / stuck
                    # replica (the lease stays fresh — the beat thread
                    # runs on); an error is a poisoned forward. Both
                    # exercise the gateway's quorum + breaker paths.
                    chaos.hook("inference.forward", self.worker_id)
                    with bind, telemetry.span("inference.forward",
                                              worker_id=self.worker_id):
                        flat_preds = self._predict(flat)
                    telemetry.inc("inference.queries_served", len(flat))
                    self._warm = True
                except Exception as e:  # a bad query batch must not kill the worker
                    telemetry.inc("inference.batch_errors")
                    flat_preds = [{"error": str(e)}] * len(flat)
                preds = [list(flat_preds[off:off + n]) if is_batch
                         else flat_preds[off]
                         for off, n, is_batch in spans]
                fwd_end = _hops.mark("fwdc" if was_cold else "fwd")
                for qid, pred, chain in zip(qids, preds, chains):
                    if chain is None:
                        self.bus.put_prediction(qid, self.worker_id, pred)
                    else:
                        chain.append(fwds)
                        chain.append(fwd_end)
                        chain.append(_hops.mark("reply"))
                        self.bus.put_prediction(qid, self.worker_id, pred,
                                                hops=chain)
        finally:
            for job_id in self.job_ids:
                self.bus.remove_worker(job_id, self.worker_id)
            self.drained.set()

    def _predict(self, queries: List[Any]) -> List[Any]:
        # Always the contract API: predict() owns query semantics
        # (classification probs, tag sequences, ...). JaxModel.predict
        # already batches the device forward internally, so the whole
        # popped micro-batch still runs as one XLA program.
        return self.model.predict(queries)


def run_inference_worker_process(bus, meta_path: str, params_path: str,
                                 trial_id: str, job_id: str, worker_id: str,
                                 batch_size: int = 64) -> None:
    """Entrypoint for an inference worker as its OWN process (spawn
    target; the mp-bus proxies pickle across). Rebuilds the trial's
    model from the store — class bytes + knobs + trained params — then
    serves until killed. This is the deployment shape the reference
    gets from one-container-per-trial (SURVEY.md §3.2), and the unit
    the serve-path elasticity test SIGKILLs."""
    # FIRST, before anything touches jax: a spawned child re-imports
    # everything fresh, and this image's sitecustomize force-registers
    # the TPU backend regardless of JAX_PLATFORMS — when the tunnel is
    # down the child then hangs in backend init and never registers on
    # the bus (admin/app.py and worker/main.py already do this dance).
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    # Observability plane: journal under RAFIKI_LOG_DIR (inherited via
    # the spawn env), adopt RAFIKI_TRACE_ID, dump a flight record on
    # fatal/SIGTERM (docs/observability.md).
    from rafiki_tpu import obs

    if obs.configure_from_env(role="infer"):
        obs.recorder.install()

    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.store import MetaStore, ParamsStore

    store = MetaStore(meta_path)
    params_store = ParamsStore(params_path)
    trial = store.get_trial(trial_id)
    sub = store.get_sub_train_job(trial["sub_train_job_id"])
    model_row = store.get_model(sub["model_id"])
    cls = load_model_class(model_row["model_file"], model_row["model_class"])
    model = cls(**trial["knobs"])
    if trial.get("params_id"):
        model.load_parameters(params_store.load(trial["params_id"]))
    InferenceWorker(bus, job_id, worker_id, model,
                    batch_size=batch_size).run()
