"""Train worker: the trial loop.

Reference parity: rafiki/worker/train.py (unverified — SURVEY.md §3.1
is the call stack): poll budget → create Trial row → get knobs from
advisor → load model class → init(knobs) → train → evaluate →
dump_parameters → persist score+params → feedback; mark trial ERRORED
on exception and continue; stop when budget exhausted.

TPU-native specifics:
  * the worker owns a fixed set of jax devices (usually exactly one
    chip — "one trial per chip"); trials run under
    ``jax.default_device`` / a dp Mesh over those devices, so N workers
    in one process drive N chips concurrently, and process-per-chip
    workers isolate XLA runtimes entirely;
  * trial-time model logs are captured via ``logger.capture`` into
    TrialLog rows (same channel as the reference);
  * each trial records its compiled-shape signature so schedulers can
    measure and amortize XLA compile time across like-shaped trials.
"""

from __future__ import annotations

import io
import time
import traceback
from typing import Any, Dict, List, Optional, Protocol

from rafiki_tpu import chaos, telemetry
from rafiki_tpu.advisor.speculative import CurveCoordinator
from rafiki_tpu.constants import BudgetType, TrainJobStatus, TrialStatus
from rafiki_tpu.model.base import BaseModel, load_model_class
from rafiki_tpu.model.knobs import Knobs, knob_config_signature
from rafiki_tpu.model.log import logger
from rafiki_tpu.obs import context as trace_context
from rafiki_tpu.obs import health as _health
from rafiki_tpu.obs.journal import journal
from rafiki_tpu.obs.ledger import ledger
from rafiki_tpu.obs.search import audit as search_audit
from rafiki_tpu.store import MetaStore, ParamsStore
from rafiki_tpu.utils.events import events


class AdvisorHandle(Protocol):
    """What the worker needs from an advisor, local or remote.

    ``propose_batch`` is optional on third-party handles — the packed
    runner probes with getattr and falls back to n× ``propose``."""

    def propose(self) -> Knobs: ...

    def feedback(self, score: float, knobs: Knobs) -> None: ...


class InProcAdvisorHandle:
    def __init__(self, advisor_service, advisor_id: str):
        self._svc = advisor_service
        self._id = advisor_id

    def propose(self) -> Knobs:
        return self._svc.propose(self._id)

    def propose_batch(self, n: int) -> List[Knobs]:
        return self._svc.propose_batch(self._id, n)

    def feedback(self, score: float, knobs: Knobs) -> None:
        self._svc.feedback(self._id, score, knobs)

    def speculate(self, score: float, knobs: Knobs, fit=None) -> None:
        self._svc.speculate(self._id, score, knobs, fit=fit)


def _journal_epoch_eval(trial_id: str, entry: Dict[str, Any],
                        wall_s: Optional[float],
                        packed: bool = False) -> None:
    """Durable per-epoch learning-curve record (``trial/epoch_eval``):
    the substrate the learning-curve-predictive advisor needs — eval
    curves survive the worker process instead of living only in the
    sqlite trial log. No-op for non-epoch log entries and when no
    journal is configured."""
    if entry.get("type") != "values":
        return
    values = entry.get("values") or {}
    if "epoch" not in values:
        return
    score = values.get("acc", values.get("loss"))
    journal.record(
        "trial", "epoch_eval", trial_id=trial_id,
        epoch=int(values["epoch"]),
        score=None if score is None else float(score),
        loss=values.get("loss"), acc=values.get("acc"),
        wall_s=None if wall_s is None else round(float(wall_s), 6),
        packed=packed)


class PackAborted(RuntimeError):
    """A pack was torn down mid-train by its supervisor (chip lost,
    mesh preempt) rather than by a trial failure. The rows stay
    RUNNING — deliberately NOT marked errored — so the mesh scheduler
    can re-pack them onto surviving chips, where each resumes from its
    newest per-epoch packed checkpoint (docs/mesh_sweep.md)."""


class EarlyKilled(RuntimeError):
    """A serial trial condemned mid-flight by the learning-curve
    predictor (docs/early_kill.md): raised from the trial's log sink at
    an epoch boundary, caught by ``run_trial``'s dedicated arm, which
    marks the trial errored, charges the doomed bucket and routes the
    predicted score to the advisor as consolation feedback."""

    def __init__(self, fit, epoch: int, best: float):
        super().__init__(
            f"early-killed at epoch {epoch}: predicted final "
            f"{fit.predicted_final:.4f} (hi {fit.hi:.4f}) vs best {best:.4f}")
        self.fit = fit
        self.epoch = int(epoch)
        self.best = float(best)


class TrainWorker:
    def __init__(
        self,
        store: MetaStore,
        params_store: ParamsStore,
        sub_train_job_id: str,
        model_class: type,
        advisor: AdvisorHandle,
        train_dataset_uri: str,
        val_dataset_uri: str,
        budget: Dict[str, Any],
        worker_id: str = "worker-0",
        devices: Optional[List[Any]] = None,
        job_created_at: Optional[float] = None,
        service_id: Optional[str] = None,
        stop_event=None,
        async_persist: bool = True,
        checkpoint_every: Optional[int] = None,
        trial_pack: Optional[int] = None,
    ):
        if not (isinstance(model_class, type) and issubclass(model_class, BaseModel)):
            raise TypeError("model_class must subclass BaseModel")
        self.store = store
        self.params_store = params_store
        self.sub_id = sub_train_job_id
        self.model_class = model_class
        self.advisor = advisor
        self.train_uri = train_dataset_uri
        self.val_uri = val_dataset_uri
        self.budget = dict(budget or {})
        self.worker_id = worker_id
        self.devices = devices
        self.job_created_at = job_created_at or time.time()
        self.service_id = service_id
        self._stop = stop_event
        # Sweep WAL handle (scheduler/wal.py), set by the mesh scheduler
        # so the mid-pack backfill closure's budget claims are
        # intent/commit-bracketed like the supervisor's up-front ones.
        # None for standalone workers (no durable control plane to join).
        self.wal = None
        self.trials_run = 0
        self._saver = _AsyncSaver(self) if async_persist else None
        # Mid-trial checkpoint cadence (epochs); 0/None = off. Env
        # RAFIKI_CHECKPOINT_EVERY sets the fleet default.
        import os

        if checkpoint_every is None:
            checkpoint_every = int(os.environ.get("RAFIKI_CHECKPOINT_EVERY", "0"))
        self.checkpoint_every = int(checkpoint_every)
        # Trial packing width: k same-program trials vmapped into one
        # XLA program (docs/trial_packing.md). 1 = off (the default,
        # behavior-identical to the serial loop).
        if trial_pack is None:
            trial_pack = int(os.environ.get("RAFIKI_TRIAL_PACK", "1"))
        self.trial_pack = max(1, int(trial_pack))
        # Learning-curve kill/speculation coordinator (docs/
        # early_kill.md). None unless RAFIKI_CURVE_KILL or
        # RAFIKI_CURVE_SPECULATE is set — every consult site guards on
        # `is None`, so the off path is today's loop bit-exactly. The
        # mesh scheduler overwrites this with one coordinator shared
        # across its chip workers (cross-chip best-so-far + stragglers).
        self.curve = CurveCoordinator.from_env()
        from rafiki_tpu.config import get_config

        self.heartbeat_min_interval_s = get_config().trial_heartbeat_s
        self._last_heartbeat = 0.0

    # -- budget --------------------------------------------------------------

    def budget_exhausted(self) -> bool:
        """Non-consuming checks (stop flag, wall clock). The trial-count
        budget is enforced by the atomic claim in ``run()``."""
        if self._stop is not None and self._stop.is_set():
            return True
        hours = self.budget.get(BudgetType.TIME_HOURS.value)
        # lint: disable=RF009 — job age vs a persisted epoch timestamp: job_created_at survives restarts, so wall clock is the only shared basis
        if hours is not None and time.time() - self.job_created_at >= float(hours) * 3600:
            return True
        return False

    # -- one trial -----------------------------------------------------------

    def run_trial(self, knobs: Knobs,
                  resume_trial_id: Optional[str] = None,
                  budget_max: Optional[int] = None) -> Optional[dict]:
        knob_config = self.model_class.get_knob_config()
        sig = knob_config_signature(knob_config, knobs)
        resume = resume_trial_id is not None
        if resume:
            trial = self.store.get_trial(resume_trial_id)
            if trial is None:
                raise KeyError(f"No trial {resume_trial_id!r} to resume")
            # Adopt it: live again, stale crash error cleared, rebound
            # to this worker so recovery sweeps see a live owner.
            self.store.mark_trial_as_running(trial["id"],
                                             service_id=self.service_id,
                                             worker_id=self.worker_id)
        else:
            # budget_max makes row-insert + slot-claim one transaction:
            # None back = the budget drained under us, nothing to run.
            trial = self.store.create_trial(
                self.sub_id, self.model_class.__name__, knobs,
                worker_id=self.worker_id, shape_sig=sig,
                service_id=self.service_id, budget_max=budget_max)
            if trial is None:
                return None
        tid = trial["id"]
        t_trial0 = time.monotonic()

        def sink(entry):
            self.store.add_trial_log(tid, entry)
            _journal_epoch_eval(tid, entry,
                               # lint: disable=RF007 — epoch_eval wall field, already under trial.total
                               wall_s=time.monotonic() - t_trial0)
            if self.curve is not None and entry.get("type") == "values":
                values = entry.get("values") or {}
                # Higher-is-better curves only (acc); loss-only models
                # are never killed — the conservative default.
                if "epoch" in values and values.get("acc") is not None:
                    ep = int(values["epoch"])
                    self.curve.observe(knobs, ep, float(values["acc"]),
                                       trial_id=tid)
                    fit = self.curve.kill_verdict(knobs, ep, trial_id=tid)
                    if fit is not None:
                        raise EarlyKilled(fit, ep, self.curve.best_so_far)
            if self.service_id is not None:
                # Epoch logs double as liveness: long trials heartbeat
                # from inside, so failure detection doesn't flag them.
                # Throttled so chatty per-batch loggers don't turn every
                # log line into an extra sqlite write transaction.
                now = time.monotonic()
                if now - self._last_heartbeat >= self.heartbeat_min_interval_s:
                    self._last_heartbeat = now
                    self.store.update_service(self.service_id, heartbeat=True)

        import contextlib

        # One trial = one trace: spans, journal records and the goodput
        # ledger entity all stitch under it across processes
        # (docs/observability.md). A resumed trial mints a fresh trace —
        # the journal links the attempts through the trial_id field.
        _trace_scope = contextlib.ExitStack()
        _trace_scope.enter_context(
            trace_context.trace(trace_context.new_trace_id()))
        events.emit("trial_started", trial_id=tid, sub_job_id=self.sub_id,
                    model=self.model_class.__name__, worker_id=self.worker_id,
                    knobs=knobs)
        model: Optional[BaseModel] = None
        persisted_async = False
        try:
            with telemetry.span("trial.total", trial_id=tid,
                                worker_id=self.worker_id), \
                    ledger.entity(f"trial:{tid}"), \
                    logger.capture(sink), self._device_scope(), \
                    self._profile_scope(tid):
                with telemetry.span("trial.build", trial_id=tid):
                    model = self.model_class(**knobs)
                    if self.devices is not None and len(self.devices) > 1 and hasattr(model, "set_mesh"):
                        from rafiki_tpu.parallel.mesh import data_parallel_mesh

                        model.set_mesh(data_parallel_mesh(self.devices))
                    self._wire_checkpoints(model, tid, resume)
                with telemetry.span("trial.train", trial_id=tid):
                    model.train(self.train_uri)
                with telemetry.span("trial.evaluate", trial_id=tid):
                    score = float(model.evaluate(self.val_uri))
            # The advisor hears the score immediately (it steers the next
            # proposal); parameter persistence is NOT on the critical
            # path — the saver thread dumps/writes/marks-completed while
            # this worker trains the next trial. Serial dump can cost as
            # much as a short trial's train+eval (device→host fetch +
            # serialize), so overlapping it nearly doubles short-trial
            # throughput.
            self.advisor.feedback(score, knobs)
            if self.curve is not None:
                self.curve.note_scored(knobs, score)
            telemetry.inc("worker.trials_succeeded")
            if self._saver is not None:
                self._saver.submit(tid, model, score, sink)
                persisted_async = True  # saver owns model.destroy() now
            else:
                with logger.capture(sink):
                    self._persist(tid, model, score)
            return self.store.get_trial(tid)
        except EarlyKilled as e:
            # Learning-curve kill (docs/early_kill.md): same shape as
            # the divergence arm — fail the trial FAST with a diagnosis,
            # charge the doomed bucket, keep the worker loop alive. The
            # consolation feedback carries the conservative PREDICTED
            # score (it can never beat best-so-far — the kill rule
            # required hi < best - margin), which steers the advisor
            # more honestly than a 0.0 floor and replays identically
            # from the audit journal on rehydration.
            fit = e.fit
            telemetry.inc("worker.trials_killed")
            self.store.mark_trial_as_errored(
                tid, f"early_killed: predicted {fit.predicted_final:.4f} "
                     f"(hi {fit.hi:.4f}) vs best {e.best:.4f} "
                     f"at epoch {e.epoch}")
            events.emit("trial_killed", trial_id=tid,
                        worker_id=self.worker_id, epoch=e.epoch,
                        predicted=fit.predicted_final)
            self.curve.note_done(knobs)
            search_audit.note_doomed(knobs)
            try:
                self.advisor.feedback(fit.predicted_final, knobs)
            except Exception:
                pass
            return self.store.get_trial(tid)
        except _health.DivergenceError as e:
            # Numerics containment (docs/health.md): the train loop
            # already journaled the divergence, banked the replay
            # capsule and charged the wasted wall to badput. The
            # worker's half of the contract is to fail the trial FAST
            # with the diagnosis (not a stack trace), steer the advisor
            # away from the region, and keep the worker loop alive.
            v = e.verdict
            telemetry.inc("worker.trials_errored")
            self.store.mark_trial_as_errored(tid, f"diverged: {e}")
            events.emit("trial_diverged", trial_id=tid,
                        worker_id=self.worker_id,
                        divergence=v.get("divergence"),
                        bad_step=v.get("bad_step"),
                        capsule=v.get("capsule"),
                        diagnosis=v.get("diagnosis"))
            _health.note_contained()
            if self.curve is not None:
                self.curve.note_done(knobs)
            # Doomed BEFORE the consolation feedback: the search ledger
            # charges this trial's wall to doomed_s, not scored_s.
            search_audit.note_doomed(knobs)
            try:
                self.advisor.feedback(0.0, knobs)
            except Exception:
                pass
            return self.store.get_trial(tid)
        except Exception:
            err = traceback.format_exc()
            telemetry.inc("worker.trials_errored")
            self.store.mark_trial_as_errored(tid, err)
            events.emit("trial_errored", trial_id=tid, worker_id=self.worker_id,
                        error=err.splitlines()[-1] if err else "")
            # Feed the advisor a floor score so it learns to avoid the
            # region instead of re-proposing it (reference just skips).
            if self.curve is not None:
                self.curve.note_done(knobs)
            search_audit.note_doomed(knobs)
            try:
                self.advisor.feedback(0.0, knobs)
            except Exception:
                pass
            return self.store.get_trial(tid)
        finally:
            _trace_scope.close()
            if model is not None and not persisted_async:
                model.destroy()

    def _wire_checkpoints(self, model: BaseModel, tid: str, resume: bool) -> None:
        """Attach mid-trial checkpointing (and restore on resume) when
        the model supports it and a cadence is configured."""
        import os as _os

        multihost = int(_os.environ.get("RAFIKI_NUM_PROCESSES", "1")) > 1
        if resume and hasattr(model, "restore_checkpoint") and not multihost:
            # Multihost groups must NOT restore: followers mirror an
            # adopted trial from epoch 0 (worker/follower.py has no
            # checkpoint channel), so a leader resuming mid-stream would
            # issue fewer collective programs than its followers replay
            # — SPMD pairing beats saved progress.
            latest = self.params_store.latest_checkpoint(tid)
            if latest is not None:
                epoch, blob = latest
                try:
                    start = model.restore_checkpoint(blob)
                    events.emit("trial_resumed", trial_id=tid,
                                from_epoch=start, worker_id=self.worker_id)
                except Exception:
                    # An unreadable checkpoint (e.g. written by an older
                    # state format) must not error the trial — the knobs
                    # are fine; rerun from scratch. Keep the cause: a
                    # systematic format regression must be tellable
                    # apart from one stale legacy blob.
                    events.emit("checkpoint_restore_failed", trial_id=tid,
                                worker_id=self.worker_id,
                                error=traceback.format_exc(limit=5))
        # The sink is also the per-epoch chaos hook site (worker.epoch:
        # kill-at-epoch-N faults), so it gets wired whenever a plane is
        # active even with checkpointing off.
        every = self.checkpoint_every
        if ((every > 0 or chaos.active() is not None)
                and hasattr(model, "set_checkpoint_sink")):
            def sink(epoch: int, make_blob) -> None:
                if every > 0 and (epoch + 1) % every == 0:
                    self._save_checkpoint(tid, epoch, make_blob)
                # AFTER the write: a kill-at-epoch-N fault lands with
                # epoch N's checkpoint already durable, which is the
                # contract resume scenarios assert.
                chaos.hook("worker.epoch", key=self.worker_id)

            model.set_checkpoint_sink(sink)

    def _save_checkpoint(self, tid: str, epoch: int, make_blob) -> None:
        """Write one mid-trial checkpoint, absorbing write failures: a
        checkpoint is an optimization, and a full disk (or an injected
        ``store.params_write`` fault) must cost resumability, not the
        trial — the training loop has the real result in device memory
        and must keep going."""
        t0 = time.monotonic()
        try:
            self.params_store.save_checkpoint(tid, epoch, make_blob())
            events.emit("checkpoint_written", trial_id=tid, epoch=epoch,
                        worker_id=self.worker_id)
        except Exception:
            telemetry.inc("worker.checkpoint_write_failed")
            events.emit("checkpoint_write_failed", trial_id=tid, epoch=epoch,
                        worker_id=self.worker_id,
                        error=traceback.format_exc(limit=3))
        finally:
            # lint: disable=RF007 — checkpoint_s ledger charge, not a span
            ledger.add("checkpoint_s", time.monotonic() - t0,
                       entity=f"trial:{tid}")

    def resume_trial(self, trial_id: str) -> dict:
        """Re-run an interrupted trial, continuing from its newest
        mid-trial checkpoint if one exists (fresh start otherwise). The
        reference cannot do this — a crashed trial is lost (SURVEY.md
        §5 'no mid-trial checkpointing')."""
        trial = self.store.get_trial(trial_id)
        if trial is None:
            raise KeyError(f"No trial {trial_id!r}")
        out = self.run_trial(trial["knobs"], resume_trial_id=trial_id)
        if self._saver is not None:
            # Recovery is a synchronous API: the caller wants the final
            # status, so drain the saver before reading the row.
            self._saver.flush()
            out = self.store.get_trial(trial_id)
        return out

    def _persist(self, tid: str, model: BaseModel, score: float) -> None:
        """Dump → write → mark completed (runs on the saver thread when
        async persistence is on)."""
        t0 = time.monotonic()
        try:
            with telemetry.span("trial.persist", trial_id=tid):
                blob = model.dump_parameters()
                params_id = self.params_store.save(blob)
                self.store.mark_trial_as_completed(tid, score, params_id)
                self.params_store.delete_checkpoints(tid)  # superseded
            # Persist runs on the saver thread (no bound entity there),
            # so the charge names its trial explicitly.
            # lint: disable=RF007 — checkpoint_s ledger charge, not a span
            ledger.add("checkpoint_s", time.monotonic() - t0,
                       entity=f"trial:{tid}")
            events.emit("trial_completed", trial_id=tid, score=score,
                        worker_id=self.worker_id)
        except Exception:
            err = traceback.format_exc()
            self.store.mark_trial_as_errored(tid, f"params persist failed:\n{err}")
            events.emit("trial_errored", trial_id=tid, worker_id=self.worker_id,
                        error="params persist failed")

    def _device_scope(self):
        import contextlib

        if self.devices and len(self.devices) == 1:
            import jax

            return jax.default_device(self.devices[0])
        return contextlib.nullcontext()

    @staticmethod
    def _profile_scope(trial_id: str):
        """Per-trial XLA profiler trace when RAFIKI_PROFILE_DIR is set
        (SURVEY.md §5: "jax.profiler trace per trial"). Traces land in
        <dir>/<trial_id>/ viewable in TensorBoard / Perfetto."""
        import contextlib
        import os

        profile_dir = os.environ.get("RAFIKI_PROFILE_DIR")
        if not profile_dir:
            return contextlib.nullcontext()
        import jax

        return jax.profiler.trace(os.path.join(profile_dir, trial_id))

    # -- the loop ------------------------------------------------------------

    def adopt_orphans_of_service(self, prev_service_id: str) -> int:
        """Resume RUNNING trials stranded by a dead predecessor worker.

        The in-job half of elastic recovery: when the scheduler restarts
        a crashed worker (scheduler/process.py supervise loop), the
        replacement CAS-adopts each trial still bound to the dead
        worker's service row — a racing periodic recovery sweep then
        loses the CAS, so every orphan is re-run exactly once — and
        re-runs it (from its newest mid-trial checkpoint when one
        exists). The predecessor already claimed these trials' budget
        slots, so the job still completes its exact trial count.
        """
        n = 0
        for t in self.store.get_trials_of_sub_train_job(self.sub_id):
            if (t["status"] != TrialStatus.RUNNING.value
                    or t.get("service_id") != prev_service_id):
                continue
            if not self.store.adopt_trial(t["id"], prev_service_id,
                                          self.service_id, self.worker_id):
                continue  # recovery sweep won the race; its re-run owns it
            self.resume_trial(t["id"])
            self.trials_run += 1
            n += 1
        return n

    def run(self) -> int:
        """Pull trials until the budget is exhausted. Returns #trials run."""
        max_trials = self.budget.get(BudgetType.MODEL_TRIAL_COUNT.value)
        budget_max = int(max_trials) if max_trials is not None else None
        packer = None
        if self.trial_pack > 1:
            packer = PackedTrialRunner(self, self.trial_pack)
            if not packer.eligible():
                packer = None  # serial loop below — packing silently off
        try:
            while not self.budget_exhausted():
                if packer is not None:
                    ran, drained = packer.run_round(budget_max)
                    self.trials_run += ran
                    if ran and self.service_id is not None:
                        self.store.update_service(self.service_id, heartbeat=True)
                    if drained:
                        break
                    continue
                with telemetry.span("trial.advisor_propose",
                                    worker_id=self.worker_id):
                    knobs = self.advisor.propose()
                # Slot-claim happens atomically inside the trial-row
                # insert (crash between claim and insert cannot leak a
                # budget slot); None back = budget drained, the unused
                # proposal is simply dropped.
                if self.run_trial(knobs, budget_max=budget_max) is None:
                    break
                self.trials_run += 1
                if self.service_id is not None:
                    self.store.update_service(self.service_id, heartbeat=True)
        finally:
            if self._saver is not None:
                # close() flushes first: every trial durable before we
                # return, and the saver thread actually exits (a bare
                # flush would leak one live thread per worker).
                self._saver.close()
        return self.trials_run


class PackedTrialRunner:
    """Drafts up to ``pack`` proposals per round, buckets them by
    packing key, and trains each multi-trial bucket as ONE vmapped XLA
    program (``JaxModel.train_packed``) on this worker's device.

    Every PER-TRIAL contract is preserved: store rows (one per trial,
    budget-claimed atomically at creation), scores, advisor feedback,
    TrialLog entries, params persistence and lifecycle events are
    exactly those of k serial trials — only the wall-clock is shared.
    Recovery, the predictor's top-k and the gateway therefore see no
    difference (docs/trial_packing.md).
    """

    def __init__(self, worker: "TrainWorker", pack: int):
        self.w = worker
        self.pack = max(1, int(pack))

    def eligible(self) -> bool:
        """Packing preconditions, checked once per run(): a packable
        JaxModel template, a single-device worker (the trial axis IS
        the parallelism — meshes and multihost SPMD groups must stay
        serial), and an unmasked train dataset."""
        import os

        from rafiki_tpu.model.base import JaxModel

        w = self.w
        if self.pack < 2:
            return False
        if not (isinstance(w.model_class, type)
                and issubclass(w.model_class, JaxModel)):
            return False
        if not w.model_class.packable():
            return False
        if w.devices is not None and len(w.devices) > 1:
            return False
        if int(os.environ.get("RAFIKI_NUM_PROCESSES", "1")) > 1:
            return False
        try:
            from rafiki_tpu.model.dataset import dataset_utils

            if dataset_utils.load(w.train_uri).mask is not None:
                return False
        except Exception:
            return False
        return True

    def run_round(self, budget_max: Optional[int]) -> "tuple[int, bool]":
        """One draft-bucket-train round. Returns (trials run, budget
        drained). Proposals whose packing key matches no other run
        serially; same-key groups run packed."""
        w = self.w
        with telemetry.span("trial.advisor_propose", worker_id=w.worker_id):
            batch = getattr(w.advisor, "propose_batch", None)
            proposals = (batch(self.pack) if batch is not None
                         else [w.advisor.propose() for _ in range(self.pack)])
        buckets: Dict[Any, List[Knobs]] = {}
        order: List[Any] = []
        for kn in proposals:
            try:
                m = w.model_class(**kn)
                key = repr(m.packing_key(m._prepared_dataset(w.train_uri)))
            except Exception:
                key = ("unpackable", id(kn))  # unique → runs serially
            if key not in buckets:
                order.append(key)
                buckets[key] = []
            buckets[key].append(kn)
        ran = 0
        for key in order:
            knobs_list = buckets[key]
            if len(knobs_list) == 1:
                if w.run_trial(knobs_list[0], budget_max=budget_max) is None:
                    return ran, True
                ran += 1
            else:
                n, drained = self._run_packed(knobs_list, budget_max)
                ran += n
                if drained:
                    return ran, True
        return ran, False

    def _run_packed(self, knobs_list: List[Knobs],
                    budget_max: Optional[int]) -> "tuple[int, bool]":
        w = self.w
        knob_config = w.model_class.get_knob_config()
        # Claim all rows up front (each claim is an atomic budget slot,
        # same transaction as the serial path); the pack shrinks to
        # whatever the budget still allows.
        rows: List["tuple[str, Knobs]"] = []
        drained = False
        for kn in knobs_list:
            trial = w.store.create_trial(
                w.sub_id, w.model_class.__name__, kn,
                worker_id=w.worker_id,
                shape_sig=knob_config_signature(knob_config, kn),
                service_id=w.service_id, budget_max=budget_max)
            if trial is None:
                drained = True
                break
            rows.append((trial["id"], kn))
        if not rows:
            return 0, True
        if len(rows) == 1:
            # Budget pressure shrank the pack to one: run it serially,
            # reusing the already-claimed row via the resume path.
            out = w.run_trial(rows[0][1], resume_trial_id=rows[0][0])
            return (1 if out is not None else 0), drained
        return self._train_rows(rows, budget_max, drained)

    def run_assigned(self, rows: "List[tuple[str, Knobs]]",
                     budget_max: Optional[int] = None,
                     abort=None) -> int:
        """Train an externally-claimed set of trial rows as one pack
        (the mesh scheduler's entry point — it creates rows up front
        and assigns them chip by chip). ``abort`` is a threading.Event:
        when set, the pack raises :class:`PackAborted` at the next
        epoch boundary — AFTER that epoch's checkpoints are durable —
        leaving every row RUNNING for re-packing. Returns the number
        of rows carried to completion (success or errored)."""
        if not rows:
            return 0
        n, _ = self._train_rows(list(rows), budget_max, False, abort=abort)
        return n

    def _train_rows(self, rows: "List[tuple[str, Knobs]]",
                    budget_max: Optional[int], drained: bool,
                    abort=None) -> "tuple[int, bool]":
        w = self.w
        knob_config = w.model_class.get_knob_config()
        k = len(rows)
        telemetry.observe("trial_pack.size", float(k))
        telemetry.observe("trial_pack.fill_ratio", k / float(self.pack))
        for tid, kn in rows:
            events.emit("trial_started", trial_id=tid, sub_job_id=w.sub_id,
                        model=w.model_class.__name__, worker_id=w.worker_id,
                        knobs=kn)
        models: List[BaseModel] = []
        # model_index -> condemning CurveFit; filled by kill_pred below,
        # read by on_evict (bookkeeping) and the post-train loop (skip).
        killed: Dict[int, Any] = {}
        pack_entity = f"pack:{w.worker_id}:k{k}"
        try:
            # One pack = one trace + one ledger entity: the pack's
            # compile/step/feed/checkpoint split is shared cost across
            # its k trials (docs/observability.md).
            with trace_context.trace(trace_context.new_trace_id()), \
                    telemetry.span("trial_pack.total", worker_id=w.worker_id,
                                   k=k), \
                    ledger.entity(pack_entity), w._device_scope():
                with telemetry.span("trial_pack.build"):
                    models = [w.model_class(**kn) for _, kn in rows]

                t_pack0 = time.monotonic()
                round_walls: List[float] = []

                def heartbeat(_epoch: int) -> None:
                    # Pack-relative wall at each round boundary: the
                    # post-hoc epoch_eval journal replay (below) joins
                    # member epoch -> round position -> this wall.
                    # lint: disable=RF007 — epoch_eval wall field, already under trial_pack.total
                    round_walls.append(time.monotonic() - t_pack0)
                    # Abort lands at the epoch boundary AFTER the
                    # checkpoint sink ran, so the newest epoch of every
                    # member is durable before the pack unwinds.
                    if abort is not None and abort.is_set():
                        raise PackAborted(
                            f"pack on {w.worker_id} aborted at epoch boundary")
                    if w.service_id is not None:
                        now = time.monotonic()
                        if now - w._last_heartbeat >= w.heartbeat_min_interval_s:
                            w._last_heartbeat = now
                            w.store.update_service(w.service_id, heartbeat=True)

                def on_evict(mi: int, epoch: int, reason: str) -> None:
                    events.emit("pack_member_evicted", trial_id=rows[mi][0],
                                epoch=epoch, reason=reason,
                                worker_id=w.worker_id)
                    if reason != "killed":
                        return
                    # Early-kill bookkeeping runs HERE — before the
                    # backfill closure proposes into the freed slot —
                    # so the replacement proposal is steered by this
                    # trial's consolation feedback (the conservative
                    # predicted score; same contract as the serial
                    # EarlyKilled arm, docs/early_kill.md).
                    tid_k, kn_k = rows[mi]
                    fit = killed.get(mi)
                    pred = fit.predicted_final if fit is not None else 0.0
                    telemetry.inc("worker.trials_killed")
                    w.store.mark_trial_as_errored(
                        tid_k, f"early_killed: predicted {pred:.4f} "
                               f"at epoch {epoch}")
                    events.emit("trial_killed", trial_id=tid_k,
                                worker_id=w.worker_id, epoch=epoch,
                                predicted=pred)
                    w.curve.note_done(kn_k)
                    search_audit.note_doomed(kn_k)
                    try:
                        w.advisor.feedback(pred, kn_k)
                    except Exception:
                        pass

                kill_pred = None
                if w.curve is not None:
                    def kill_pred(mi: int, epoch: int, metrics) -> bool:
                        # Feed the live packed curve point, then ask.
                        # Same higher-is-better guard as the serial
                        # sink: loss-only packs are never killed.
                        tid_k, kn_k = rows[mi]
                        acc = (metrics or {}).get("acc")
                        if acc is None:
                            return False
                        w.curve.observe(kn_k, epoch, float(acc),
                                        trial_id=tid_k)
                        fit = w.curve.kill_verdict(kn_k, epoch,
                                                   trial_id=tid_k)
                        if fit is None:
                            return False
                        killed[mi] = fit
                        return True

                def backfill(n: int) -> List[BaseModel]:
                    """Fill freed pack slots with freshly proposed
                    trials mid-pack. Proposals whose packing_key differs
                    from the live pack's are dropped (they'd need their
                    own program; the next round picks them up via the
                    normal draft path) BEFORE any row is claimed."""
                    nonlocal drained
                    if drained or w.advisor is None:
                        return []
                    # Speculative scoring (docs/early_kill.md): feed
                    # the advisor predicted scores for pack-mates still
                    # mid-flight so this proposal doesn't draft blind
                    # next to the constant-liar floor. No-op unless
                    # RAFIKI_CURVE_SPECULATE is set.
                    if w.curve is not None:
                        w.curve.speculate_inflight(w.advisor)
                    pack_key = repr(models[0].packing_key(
                        models[0]._prepared_dataset(w.train_uri)))
                    out: List[BaseModel] = []
                    for _ in range(n):
                        try:
                            kn = w.advisor.propose()
                            m2 = w.model_class(**kn)
                            if repr(m2.packing_key(
                                    m2._prepared_dataset(w.train_uri))) != pack_key:
                                telemetry.inc("trial_pack.backfill_key_mismatch")
                                continue
                        except Exception:
                            continue
                        wal = getattr(w, "wal", None)
                        txn = None if wal is None else wal.intent(
                            "backfill", sub_id=w.sub_id,
                            knobs_hash=search_audit.knobs_hash(kn))
                        trial = w.store.create_trial(
                            w.sub_id, w.model_class.__name__, kn,
                            worker_id=w.worker_id,
                            shape_sig=knob_config_signature(knob_config, kn),
                            service_id=w.service_id, budget_max=budget_max)
                        if trial is None:
                            if txn is not None:
                                wal.commit(txn, "backfill", denied=True)
                            drained = True
                            break
                        if txn is not None:
                            wal.commit(txn, "backfill",
                                       trial_id=trial["id"])
                        rows.append((trial["id"], kn))
                        events.emit("trial_started", trial_id=trial["id"],
                                    sub_job_id=w.sub_id,
                                    model=w.model_class.__name__,
                                    worker_id=w.worker_id, knobs=kn)
                        out.append(m2)
                    return out

                # Per-epoch checkpoints for the WHOLE pack: each trial
                # gets its own serial-format checkpoint sliced out of
                # the live pack, so a killed pack resumes every member
                # independently (serially) from its newest epoch — the
                # pack itself is never serialized. Wired whenever a
                # cadence is set, and whenever a chaos plane is active
                # (the sink doubles as the worker.epoch fault site, same
                # as the serial path).
                every = w.checkpoint_every
                ckpt_sink = None
                if every > 0 or chaos.active() is not None:
                    def ckpt_sink(epoch: int, make_blobs) -> None:
                        if every > 0 and (epoch + 1) % every == 0:
                            self._save_pack_checkpoints(rows, epoch, make_blobs)
                        # AFTER the writes: a kill-at-epoch-N fault lands
                        # with every member's epoch-N snapshot durable.
                        chaos.hook("worker.epoch", key=w.worker_id)

                with telemetry.span("trial_pack.train"):
                    histories = w.model_class.train_packed(
                        models, w.train_uri, on_epoch=heartbeat,
                        checkpoint_sink=ckpt_sink,
                        backfill=backfill, on_evict=on_evict,
                        kill_predicate=kill_pred)
                # Numerics containment (docs/health.md): members the
                # pack evicted for divergence carry a verdict and hold
                # their params as-of the bad epoch — they must not
                # reach evaluation (a NaN score row would poison the
                # advisor's scale). Survivors evaluate as usual.
                verdicts = [getattr(m, "_health_verdict", None)
                            for m in models]
                # Killed members skip evaluation too — scoring them
                # would spend exactly the wall the kill saved.
                healthy_idx = [i for i, v in enumerate(verdicts)
                               if v is None and i not in killed]
                with telemetry.span("trial_pack.evaluate"):
                    healthy_scores = (w.model_class.evaluate_packed(
                        [models[i] for i in healthy_idx], w.val_uri)
                        if healthy_idx else [])
                scores: List[Optional[float]] = [None] * len(models)
                for j, i in enumerate(healthy_idx):
                    scores[i] = healthy_scores[j]
        except PackAborted:
            # Supervisor-driven teardown: rows STAY RUNNING (the mesh
            # re-packs them onto surviving chips), device state is
            # released, and the abort propagates to the caller.
            for m in models:
                try:
                    m.destroy()
                except Exception:
                    pass
            raise
        except Exception:
            err = traceback.format_exc()
            for i, (tid, kn) in enumerate(rows):
                if i in killed:
                    # Already marked errored + fed back in on_evict.
                    continue
                telemetry.inc("worker.trials_errored")
                w.store.mark_trial_as_errored(tid, err)
                events.emit("trial_errored", trial_id=tid, worker_id=w.worker_id,
                            error=err.splitlines()[-1] if err else "")
                # Same floor-score contract as the serial path: the
                # advisor learns to avoid the region.
                search_audit.note_doomed(kn)
                try:
                    w.advisor.feedback(0.0, kn)
                except Exception:
                    pass
            for m in models:
                try:
                    m.destroy()
                except Exception:
                    pass
            return len(rows), drained

        # Completed packs supersede their mid-trial checkpoints the same
        # way serial trials do (_persist deletes them per trial below).
        # Per-trial bookkeeping in creation order — logs, feedback,
        # persistence — indistinguishable from k serial trials.
        for i, (tid, kn) in enumerate(rows):
            def sink(entry, _tid=tid):
                w.store.add_trial_log(_tid, entry)

            with logger.capture(sink):
                logger.define_plot("Training", ["loss", "acc"], x_axis="epoch")
                for pos, h in enumerate(histories[i]):
                    logger.log(**h)
                    # Position in a member's history == the round it
                    # ran at (exact for whole-pack members; backfilled
                    # members join mid-pack, so their early positions
                    # borrow the pack's early-round walls — close, and
                    # honest about being pack-relative).
                    _journal_epoch_eval(
                        tid, {"type": "values", "values": h},
                        wall_s=(round_walls[pos]
                                if pos < len(round_walls) else None),
                        packed=True)
            if i in killed:
                # Store row, doomed charge and consolation feedback all
                # happened in on_evict (pre-backfill); the epoch_eval
                # journal replay above still ran — the curve prefix is
                # exactly what `obs curves --predicted` audits a kill
                # against.
                try:
                    models[i].destroy()
                except Exception:
                    pass
                continue
            if verdicts[i] is not None:
                # Same contract as the serial DivergenceError arm:
                # ERRORED with the diagnosis, floor score to the
                # advisor, containment counted — and no persistence
                # (the params ARE the divergent state; the capsule is
                # the forensic artifact, not the params store).
                v = verdicts[i]
                telemetry.inc("worker.trials_errored")
                w.store.mark_trial_as_errored(
                    tid, f"diverged: {v.get('diagnosis')}")
                events.emit("trial_diverged", trial_id=tid,
                            worker_id=w.worker_id,
                            divergence=v.get("divergence"),
                            bad_step=v.get("bad_step"),
                            capsule=v.get("capsule"),
                            diagnosis=v.get("diagnosis"))
                _health.note_contained()
                if w.curve is not None:
                    w.curve.note_done(kn)
                search_audit.note_doomed(kn)
                try:
                    w.advisor.feedback(0.0, kn)
                except Exception:
                    pass
                try:
                    models[i].destroy()
                except Exception:
                    pass
                continue
            score = float(scores[i])
            w.advisor.feedback(score, kn)
            if w.curve is not None:
                w.curve.note_scored(kn, score)
            telemetry.inc("worker.trials_succeeded")
            telemetry.inc("worker.packed_trials")
            if w._saver is not None:
                w._saver.submit(tid, models[i], score, None)
            else:
                w._persist(tid, models[i], score)
        telemetry.inc("worker.packed_rounds")
        return len(rows), drained

    def _save_pack_checkpoints(self, rows, epoch: int, make_blobs) -> None:
        """Write one epoch's per-trial checkpoints for the pack, with
        the serial path's durability contract: a failed write (full
        disk, injected ``store.params_write`` fault) costs that trial's
        resumability, never the pack — training has the real state in
        device memory and must keep going."""
        w = self.w
        t0 = time.monotonic()
        try:
            blobs = make_blobs()
        except Exception:
            telemetry.inc("worker.checkpoint_write_failed")
            events.emit("checkpoint_write_failed", epoch=epoch,
                        worker_id=w.worker_id, trial_id=rows[0][0],
                        error=traceback.format_exc(limit=3))
            # lint: disable=RF007 — checkpoint_s ledger charge, not a span
            ledger.add("checkpoint_s", time.monotonic() - t0)
            return
        # make_blobs() yields (model_index, member_epoch, blob) — each
        # member's checkpoint is filed under its OWN epoch counter
        # (evicted/backfilled members drift from the pack round index).
        for mi, member_epoch, blob in blobs:
            tid = rows[mi][0]
            try:
                w.params_store.save_checkpoint(tid, member_epoch, blob)
                events.emit("checkpoint_written", trial_id=tid,
                            epoch=member_epoch, worker_id=w.worker_id)
            except Exception:
                telemetry.inc("worker.checkpoint_write_failed")
                events.emit("checkpoint_write_failed", trial_id=tid,
                            epoch=member_epoch, worker_id=w.worker_id,
                            error=traceback.format_exc(limit=3))
        # Charged to the bound pack entity (the sink runs inside it).
        # lint: disable=RF007 — checkpoint_s ledger charge, not a span
        ledger.add("checkpoint_s", time.monotonic() - t0)


class _AsyncSaver:
    """One background thread persisting trial parameters off the
    critical path. Bounded to one pending save: at most two param sets
    are alive at once (the one being written and the one training), so
    memory stays flat; a slow disk degrades to serial, never unbounded.
    """

    def __init__(self, worker: "TrainWorker"):
        import queue
        import threading

        self._worker = worker
        self._q: "queue.Queue" = queue.Queue(maxsize=1)
        self._thread = threading.Thread(target=self._loop,
                                        name=f"saver-{worker.worker_id}",
                                        daemon=True)
        self._thread.start()

    def submit(self, trial_id: str, model: BaseModel, score: float,
               sink=None) -> None:
        import threading

        if not self._thread.is_alive():
            # close()d by a previous run(); restart for the new caller
            # (single-producer, so no start race).
            self._thread = threading.Thread(
                target=self._loop, name=self._thread.name, daemon=True)
            self._thread.start()
        self._q.put((trial_id, model, score, sink))

    def _loop(self) -> None:
        import contextlib

        while True:
            item = self._q.get()
            if item is None:
                self._q.task_done()
                return
            trial_id, model, score, sink = item
            try:
                # Re-enter the trial's log capture on this thread so
                # logger.log() calls during dump still land in TrialLog.
                scope = (logger.capture(sink) if sink is not None
                         else contextlib.nullcontext())
                with scope:
                    self._worker._persist(trial_id, model, score)
            except Exception:
                # _persist already contains failures; the saver thread
                # must never die — but what it absorbs gets counted
                # (RF006: a silent swallow in a long-running loop hides
                # every failure the loop will ever have).
                telemetry.inc("worker.saver_errors")
            finally:
                try:
                    model.destroy()
                # lint: disable=RF006 — a throwing user destroy() must not kill the saver; nothing to recover
                except Exception:
                    pass
                self._q.task_done()

    def flush(self) -> None:
        """Block until all submitted saves are durable."""
        self._q.join()

    def close(self) -> None:
        self.flush()
        self._q.put(None)
        self._thread.join(timeout=10)


def build_worker_from_store(store: MetaStore, params_store: ParamsStore,
                            sub_train_job_id: str, advisor: AdvisorHandle,
                            worker_id: str = "worker-0", devices=None,
                            stop_event=None, async_persist: bool = True) -> TrainWorker:
    """Reconstruct a TrainWorker from meta-store rows (the entrypoint a
    subprocess worker uses, mirroring the reference's env-var-driven
    container entrypoint)."""
    sub_row = store.get_sub_train_job(sub_train_job_id)
    if sub_row is None:
        raise KeyError(f"No sub train job {sub_train_job_id!r}")
    job = store.get_train_job(sub_row["train_job_id"])
    model = store.get_model(sub_row["model_id"])
    model_cls = load_model_class(model["model_file"], model["model_class"])
    return TrainWorker(
        store, params_store, sub_train_job_id, model_cls, advisor,
        job["train_dataset_uri"], job["val_dataset_uri"], job["budget"],
        worker_id=worker_id, devices=devices, job_created_at=job["created_at"],
        stop_event=stop_event, async_persist=async_persist,
    )
