"""Train-worker process entrypoint: ``python -m rafiki_tpu.worker.main``.

Reference parity: rafiki/worker/ entrypoints (unverified — SURVEY.md
§1 L5): the reference launches workers inside containers "driven by
env vars (service id, job id)". Same contract here — the
ProcessScheduler spawns this module with:

  RAFIKI_WORKER_DB            meta-store sqlite path
  RAFIKI_WORKER_PARAMS_DIR    params-store directory
  RAFIKI_WORKER_SUB_JOB_ID    sub-train-job to pull trials for
  RAFIKI_WORKER_ID            human-readable worker id
  RAFIKI_WORKER_SERVICE_ID    service row to heartbeat (optional)
  RAFIKI_WORKER_ADVISOR_URL   http://127.0.0.1:<port>
  RAFIKI_WORKER_ADVISOR_ID    advisor to ask for knobs
  RAFIKI_WORKER_ADVISOR_SECRET shared secret (optional)

Device pinning is inherited from the environment the scheduler set
(JAX_PLATFORMS / XLA_FLAGS / TPU_VISIBLE_CHIPS…): this process sees
only its own chips, giving each trial an isolated XLA runtime — the
TPU-native answer to the reference's one-GPU-per-container isolation.

Exit codes: 0 = budget exhausted cleanly, 1 = crash,
17 = backend-init watchdog timeout (TPU runtime unreachable).
"""

from __future__ import annotations

import os
import sys
import time


def initialize_collective(initialize, coordinator: str, num_processes: int,
                          process_id: int) -> None:
    """Join the distributed cluster with retry + exponential backoff.

    Collective initialization is the flakiest moment of a multihost
    job: a follower that races the coordinator's bind, or a transient
    DCN hiccup, fails ``jax.distributed.initialize`` even though the
    pod is healthy. Bounded retries (``RAFIKI_COLLECTIVE_INIT_RETRIES``,
    backoff ``RAFIKI_COLLECTIVE_INIT_BACKOFF_S`` doubling per attempt)
    absorb that; exhaustion re-raises the last error so the scheduler's
    restart-with-backoff path takes over. The ``collective.init`` chaos
    site is armed once per attempt (error mode = injected init
    failure), keyed ``p<process_id>`` (docs/chaos.md).
    """
    from rafiki_tpu import chaos
    from rafiki_tpu.utils.events import events

    retries = int(os.environ.get("RAFIKI_COLLECTIVE_INIT_RETRIES", "3"))
    backoff = float(os.environ.get("RAFIKI_COLLECTIVE_INIT_BACKOFF_S", "0.5"))
    for attempt in range(retries + 1):
        try:
            chaos.hook("collective.init", key=f"p{process_id}")
            initialize(coordinator_address=coordinator,
                       num_processes=num_processes,
                       process_id=process_id)
            return
        except Exception as e:
            if attempt >= retries:
                raise
            events.emit("collective_init_retry", process_id=process_id,
                        attempt=attempt, error=str(e))
            time.sleep(backoff * (2 ** attempt))


def main() -> int:
    db_path = os.environ["RAFIKI_WORKER_DB"]
    params_dir = os.environ["RAFIKI_WORKER_PARAMS_DIR"]
    sub_job_id = os.environ["RAFIKI_WORKER_SUB_JOB_ID"]
    worker_id = os.environ.get("RAFIKI_WORKER_ID", f"pw-{os.getpid()}")
    service_id = os.environ.get("RAFIKI_WORKER_SERVICE_ID")
    advisor_url = os.environ["RAFIKI_WORKER_ADVISOR_URL"]
    advisor_id = os.environ["RAFIKI_WORKER_ADVISOR_ID"]
    secret = os.environ.get("RAFIKI_WORKER_ADVISOR_SECRET")

    # Honour a CPU-platform request before jax initialises (the image's
    # sitecustomize force-registers a TPU backend otherwise).
    import jax

    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()

    # Backend-init watchdog: jax blocks indefinitely when the TPU
    # runtime is unreachable; a silent hang would stall the scheduler's
    # supervise loop with no diagnosis. Exit with a structured error
    # instead (the scheduler records it on the service row).
    import threading

    init_timeout = float(os.environ.get("RAFIKI_BACKEND_INIT_TIMEOUT_S", "180"))

    def _init_stuck():
        print(f"worker {worker_id}: FATAL backend init exceeded "
              f"{init_timeout:.0f}s (TPU runtime unreachable?) — exiting",
              flush=True)
        os._exit(17)

    watchdog = threading.Timer(init_timeout, _init_stuck)
    watchdog.daemon = True
    watchdog.start()

    # Persistent XLA compilation cache: a restarted (or sibling) worker
    # loads executables compiled by any previous process instead of
    # recompiling — the cross-process half of compile amortization (the
    # in-process half is ops.train's program cache).
    from rafiki_tpu.utils.backend import enable_compilation_cache

    enable_compilation_cache()

    # Multi-host pods: when the scheduler provides coordinator env, join
    # the jax.distributed cluster over DCN before touching devices —
    # this worker then sees its host's chips while collectives span the
    # pod (the reference's NCCL/MPI role is played by XLA here).
    # Process 0 of the group is the control-plane leader; the rest
    # mirror its trials compute-for-compute (worker/follower.py).
    coordinator = os.environ.get("RAFIKI_COORDINATOR_ADDRESS")
    if coordinator:
        from rafiki_tpu import chaos

        # jax gates cross-process CPU collectives behind a config
        # switch; without gloo a multi-process CPU group dies at first
        # program init with "Multiprocess computations aren't
        # implemented on the CPU backend". Must land before the backend
        # client is created; irrelevant (and skipped) on TPU platforms.
        if os.environ.get("JAX_PLATFORMS", "").strip().lower() == "cpu":
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "gloo")
            except Exception:
                pass  # older jax: CPU collectives need no gate

        process_id = int(os.environ["RAFIKI_PROCESS_ID"])
        # Start-skew site: a delay-mode fault here staggers this
        # process's arrival at the collective barrier (leader/follower
        # skew — docs/chaos.md).
        chaos.hook("mesh.skew", key=f"p{process_id}")
        initialize_collective(
            jax.distributed.initialize, coordinator,
            int(os.environ["RAFIKI_NUM_PROCESSES"]), process_id)

    jax.devices()  # force backend init under the watchdog
    watchdog.cancel()

    from rafiki_tpu.utils.events import configure_from_env, events

    configure_from_env()

    # Observability plane: per-process journal under RAFIKI_LOG_DIR
    # (spawn env), adopt the scheduler's RAFIKI_TRACE_ID as the process
    # default, dump a flight record on fatal/SIGTERM so a killed worker
    # leaves a reconstructible last-N trail (docs/observability.md).
    from rafiki_tpu import obs

    if obs.configure_from_env(role="train-worker"):
        obs.recorder.install()

    from rafiki_tpu.store import MetaStore, ParamsStore

    store = MetaStore(db_path)
    if coordinator:
        events.emit("multihost_init", worker_id=worker_id,
                    process_id=jax.process_index(),
                    process_count=jax.process_count(),
                    global_devices=len(jax.devices()),
                    local_devices=len(jax.local_devices()))
        from rafiki_tpu.parallel.multihost import is_leader

        if not is_leader():
            from rafiki_tpu.worker.follower import FollowerWorker

            n = FollowerWorker(
                store, sub_job_id,
                leader_worker_id=os.environ.get("RAFIKI_LEADER_WORKER_ID"),
                leader_service_id=os.environ.get("RAFIKI_LEADER_SERVICE_ID"),
            ).run()
            print(f"follower {worker_id}: mirrored {n} trials", flush=True)
            return 0

    from rafiki_tpu.advisor.app import HttpAdvisorHandle
    from rafiki_tpu.worker.train import build_worker_from_store

    params_store = ParamsStore(params_dir)
    advisor = HttpAdvisorHandle(advisor_url, advisor_id, secret=secret)
    worker = build_worker_from_store(
        store, params_store, sub_job_id, advisor,
        worker_id=worker_id, devices=jax.devices())
    worker.service_id = service_id
    try:
        # Restart path: this process replaces a crashed predecessor —
        # sweep every dead service row the scheduler recorded for this
        # slot and resume the orphaned trials bound to them (CAS-adopted
        # exactly once even against a racing recovery sweep).
        adopt_sids = os.environ.get("RAFIKI_WORKER_ADOPT_SERVICE_ID", "")
        for sid in filter(None, adopt_sids.split(",")):
            n_adopted = worker.adopt_orphans_of_service(sid)
            if n_adopted:
                print(f"worker {worker_id}: adopted {n_adopted} orphaned "
                      f"trial(s) of dead service {sid}", flush=True)
        n = worker.run()
    finally:
        if coordinator and service_id:
            # Tell our followers we're done BEFORE exiting — on the
            # crash path too: the scheduler only writes terminal
            # sub-job status after ALL group processes exit, so a
            # follower waiting on that (or on a service row a dead
            # leader never updated) would deadlock the group.
            from rafiki_tpu.constants import ServiceStatus

            store.update_service(service_id,
                                 status=ServiceStatus.STOPPED.value)
    print(f"worker {worker_id}: ran {n} trials", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
