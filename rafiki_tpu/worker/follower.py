"""Follower half of a multi-host dp worker group.

In a worker group spanning N processes (one per host of a pod slice),
process 0 — the leader — runs the ordinary ``TrainWorker`` trial loop:
store writes, advisor propose/feedback, params persistence. Processes
1..N-1 run this follower loop instead. SPMD requires every process to
execute the SAME sequence of collective programs, so the follower
mirrors each of the leader's trials compute-for-compute:

  * it watches the shared meta store for trials of its sub-job
    entering RUNNING (the leader creates the row BEFORE building the
    model, so the follower can never miss a trial's collectives);
  * for each, it builds the same model from the same knobs, joins the
    same dp mesh over the global device set, and calls train+evaluate —
    drawing identical batches (dataset iteration is seeded by trial
    seed + epoch) and feeding its local shards of them;
  * it performs NO store writes, NO advisor calls, NO persistence —
    single-headed control plane, replicated data plane;
  * it exits when the sub-job reaches a terminal status or the trial
    budget is exhausted and nothing is running.

Group-failure handling: if any group member dies mid-trial (worker
crash, OOM, SIGKILL), the scheduler's supervise loop detects the dead
process directly and tears the WHOLE group down at once — survivors
stuck inside a collective the dead peer abandoned are killed rather
than left to wait out the transport timeout — then respawns the group
(bounded restarts, exponential backoff); the new leader CAS-adopts the
orphaned trial and the followers mirror its re-run from epoch 0
(scheduler/process.py supervise loop, worker/train.py
adopt_orphans_of_service). Trial-level containment of *model* errors
still works: the leader catches them between collective programs.
"""

from __future__ import annotations

import time
from typing import Optional

from rafiki_tpu.constants import TrainJobStatus, TrialStatus
from rafiki_tpu.model.base import load_model_class
from rafiki_tpu.store import MetaStore

_TERMINAL = {TrainJobStatus.COMPLETED.value, TrainJobStatus.ERRORED.value,
             TrainJobStatus.STOPPED.value}


class FollowerWorker:
    def __init__(self, store: MetaStore, sub_train_job_id: str,
                 leader_worker_id: Optional[str] = None,
                 leader_service_id: Optional[str] = None,
                 poll_s: float = 0.2):
        self.store = store
        self.sub_id = sub_train_job_id
        # Scope to OUR group's leader: with several multihost worker
        # groups on one sub-job, mirroring another group's trials would
        # enter collectives our own leader never issues (deadlock).
        self.leader_worker_id = leader_worker_id
        self.leader_service_id = leader_service_id
        self.poll_s = poll_s
        self.mirrored = 0

    def _budget_drained(self, job: dict, trials: list) -> bool:
        budget = job.get("budget") or {}
        max_trials = budget.get("MODEL_TRIAL_COUNT")
        if max_trials is None:
            return False
        settled = [t for t in trials
                   if t["status"] in (TrialStatus.COMPLETED.value,
                                      TrialStatus.ERRORED.value)]
        return len(settled) >= int(max_trials)

    def run(self) -> int:
        """Mirror trials until the job ends. Returns #trials mirrored."""
        import jax

        sub = self.store.get_sub_train_job(self.sub_id)
        if sub is None:
            raise KeyError(f"No sub train job {self.sub_id!r}")
        job = self.store.get_train_job(sub["train_job_id"])
        model_row = self.store.get_model(sub["model_id"])
        model_cls = load_model_class(model_row["model_file"],
                                     model_row["model_class"])
        from rafiki_tpu.parallel.mesh import data_parallel_mesh

        mesh = data_parallel_mesh(jax.devices())
        seen = set()
        while True:
            trials = self.store.get_trials_of_sub_train_job(self.sub_id)
            ran_one = False
            for t in trials:
                if t["id"] in seen or t["status"] != TrialStatus.RUNNING.value:
                    continue
                if (self.leader_worker_id is not None
                        and t.get("worker_id") != self.leader_worker_id):
                    continue  # another group's trial
                seen.add(t["id"])
                ran_one = True
                model = None
                try:
                    # Construction stays INSIDE the containment: a
                    # knob-dependent constructor error raises on the
                    # leader too (same class, same knobs) and must not
                    # kill this process — a dead follower stalls the
                    # group at the next collective.
                    model = model_cls(**t["knobs"])
                    if hasattr(model, "set_mesh"):
                        model.set_mesh(mesh)
                    model.train(job["train_dataset_uri"])
                    model.evaluate(job["val_dataset_uri"])
                    self.mirrored += 1
                # lint: disable=RF006 — leader hits the identical error and owns reporting; the follower only keeps collectives paired
                except Exception:
                    # The leader owns error handling; our job was only
                    # to keep the collectives paired. If the model
                    # itself raised, it raised identically on the
                    # leader (same program, same data) before any
                    # collective mismatch.
                    pass
                finally:
                    try:
                        if model is not None:
                            model.destroy()
                    # lint: disable=RF006 — user-model destroy() must not kill the group; nothing to recover
                    except Exception:
                        pass
            if ran_one:
                continue  # look again immediately: the next trial may be up
            sub = self.store.get_sub_train_job(self.sub_id)
            if sub is None or sub["status"] in _TERMINAL:
                break
            if self._budget_drained(job, trials) and not any(
                    t["status"] == TrialStatus.RUNNING.value for t in trials):
                break
            if self._leader_done():
                # Covers budgets with no trial count (e.g. TIME_HOURS
                # only): the leader marks its service row terminal
                # before exiting; without this the follower would wait
                # for a sub-job status the scheduler only writes after
                # ALL group processes (including us) exit.
                break
            time.sleep(self.poll_s)
        return self.mirrored

    def _leader_done(self) -> bool:
        if self.leader_service_id is None:
            return False
        from rafiki_tpu.constants import ServiceStatus

        svc = self.store.get_service(self.leader_service_id)
        return svc is None or svc["status"] in (
            ServiceStatus.STOPPED.value, ServiceStatus.ERRORED.value)
