"""Data-plane workers: the processes/threads that touch devices.

Reference parity: rafiki/worker/ (train.py, inference.py, unverified
paths — SURVEY.md §2): worker entrypoints launched inside containers
and driven by env vars. Here workers are plain objects runnable
in-thread (LocalScheduler), or as subprocesses pinned to one chip
(ProcessScheduler) — the TPU-native analog of one-container-per-GPU.
"""

from rafiki_tpu.worker.train import AdvisorHandle, InProcAdvisorHandle, TrainWorker
from rafiki_tpu.worker.inference import InferenceWorker

__all__ = ["TrainWorker", "AdvisorHandle", "InProcAdvisorHandle", "InferenceWorker"]
