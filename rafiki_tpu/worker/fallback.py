"""Stacked-route loss fallback: watch the stacked worker's lease, and
when it dies (SIGKILL never runs remove_worker — the lease just goes
stale) degrade the job to the replicated per-trial route by spawning
fallback workers.

The stacked serving route (docs/serving.md) concentrates a job's whole
top-k ensemble in ONE worker process: a single process loss would
otherwise take the job from k-way redundancy to zero capacity. This
supervisor is the containment: it polls the bus's lease table (the same
liveness source the predictor routes by), and the moment the watched
worker drops out of the fresh set it journals ``serving/fallback`` and
invokes the caller-supplied ``spawn_fallback`` — typically starting
one-worker-per-trial replicated serving from the already-loaded params.
In-flight requests ride the gateway's blackout re-route
(``GatewayConfig.blackout_retries``) while the fallback spins up, so
nothing admitted is dropped; the chaos scenario
``stacked-worker-loss-fallback`` pins exactly that sequence.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal


class FallbackSupervisor:
    """Fire ``spawn_fallback()`` once when ``worker_id``'s lease dies.

    ``ttl_s`` mirrors the predictor's ``worker_ttl_s`` — supervisor and
    router must agree on what "dead" means, or the fallback would spawn
    while the router still fans out to the corpse (or vice versa).
    """

    def __init__(self, bus, job_id: str, worker_id: str,
                 spawn_fallback: Callable[[], None],
                 ttl_s: float = 3.0, poll_s: float = 0.25):
        self.bus = bus
        self.job_id = job_id
        self.worker_id = worker_id
        self._spawn = spawn_fallback
        self.ttl_s = ttl_s
        self.poll_s = poll_s
        self._stop = threading.Event()
        self.fired = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FallbackSupervisor":
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"fallback-{self.worker_id}")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _run(self) -> None:
        # Wait for the watched worker to exist at all before arming —
        # a supervisor started alongside the worker must not fire on
        # the registration race.
        while not self._stop.wait(self.poll_s):
            try:
                fresh = self.bus.get_workers(self.job_id,
                                             max_age_s=self.ttl_s)
            except Exception:  # bus manager teardown: exit quietly
                return
            if self.worker_id in fresh:
                break
        while not self._stop.wait(self.poll_s):
            try:
                fresh = self.bus.get_workers(self.job_id,
                                             max_age_s=self.ttl_s)
            except Exception:
                return
            if self.worker_id not in fresh:
                telemetry.inc("serving.fallbacks")
                _journal.record("serving", "fallback",
                                job_id=self.job_id,
                                lost_worker=self.worker_id,
                                route="replicated")
                try:
                    self._spawn()
                finally:
                    self.fired.set()
                return
