"""The recovery scenario catalog (docs/chaos.md).

Each scenario is a declarative bundle: a ``RAFIKI_CHAOS`` fault spec,
extra environment (inherited by subprocess workers), and a body that
stands up a real in-proc cluster — sqlite meta store, params store,
bus, subprocess or thread workers — lets the injected faults land, and
asserts the recovery invariants through ``check()``. The runner
(runner.py) owns env install/teardown, telemetry, and reporting; a
scenario body only builds the cluster and checks invariants.

Scenario bodies import the framework lazily: the CLI must be able to
pin the jax platform (``honor_env_platform``) before anything pulls in
jax (analysis rule RF001).

The catalog:

=============================  =============================================
kill-mid-trial-resume          worker SIGKILLs itself at epoch N mid-trial;
                               the supervise loop respawns, the replacement
                               adopts and resumes from the epoch-N
                               checkpoint; no lost/duplicated trial rows
kill-mid-pack-resume           the ISSUE acceptance scenario: a k=4 packed
                               run killed mid-pack resumes ALL members from
                               per-epoch slice checkpoints, and each
                               resumed trial's final params bit-match an
                               unfaulted serial run
straggler-quorum               one of three serving replicas stuck 3s per
                               forward; quorum gather answers fast without
                               timeout errors, hedging past the straggler
drain-under-load               gateway drain under background load with
                               injected frontend latency: flushes inflight,
                               sheds new work as ``draining``
predictor-outage-surfaces      every bus heartbeat skipped: the bounded
                               stale-lease grace serves through a hiccup,
                               then a real outage raises RuntimeError
checkpoint-write-failure       every checkpoint write errors; the trial
                               still completes (resumability lost, work
                               kept) and the failure is counted
mesh-chip-loss-repack          a chip preempted mid-sweep: the mesh
                               supervisor re-packs its RUNNING trials onto
                               the survivor, every trial completes with a
                               score, and resumed params bit-match
                               unfaulted serial runs
chip-loss-mid-sharded-trial    member 1 of a width-2 sharded group
                               preempted mid-trial: the group aborts at
                               the epoch boundary with that epoch's
                               manifest durable, re-forms at width 1,
                               resumes via reshard-on-restore, and the
                               final params bit-match an unfaulted run
pack-straggler-evict           one pack member early-stops epochs before
                               its mates: it is evicted from the stacked
                               state mid-pack, its slot backfilled with a
                               freshly proposed trial, and the evictee
                               bit-matches a serial early-stopped run
nan-trial-contained            member 2 of a k=4 pack gets one step's
                               grads NaN-poisoned: the divergence is
                               detected at the epoch boundary, a replay
                               capsule banked and bit-verified, the sick
                               member evicted and ERRORED with a
                               diagnosis, and the three survivors
                               complete with params bit-matching
                               unfaulted serial runs
collective-kill-mid-step       a dp-mesh worker SIGKILLed inside the
                               collective step path; the respawn resumes
                               from checkpoint and finishes the budget
mesh-degrades-single-chip      every mesh-formation attempt fails: the
                               sweep degrades to single-chip mode inside
                               its grace window and still completes
stacked-worker-loss-fallback   SIGKILL the stacked worker serving a whole
                               top-k ensemble mid-load: the fallback
                               supervisor degrades the job to replicated
                               per-trial workers, the gateway's blackout
                               re-route carries every admitted request to
                               an answer, and the loss→fallback story
                               reconstructs from the journals
load-spike-scale-up            the only serving replica pinned 0.3s slow:
                               the burn engine breaches serving p99, the
                               autoscale controller scales the lane up, and
                               the spike recovers — recovery-time-to-SLO
                               recorded for the bench trend gate
supervisor-kill-mid-sweep      SIGKILL the whole sweep-supervisor process
                               mid-sweep: resume_sweep in a fresh process
                               reconciles the WAL (zero double-claims),
                               rehydrates the GP advisor, adopts every
                               orphan, and the resumed sweep's best score
                               and knob set equal an unfaulted run's
host-loss-mid-sweep            two whole-host losses: survivors re-pack
                               the first lost host's rows, the second
                               loss takes the supervisor, resume adopts
                               the rest and finishes the budget
autoscale-flap-damping         an adversarial square-wave pressure signal
                               (plus injected sensor faults) on a fake
                               clock: damping bounds the actuation count
                               with growing guard intervals while the same
                               signal undamped thrashes every tick
noisy-neighbor-shed            an aggressor tenant floods a tenant-aware
                               gateway at ~10x the victim's rate: weighted
                               admission + per-tenant quotas shed the
                               AGGRESSOR (reason tenant_quota) while the
                               victim's p99 holds inside its gold budget —
                               proven from per-tenant journals alone
=============================  =============================================
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Dict, List, Optional

# check(name, ok, detail) — the invariant-recording callback the runner
# passes into every scenario body.
CheckFn = Callable[..., None]


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    spec: str                      # RAFIKI_CHAOS value for the run
    fn: Callable[..., None]        # fn(tmp: Path, check: CheckFn)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)


SCENARIOS: Dict[str, Scenario] = {}


def scenario(name: str, description: str, spec: str,
             env: Optional[Dict[str, str]] = None):
    def register(fn):
        SCENARIOS[name] = Scenario(name=name, description=description,
                                   spec=spec, fn=fn, env=dict(env or {}))
        return fn
    return register


# ---------------------------------------------------------------------------
# Shared fixtures
# ---------------------------------------------------------------------------

# A 3-epoch MLP whose only shape knob is fixed: every proposal shares a
# packing key (k trials vmap into one program) and ``seed`` defaults to
# 0, so a fresh model with a trial's knobs retrains bit-identically —
# the reference run the resume invariants compare against.
FF_SOURCE = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.ff import _Mlp

class ChaosFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "hidden_units": FixedKnob(16),
            "learning_rate": FloatKnob(1e-3, 3e-2, is_exp=True),
            "batch_size": FixedKnob(32),
            "epochs": FixedKnob(3),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1,
                    hidden_units=int(self.knobs["hidden_units"]),
                    num_classes=num_classes)
"""

TRAIN = "synthetic://images?classes=5&n=128&w=8&h=8&seed=0"
VAL = "synthetic://images?classes=5&n=64&w=8&h=8&seed=1"

JOB = "chaosjob"


def _train_env(tmp):
    from rafiki_tpu.store import MetaStore, ParamsStore

    store = MetaStore(tmp / "meta.sqlite3")
    params = ParamsStore(tmp / "params")
    model = store.create_model("chaosff", "IMAGE_CLASSIFICATION", None,
                               FF_SOURCE, "ChaosFF")
    return store, params, model


def _make_job(store, model, budget):
    job = store.create_train_job("chaosapp", "IMAGE_CLASSIFICATION", None,
                                 TRAIN, VAL, budget)
    store.create_sub_train_job(job["id"], model["id"])
    return job


def _check_rows(check, store, job_id, expect: int):
    """The lost/duplicated-rows invariant shared by the kill scenarios:
    exactly ``expect`` trial rows (the atomic budget claim survived the
    crash — no slot leaked, no trial double-created), all COMPLETED."""
    trials = store.get_trials_of_train_job(job_id)
    check("exact_trial_rows", len(trials) == expect,
          f"{len(trials)} rows for budget {expect}")
    bad = [t["id"] for t in trials if t["status"] != "COMPLETED"]
    check("all_trials_completed", not bad, f"not completed: {bad}")
    check("no_duplicate_rows",
          len({t["id"] for t in trials}) == len(trials), "duplicate ids")
    return trials


def _params_match_serial(check, params, trials, source=None, cls_name=None,
                         train_uri=None):
    """Bit-match invariant: each resumed trial's persisted params equal
    a fresh unfaulted serial train() with the same knobs (seed knob
    defaults identically), leaf for leaf."""
    import numpy as np

    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.utils.serial import load_pytree

    cls = load_model_class(source or FF_SOURCE, cls_name or "ChaosFF")
    train_uri = train_uri or TRAIN

    def leaves(blob: bytes):
        import pickle

        return load_pytree(pickle.loads(blob)["packed"])

    def flat(d, prefix=""):
        for k in sorted(d):
            v = d[k]
            if isinstance(v, dict):
                yield from flat(v, f"{prefix}{k}/")
            else:
                yield f"{prefix}{k}", v

    for t in trials:
        ref = cls(**t["knobs"])
        ref.train(train_uri)
        got = dict(flat(leaves(params.load(t["params_id"]))))
        want = dict(flat(leaves(ref.dump_parameters())))
        ref.destroy()
        same = (set(got) == set(want)
                and all(np.array_equal(got[k], want[k]) for k in want))
        check(f"params_match_serial:{t['id'][:8]}", same,
              "resumed params differ from unfaulted serial run")


def _no_corrupt_checkpoints(check, params, trials):
    """Completed trials must have their mid-trial checkpoints swept
    (they are superseded by final params), and every persisted params
    blob must load — a torn write would throw here."""
    leftovers = []
    for t in trials:
        if params.latest_checkpoint(t["id"]) is not None:
            leftovers.append(t["id"])
        params.load(t["params_id"])  # digest-verified read; raises if torn
    check("no_stale_checkpoints", not leftovers,
          f"checkpoints outlived completion: {leftovers}")


# ---------------------------------------------------------------------------
# Train-path scenarios (real subprocess workers)
# ---------------------------------------------------------------------------

@scenario(
    "kill-mid-trial-resume",
    "SIGKILL the worker after epoch 1 of a 3-epoch trial; the respawned "
    "worker must adopt and resume from the epoch-1 checkpoint, then "
    "finish the remaining budget — no lost or duplicated trial rows.",
    spec="seed=7;worker.epoch:kill:after=1:times=1:unless=-r",
    env={"RAFIKI_CHECKPOINT_EVERY": "1", "RAFIKI_WORKER_MAX_RESTARTS": "3",
         "RAFIKI_WORKER_RESTART_BACKOFF_S": "0.2"},
)
def kill_mid_trial_resume(tmp, check: CheckFn) -> None:
    from rafiki_tpu.scheduler import ProcessScheduler

    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 2})
    sched = ProcessScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=1,
                                 advisor_kind="random", platform="cpu")
    check("job_completed", result.status == "COMPLETED", result.errors)
    trials = _check_rows(check, store, job["id"], expect=2)
    # The kill really happened and recovery really ran: at least one
    # trial finished under the RESPAWNED worker (its id carries the
    # restart suffix the unless=-r filter keys off).
    resumed = [t for t in trials if "-r" in (t["worker_id"] or "")]
    check("trial_finished_by_respawned_worker", len(resumed) >= 1,
          f"worker ids: {[t['worker_id'] for t in trials]}")
    _no_corrupt_checkpoints(check, params, trials)


@scenario(
    "kill-mid-pack-resume",
    "The acceptance scenario: a k=4 packed run SIGKILLed mid-pack must "
    "resume ALL four trials from their per-epoch slice checkpoints; "
    "resumed final params bit-match an unfaulted serial run.",
    spec="seed=7;worker.epoch:kill:after=1:times=1:unless=-r",
    env={"RAFIKI_CHECKPOINT_EVERY": "1", "RAFIKI_TRIAL_PACK": "4",
         "RAFIKI_WORKER_MAX_RESTARTS": "3",
         "RAFIKI_WORKER_RESTART_BACKOFF_S": "0.2"},
)
def kill_mid_pack_resume(tmp, check: CheckFn) -> None:
    from rafiki_tpu.scheduler import ProcessScheduler

    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 4})
    sched = ProcessScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=1,
                                 advisor_kind="random", platform="cpu")
    check("job_completed", result.status == "COMPLETED", result.errors)
    trials = _check_rows(check, store, job["id"], expect=4)
    resumed = [t for t in trials if "-r" in (t["worker_id"] or "")]
    check("all_trials_resumed_by_respawned_worker", len(resumed) == 4,
          f"worker ids: {[t['worker_id'] for t in trials]}")
    _no_corrupt_checkpoints(check, params, trials)
    _params_match_serial(check, params, trials)


@scenario(
    "checkpoint-write-failure",
    "Every mid-trial checkpoint write fails (injected store error). "
    "A checkpoint is an optimization: the trial must still COMPLETE — "
    "only its resumability is lost — and the failure must be counted.",
    spec="seed=7;store.params_write:error:match=_ckpt_",
    env={"RAFIKI_CHECKPOINT_EVERY": "1"},
)
def checkpoint_write_failure(tmp, check: CheckFn) -> None:
    from rafiki_tpu import telemetry
    from rafiki_tpu.scheduler import LocalScheduler

    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 1})
    sched = LocalScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=1,
                                 advisor_kind="random")
    check("job_completed", result.status == "COMPLETED", result.errors)
    trials = _check_rows(check, store, job["id"], expect=1)
    check("write_failures_counted",
          telemetry.get_counter("worker.checkpoint_write_failed") >= 1.0,
          "no worker.checkpoint_write_failed increments")
    # Final params take the non-checkpoint path: unaffected, loadable.
    params.load(trials[0]["params_id"])


# ---------------------------------------------------------------------------
# Serving-path scenarios (in-proc bus + thread workers)
# ---------------------------------------------------------------------------

class _ConstModel:
    """Fixed prob-vector stand-in: the serving scenarios exercise the
    gather/drain machinery, not the model."""

    def __init__(self, vec):
        self.vec = list(vec)

    def predict(self, queries):
        return [self.vec for _ in queries]


class _ServingCluster:
    def __init__(self, n_workers: int, job: str = JOB):
        from rafiki_tpu.bus import InProcBus
        from rafiki_tpu.worker.inference import InferenceWorker

        self.bus = InProcBus()
        self.job = job
        self.stop = threading.Event()
        self.threads = []
        for i in range(n_workers):
            w = InferenceWorker(self.bus, job, f"w{i}",
                                _ConstModel([0.6, 0.4]),
                                stop_event=self.stop)
            th = threading.Thread(target=w.run, daemon=True,
                                  name=f"chaos-iw-w{i}")
            self.threads.append(th)
            th.start()
        deadline = time.monotonic() + 10
        while len(self.bus.get_workers(job)) < n_workers:
            if time.monotonic() >= deadline:
                raise RuntimeError("inference workers never registered")
            time.sleep(0.005)

    def close(self):
        self.stop.set()
        for th in self.threads:
            th.join(timeout=5)


@scenario(
    "straggler-quorum",
    "One of three serving replicas is stuck 3s per forward. Quorum "
    "gather (min_replies=2) must answer every request fast, with no "
    "timeout errors, hedging past the straggler.",
    spec="seed=7;inference.forward:delay:delay=3:match=w2",
)
def straggler_quorum(tmp, check: CheckFn) -> None:
    from rafiki_tpu import chaos
    from rafiki_tpu.gateway import Gateway, GatewayConfig
    from rafiki_tpu.predictor import Predictor

    cluster = _ServingCluster(3)
    try:
        predictor = Predictor(cluster.bus, JOB, timeout_s=8.0)
        gw = Gateway(predictor, GatewayConfig(min_replies=2,
                                              hedge_grace_s=0.1))
        t0 = time.monotonic()
        outs = gw.predict([[1.0], [2.0]])
        # lint: disable=RF007 — invariant bound on gather wall, not telemetry
        elapsed = time.monotonic() - t0
        check("all_queries_answered",
              len(outs) == 2 and all(
                  not (isinstance(o, dict) and "error" in o) for o in outs),
              f"outputs: {outs}")
        check("quorum_faster_than_straggler", elapsed < 2.5,
              f"gather took {elapsed:.2f}s against a 3s straggler")
        stats = gw.stats()
        check("no_gather_timeouts", stats["timeouts"] == 0, stats["timeouts"])
        check("straggler_hedged", stats["hedged"] >= 1, stats["hedged"])
        plane = chaos.active()
        fired = [] if plane is None else plane.schedule()
        check("straggler_fault_fired",
              any(site == "inference.forward" and "w2" in key
                  for site, _mode, _hit, key in fired),
              f"schedule: {fired}")
    finally:
        cluster.close()


@scenario(
    "drain-under-load",
    "Drain the gateway while background requests (with injected "
    "frontend latency) hold inflight slots: drain must flush them "
    "within its timeout and every post-drain request must shed.",
    spec="seed=7;gateway.predict:delay:delay=0.3:times=6",
)
def drain_under_load(tmp, check: CheckFn) -> None:
    from rafiki_tpu.gateway import Gateway, GatewayConfig, ShedError
    from rafiki_tpu.predictor import Predictor

    cluster = _ServingCluster(1)
    try:
        predictor = Predictor(cluster.bus, JOB, timeout_s=8.0)
        gw = Gateway(predictor, GatewayConfig(max_inflight=2, max_queue=8))
        outcomes: List[str] = []
        lock = threading.Lock()

        def fire():
            try:
                gw.predict([[1.0]])
                out = "ok"
            except ShedError as e:
                out = f"shed:{e.reason}"
            with lock:
                outcomes.append(out)

        threads = [threading.Thread(target=fire) for _ in range(6)]
        for th in threads:
            th.start()
        time.sleep(0.15)  # let the first wave hold inflight slots
        drained = gw.drain(timeout=10.0)
        for th in threads:
            th.join(timeout=15)
        check("drain_flushed_inflight", drained, "drain() timed out")
        check("inflight_zero_after_drain", gw.admission.inflight == 0,
              gw.admission.inflight)
        check("some_requests_served", outcomes.count("ok") >= 1, outcomes)
        check("no_request_lost", len(outcomes) == 6, outcomes)
        try:
            gw.predict([[1.0]])
            check("post_drain_request_shed", False, "predict succeeded")
        except ShedError as e:
            check("post_drain_request_shed", e.reason == "draining", e.reason)
    finally:
        cluster.close()


@scenario(
    "predictor-outage-surfaces",
    "Every bus heartbeat skipped. Inside the bounded stale grace the "
    "predictor still serves (counted fallback); past it the outage "
    "surfaces as RuntimeError, not per-query timeouts.",
    spec="seed=7;bus.heartbeat:skip",
)
def predictor_outage_surfaces(tmp, check: CheckFn) -> None:
    from rafiki_tpu import chaos, telemetry
    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.predictor import Predictor

    bus = InProcBus()
    for w in ("w0", "w1"):
        bus.add_worker(JOB, w)
    stop = threading.Event()

    def beat():
        while not stop.wait(0.05):
            for w in ("w0", "w1"):
                bus.heartbeat(JOB, w)  # chaos skips every one

    th = threading.Thread(target=beat, daemon=True)
    th.start()
    try:
        ttl = 0.4
        predictor = Predictor(bus, JOB, timeout_s=1.0, worker_ttl_s=ttl)
        # Phase 1 — a hiccup: leases ~1.5×TTL old, inside the 2×TTL
        # grace. The bounded fallback serves the full set and counts.
        time.sleep(1.5 * ttl)
        graced = predictor.live_workers()
        check("grace_window_serves", set(graced) == {"w0", "w1"}, graced)
        check("fallback_counted",
              telemetry.get_counter("predictor.stale_lease_fallback") >= 1.0,
              "no predictor.stale_lease_fallback increments")
        # Phase 2 — an outage: leases beyond the grace bound. Empty
        # fan-out set, and predict() raises instead of masquerading
        # the outage as slow answers.
        time.sleep(1.0 * ttl)
        check("outage_set_empty", predictor.live_workers() == [], "not empty")
        try:
            predictor.predict([[1.0]])
            check("outage_raises", False, "predict succeeded")
        except RuntimeError as e:
            check("outage_raises", "no live inference workers" in str(e), e)
        check("outage_counted",
              telemetry.get_counter("predictor.no_live_workers") >= 1.0,
              "no predictor.no_live_workers increments")
        plane = chaos.active()
        fired = [] if plane is None else plane.schedule()
        check("heartbeats_skipped",
              sum(1 for site, mode, _h, _k in fired
                  if site == "bus.heartbeat" and mode == "skip") >= 2,
              f"schedule: {fired}")
    finally:
        stop.set()
        th.join(timeout=2)


# ---------------------------------------------------------------------------
# Mesh-sweep / elastic-pack scenarios (docs/mesh_sweep.md)
# ---------------------------------------------------------------------------

# ChaosFF plus an early-stop rule keyed off learning_rate — a DYNAMIC
# knob, so a high-lr (early-stopping) member and a low-lr (full-budget)
# member still share one packing key / compiled program and can train
# in the same pack.
EVICT_SOURCE = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.ff import _Mlp

class EvictFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "hidden_units": FixedKnob(16),
            "learning_rate": FloatKnob(1e-3, 3e-2, is_exp=True),
            "batch_size": FixedKnob(32),
            "epochs": FixedKnob(3),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1,
                    hidden_units=int(self.knobs["hidden_units"]),
                    num_classes=num_classes)

    def should_stop_early(self, epoch, metrics):
        # A high-lr member "converges" after its first epoch: the
        # deterministic straggler-eviction trigger.
        return float(self.knobs["learning_rate"]) >= 0.02
"""


def _journal_has(recs, kind: str, name: str) -> bool:
    return any(r.get("kind") == kind and r.get("name") == name for r in recs)


@scenario(
    "mesh-chip-loss-repack",
    "Preempt chip 1 of a 2-chip mesh sweep mid-pack: the supervisor "
    "must re-pack its RUNNING trials onto the survivor, every trial "
    "completes with a score, resumed params bit-match unfaulted serial "
    "runs, and the loss/re-pack story reads back out of the journals.",
    spec="seed=11;scheduler.preempt:kill:after=2:times=1:match=chip1",
    env={"RAFIKI_CHECKPOINT_EVERY": "1"},
)
def mesh_chip_loss_repack(tmp, check: CheckFn) -> None:
    from rafiki_tpu import chaos, telemetry
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.obs.ledger import ledger
    from rafiki_tpu.scheduler import MeshSweepScheduler

    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 4})
    sched = MeshSweepScheduler(store, params)
    result = sched.run_sweep(job["id"], chips=2, trials_per_chip=2,
                             advisor_kind="random")
    check("job_completed", result.status == "COMPLETED", result.errors)
    trials = _check_rows(check, store, job["id"], expect=4)
    check("all_scores_recorded",
          all(t.get("score") is not None for t in trials),
          f"scores: {[t.get('score') for t in trials]}")
    check("chip_loss_counted",
          telemetry.get_counter("mesh.chips_lost") >= 1.0,
          "no mesh.chips_lost increments")
    # The kill really fired, against chip1 specifically.
    plane = chaos.active()
    fired = [] if plane is None else plane.schedule()
    check("preempt_fired",
          any(site == "scheduler.preempt" and key == "chip1"
              for site, _mode, _hit, key in fired),
          f"schedule: {fired}")
    # Re-pack work must land on the survivor: some trial finished under
    # a worker other than chip1's.
    workers = {t.get("worker_id") for t in trials}
    check("survivor_finished_trials",
          any(w and w.endswith("-mesh-c0") for w in workers),
          f"worker ids: {sorted(w or '' for w in workers)}")
    # Reconstructible from the journals alone (single-process sweep, so
    # the runner-side multi-pid checks don't apply — assert here).
    recs = journal_mod.read_dir(journal_mod.journal.log_dir)
    check("journal_records_chip_loss", _journal_has(recs, "mesh", "chip_lost"),
          "no mesh/chip_lost journal record")
    check("journal_records_repack", _journal_has(recs, "mesh", "repack"),
          "no mesh/repack journal record")
    # Recovery cost charged to the sweep's downtime bucket.
    ent = ledger.snapshot()["entities"].get(f"mesh:{job['id']}", {})
    check("downtime_charged", ent.get("downtime_s", 0.0) > 0.0, ent)
    _params_match_serial(check, params, trials)


# A Transformer family with every shape knob fixed: one knob config, so
# a fresh model with a trial's knobs retrains bit-identically — and the
# width-invariance of the sharded loop (shard/loop.py) makes that same
# serial run the reference for a GROUP trial at any width.
SHARD_SOURCE = b"""
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.transformer import Transformer

class ShardTf(Transformer):
    @staticmethod
    def get_knob_config():
        return {
            "embed_dim": FixedKnob(32),
            "num_heads": FixedKnob(2),
            "num_layers": FixedKnob(1),
            "learning_rate": FloatKnob(1e-3, 1e-2, is_exp=True),
            "batch_size": FixedKnob(32),
            "epochs": FixedKnob(3),
            "seed": FixedKnob(0),
        }
"""

SHARD_TRAIN = "synthetic://text?vocab=81&classes=5&n=256&len=16&seed=0"
SHARD_VAL = "synthetic://text?vocab=81&classes=5&n=64&len=16&seed=1"


@scenario(
    "chip-loss-mid-sharded-trial",
    "Preempt member 1 of a width-2 sharded group mid-trial: the group "
    "must abort at the epoch boundary (that epoch's shard-chunk "
    "manifest durable FIRST), re-form at width 1 on the survivor, "
    "resume via reshard-on-restore, complete with a score, and the "
    "final params must bit-match an unfaulted serial run.",
    spec="seed=11;scheduler.preempt:kill:after=2:times=1:match=chip1",
    env={"RAFIKI_CHECKPOINT_EVERY": "1", "RAFIKI_SHARD_WIDTH": "2"},
)
def chip_loss_mid_sharded_trial(tmp, check: CheckFn) -> None:
    from rafiki_tpu import chaos, telemetry
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.scheduler import MeshSweepScheduler
    from rafiki_tpu.store import MetaStore, ParamsStore

    store = MetaStore(tmp / "meta.sqlite3")
    params = ParamsStore(tmp / "params")
    model = store.create_model("shardtf", "TEXT_CLASSIFICATION", None,
                               SHARD_SOURCE, "ShardTf")
    job = store.create_train_job("shardapp", "TEXT_CLASSIFICATION", None,
                                 SHARD_TRAIN, SHARD_VAL,
                                 {"MODEL_TRIAL_COUNT": 1})
    store.create_sub_train_job(job["id"], model["id"])
    sched = MeshSweepScheduler(store, params)
    result = sched.run_sweep(job["id"], chips=2, trials_per_chip=1,
                             advisor_kind="random")
    check("job_completed", result.status == "COMPLETED", result.errors)
    trials = _check_rows(check, store, job["id"], expect=1)
    check("score_recorded", trials[0].get("score") is not None, trials[0])
    check("group_worker_finished",
          (trials[0].get("worker_id") or "").endswith("-shard-g0"),
          f"worker id: {trials[0].get('worker_id')}")
    # The kill really fired, against a group member specifically —
    # reject a vacuous pass where the fault never landed.
    plane = chaos.active()
    fired = [] if plane is None else plane.schedule()
    check("preempt_fired",
          any(site == "scheduler.preempt" and key == "chip1"
              for site, _mode, _hit, key in fired),
          f"schedule: {fired}")
    check("chip_loss_counted",
          telemetry.get_counter("mesh.chips_lost") >= 1.0,
          "no mesh.chips_lost increments")
    # Recovery restored a durable manifest onto the narrower mesh.
    check("reshard_restore_counted",
          telemetry.get_counter("shard.reshard_restores") >= 1.0,
          "no shard.reshard_restores increments")
    # The width history reconstructs from the journal stream alone:
    # formed at 2, member lost, re-formed at 1, resharded 2 -> 1.
    recs = journal_mod.read_dir(journal_mod.journal.log_dir)
    shard = [r for r in recs if r.get("kind") == "shard"]
    widths = [r.get("width") for r in shard if r.get("name") == "group_formed"]
    check("group_formed_then_reformed", widths == [2, 1],
          f"group_formed widths: {widths}")
    check("journal_records_member_loss",
          _journal_has(recs, "shard", "member_lost"),
          "no shard/member_lost journal record")
    reshards = [(r.get("from_width"), r.get("to_width"))
                for r in shard if r.get("name") == "reshard"]
    check("journal_records_reshard", (2, 1) in reshards,
          f"reshard records: {reshards}")
    _params_match_serial(check, params, trials, source=SHARD_SOURCE,
                         cls_name="ShardTf", train_uri=SHARD_TRAIN)


@scenario(
    "pack-straggler-evict",
    "One member of a k=2 pack early-stops at epoch 0 while its mate "
    "trains the full budget: the straggler must be EVICTED from the "
    "stacked state mid-pack, its slot backfilled with a freshly "
    "proposed trial, all three trials complete, and the evictee "
    "bit-matches a serial early-stopped run.",
    spec="seed=11;worker.epoch:delay:delay=0.05:times=1",
)
def pack_straggler_evict(tmp, check: CheckFn) -> None:
    from rafiki_tpu import telemetry
    from rafiki_tpu.advisor import AdvisorService
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.model.knobs import knob_config_signature
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import (InProcAdvisorHandle,
                                         PackedTrialRunner, TrainWorker)

    store = MetaStore(tmp / "meta.sqlite3")
    params = ParamsStore(tmp / "params")
    model = store.create_model("evictff", "IMAGE_CLASSIFICATION", None,
                               EVICT_SOURCE, "EvictFF")
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 3})
    sub = store.get_sub_train_jobs(job["id"])[0]
    cls = load_model_class(EVICT_SOURCE, "EvictFF")
    advisors = AdvisorService()
    advisor_id = advisors.create_advisor(cls.get_knob_config(), kind="random")
    worker = TrainWorker(
        store, params, sub["id"], cls,
        InProcAdvisorHandle(advisors, advisor_id), TRAIN, VAL,
        {"MODEL_TRIAL_COUNT": 3}, worker_id="evict-w0", async_persist=False)
    knob_config = cls.get_knob_config()
    base = {"hidden_units": 16, "batch_size": 32, "epochs": 3}
    rows = []
    # lr >= 0.02 trips EvictFF.should_stop_early at epoch 0 — a
    # straggler next to a full-budget mate.
    for kn in (dict(base, learning_rate=0.025),
               dict(base, learning_rate=0.005)):
        trial = store.create_trial(sub["id"], "EvictFF", kn,
                                   shape_sig=knob_config_signature(
                                       knob_config, kn),
                                   budget_max=3)
        rows.append((trial["id"], kn))
    n = PackedTrialRunner(worker, 2).run_assigned(rows, budget_max=3)
    # 2 assigned + 1 backfilled into the evicted straggler's slot.
    check("all_rows_carried", n == 3, f"carried {n}, want 3")
    trials = _check_rows(check, store, job["id"], expect=3)
    check("straggler_evicted",
          telemetry.get_counter("trial_pack.evictions") >= 1.0,
          "no trial_pack.evictions increments")
    check("slot_backfilled",
          telemetry.get_counter("trial_pack.backfills") >= 1.0,
          "no trial_pack.backfills increments")
    check("all_scores_recorded",
          all(t.get("score") is not None for t in trials),
          f"scores: {[t.get('score') for t in trials]}")
    _params_match_serial(check, params, trials,
                         source=EVICT_SOURCE, cls_name="EvictFF")


@scenario(
    "nan-trial-contained",
    "Chaos NaN-poisons one gradient step of pack member 2 (k=4). The "
    "health plane must trip at the epoch boundary, bank a replay "
    "capsule that re-executes bit-exactly, evict ONLY the sick member "
    "(ERRORED with a diagnosis, floor score fed back), and carry the "
    "three survivors to completion with params bit-matching unfaulted "
    "serial runs.",
    spec="seed=19;train.nan:nan:times=1:match=@m2",
)
def nan_trial_contained(tmp, check: CheckFn) -> None:
    from rafiki_tpu import telemetry
    from rafiki_tpu.advisor import AdvisorService
    from rafiki_tpu.chaos import plane as plane_mod
    from rafiki_tpu.model.base import load_model_class
    from rafiki_tpu.model.knobs import knob_config_signature
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.store import MetaStore, ParamsStore
    from rafiki_tpu.worker.train import (InProcAdvisorHandle,
                                         PackedTrialRunner, TrainWorker)

    store = MetaStore(tmp / "meta.sqlite3")
    params = ParamsStore(tmp / "params")
    model = store.create_model("nanff", "IMAGE_CLASSIFICATION", None,
                               FF_SOURCE, "ChaosFF")
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 4})
    sub = store.get_sub_train_jobs(job["id"])[0]
    cls = load_model_class(FF_SOURCE, "ChaosFF")
    advisors = AdvisorService()
    advisor_id = advisors.create_advisor(cls.get_knob_config(), kind="random")
    worker = TrainWorker(
        store, params, sub["id"], cls,
        InProcAdvisorHandle(advisors, advisor_id), TRAIN, VAL,
        {"MODEL_TRIAL_COUNT": 4}, worker_id="nan-w0", async_persist=False)
    knob_config = cls.get_knob_config()
    base = {"hidden_units": 16, "batch_size": 32, "epochs": 3}
    rows = []
    # budget_max=4 doubles as the backfill gate: the evicted slot must
    # NOT be refilled (the budget is already fully claimed), keeping
    # member indices stable for the @m2 match below.
    for lr in (0.001, 0.002, 0.004, 0.008):
        kn = dict(base, learning_rate=lr)
        trial = store.create_trial(sub["id"], "ChaosFF", kn,
                                   shape_sig=knob_config_signature(
                                       knob_config, kn),
                                   budget_max=4)
        rows.append((trial["id"], kn))
    n = PackedTrialRunner(worker, 4).run_assigned(rows, budget_max=4)
    check("all_rows_carried", n == 4, f"carried {n}, want 4")

    # Vacuous-pass rejection: the fault must actually have fired at the
    # train.nan site for member 2 — a scenario that "passes" because
    # the poison never landed proves nothing.
    fired = [(site, mode, key)
             for site, mode, _hit, key in plane_mod.active().schedule()
             if site == "train.nan"]
    check("nan_fault_fired", len(fired) == 1 and "@m2" in fired[0][2],
          f"train.nan firings: {fired}")

    trials = store.get_trials_of_train_job(job["id"])
    check("exact_trial_rows", len(trials) == 4,
          f"{len(trials)} rows for budget 4 (backfill must not refill "
          "a diverged slot under a drained budget)")
    errored = [t for t in trials if t["status"] == "ERRORED"]
    completed = [t for t in trials if t["status"] == "COMPLETED"]
    check("one_member_errored", len(errored) == 1,
          f"statuses: {[t['status'] for t in trials]}")
    check("three_survivors_completed", len(completed) == 3,
          f"statuses: {[t['status'] for t in trials]}")
    check("diagnosis_surfaced",
          bool(errored) and "diverged" in (errored[0].get("error") or ""),
          f"error: {errored[0].get('error') if errored else None}")
    check("survivors_scored",
          all(t.get("score") is not None for t in completed),
          f"scores: {[t.get('score') for t in completed]}")
    check("divergence_counted",
          telemetry.get_counter("health.divergences") >= 1.0,
          "no health.divergences increments")
    check("containment_counted",
          telemetry.get_counter("health.contained") >= 1.0,
          "no health.contained increments")
    check("eviction_counted",
          telemetry.get_counter("health.evictions") >= 1.0,
          "no health.evictions increments")

    recs = journal_mod.read_dir(journal_mod.journal.log_dir)
    check("journal_records_divergence",
          _journal_has(recs, "health", "divergence"),
          "no health/divergence journal record")
    check("journal_records_capsule",
          _journal_has(recs, "health", "capsule"),
          "no health/capsule journal record")

    # The capsule is a faithful repro: re-execute the truncated epoch
    # and require every compared sentinel value bit-identical.
    caps = sorted((journal_mod.journal.log_dir or tmp).glob("capsule-*.rcap"))
    check("capsule_banked", len(caps) >= 1, "no capsule-*.rcap on disk")
    if caps:
        from rafiki_tpu.obs.health import capsule as capsule_mod

        verdict = capsule_mod.replay(caps[-1])
        check("capsule_replay_bit_exact", verdict["reproduced"],
              f"mismatches: {verdict['mismatches']}")
        check("capsule_replay_poisoned", verdict["poisoned"],
              "replayed capsule carried no poison column")

    _params_match_serial(check, params, completed)


@scenario(
    "collective-kill-mid-step",
    "SIGKILL a dp-mesh worker inside the collective step path (the "
    "collective.step site fires each epoch a mesh plan is live). The "
    "respawned worker must adopt, resume from the epoch checkpoint and "
    "finish the budget. No bit-match here: dp gradient reduction order "
    "differs from serial by design.",
    spec="seed=13;collective.step:kill:after=1:times=1:unless=-r",
    env={"RAFIKI_CHECKPOINT_EVERY": "1", "RAFIKI_WORKER_MAX_RESTARTS": "3",
         "RAFIKI_WORKER_RESTART_BACKOFF_S": "0.2"},
)
def collective_kill_mid_step(tmp, check: CheckFn) -> None:
    from rafiki_tpu.scheduler import ProcessScheduler

    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 2})
    sched = ProcessScheduler(store, params)
    result = sched.run_train_job(job["id"], n_workers=1, devices_per_trial=2,
                                 advisor_kind="random", platform="cpu")
    check("job_completed", result.status == "COMPLETED", result.errors)
    trials = _check_rows(check, store, job["id"], expect=2)
    resumed = [t for t in trials if "-r" in (t["worker_id"] or "")]
    check("trial_finished_by_respawned_worker", len(resumed) >= 1,
          f"worker ids: {[t['worker_id'] for t in trials]}")
    _no_corrupt_checkpoints(check, params, trials)


@scenario(
    "mesh-degrades-single-chip",
    "Every mesh-formation attempt fails (injected collective.init "
    "errors past the retry budget): the sweep must DEGRADE to "
    "single-chip mode inside its grace window — same trials, one chip "
    "— and still complete, with the downgrade journaled.",
    spec="seed=17;collective.init:error:times=8",
    env={"RAFIKI_MESH_INIT_RETRIES": "2", "RAFIKI_MESH_INIT_BACKOFF_S": "0.01",
         "RAFIKI_MESH_FORM_GRACE_S": "5"},
)
def mesh_degrades_single_chip(tmp, check: CheckFn) -> None:
    from rafiki_tpu import telemetry
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.scheduler import MeshSweepScheduler

    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": 2})
    sched = MeshSweepScheduler(store, params)
    result = sched.run_sweep(job["id"], chips=2, trials_per_chip=2,
                             advisor_kind="random")
    check("job_completed", result.status == "COMPLETED", result.errors)
    trials = _check_rows(check, store, job["id"], expect=2)
    check("degradation_counted",
          telemetry.get_counter("mesh.degraded_single_chip") >= 1.0,
          "no mesh.degraded_single_chip increments")
    check("init_retries_counted",
          telemetry.get_counter("mesh.init_retries") >= 2.0,
          "no mesh.init_retries increments")
    workers = {t.get("worker_id") for t in trials}
    check("single_chip_ran_everything",
          all(w and w.endswith("-mesh-c0") for w in workers),
          f"worker ids: {sorted(w or '' for w in workers)}")
    recs = journal_mod.read_dir(journal_mod.journal.log_dir)
    check("journal_records_degradation",
          _journal_has(recs, "mesh", "degraded"),
          "no mesh/degraded journal record")
    _params_match_serial(check, params, trials)


# ---------------------------------------------------------------------------
# Stacked-route loss scenario (mp bus + spawned stacked worker)
# ---------------------------------------------------------------------------


def _stacked_stub_main(bus, job: str, worker_id: str) -> None:
    """Spawn target: the stacked worker as its OWN process — the
    deployment shape of the stacked serving route, where one process
    holds a job's whole top-k ensemble (docs/serving.md). RAFIKI_CHAOS
    rides the spawn env, so the inference.forward kill fires HERE, in
    the child, exactly like a real stacked-worker loss."""
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    from rafiki_tpu import obs

    obs.configure_from_env(role="infer")
    from rafiki_tpu.worker.inference import InferenceWorker

    InferenceWorker(bus, job, worker_id, _ConstModel([0.6, 0.4])).run()


@scenario(
    "stacked-worker-loss-fallback",
    "SIGKILL the stacked worker that serves a job's WHOLE top-k "
    "ensemble mid-load: the fallback supervisor must degrade the job "
    "to replicated per-trial workers, the gateway's blackout re-route "
    "must carry every admitted request to an answer (zero dropped), "
    "and the loss->fallback story must reconstruct from the journals.",
    spec="seed=7;inference.forward:kill:after=1:times=1:match=stacked",
)
def stacked_worker_loss_fallback(tmp, check: CheckFn) -> None:
    import multiprocessing as mp
    import os

    from rafiki_tpu import telemetry
    from rafiki_tpu.bus.queues import make_mp_bus
    from rafiki_tpu.gateway import Gateway, GatewayConfig
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.predictor import Predictor
    from rafiki_tpu.worker.fallback import FallbackSupervisor
    from rafiki_tpu.worker.inference import InferenceWorker

    ttl = 1.0
    ctx = mp.get_context("spawn")
    manager = ctx.Manager()
    stop = threading.Event()
    fallback_threads: List[threading.Thread] = []
    proc = None
    sup = None
    try:
        bus = make_mp_bus(manager)
        proc = ctx.Process(target=_stacked_stub_main,
                           args=(bus, JOB, "stacked-w0"), daemon=True)
        proc.start()
        deadline = time.monotonic() + 30
        while "stacked-w0" not in bus.get_workers(JOB):
            if time.monotonic() >= deadline:
                raise RuntimeError("stacked worker never registered")
            time.sleep(0.02)

        def spawn_fallback():
            # The replicated degrade: one thread worker per "trial"
            # (const-model stand-ins — this scenario pins the loss
            # control flow, not the model math).
            for i in range(2):
                w = InferenceWorker(bus, JOB, f"fb{i}",
                                    _ConstModel([0.6, 0.4]),
                                    stop_event=stop)
                th = threading.Thread(target=w.run, daemon=True,
                                      name=f"chaos-fb{i}")
                fallback_threads.append(th)
                th.start()

        sup = FallbackSupervisor(bus, JOB, "stacked-w0", spawn_fallback,
                                 ttl_s=ttl, poll_s=0.1).start()
        predictor = Predictor(bus, JOB, timeout_s=10.0, worker_ttl_s=ttl)
        gw = Gateway(predictor, GatewayConfig(min_replies=1,
                                              blackout_retries=4))
        # Request 1 is the fault's after=1 skip: the stacked worker
        # serves it, seeding the latency EWMA the blackout probes key
        # off. Request 2's forward IS the kill — its envelope dies with
        # the worker and only the blackout re-route can save it.
        outcomes = []
        for i in range(5):
            try:
                outs = gw.predict([[float(i)]], deadline_s=10.0)
                ok = bool(outs) and not any(
                    isinstance(o, dict) and "error" in o for o in outs)
            except Exception:
                ok = False
            outcomes.append(ok)
        check("no_request_dropped", all(outcomes), f"outcomes: {outcomes}")
        check("stacked_worker_sigkilled",
              not proc.is_alive() and proc.exitcode == -9,
              f"alive={proc.is_alive()} exitcode={proc.exitcode}")
        check("fallback_supervisor_fired", sup.fired.is_set(),
              "supervisor never saw the lease die")
        check("blackout_reroute_engaged",
              telemetry.get_counter("gateway.blackout_retries") >= 1.0,
              "no gateway.blackout_retries increments")
        recs = journal_mod.read_dir(journal_mod.journal.log_dir)
        check("journal_records_fallback",
              _journal_has(recs, "serving", "fallback"),
              "no serving/fallback journal record")
        check("journal_records_blackout_retry",
              _journal_has(recs, "gateway", "blackout_retry"),
              "no gateway/blackout_retry journal record")
        # The kill really fired, and in the CHILD: its chaos/injected
        # record carries the child pid, which with the parent's records
        # makes the journals a >=2-pid reconstruction of the loss.
        injected = [r for r in recs if r.get("kind") == "chaos"
                    and r.get("name") == "injected"
                    and r.get("site") == "inference.forward"]
        check("kill_journaled_from_child",
              any(r.get("pid") != os.getpid() for r in injected),
              f"injected records: {injected}")
    finally:
        if sup is not None:
            sup.stop()
        stop.set()
        for th in fallback_threads:
            th.join(timeout=5)
        if proc is not None and proc.is_alive():
            proc.terminate()
            proc.join(timeout=5)
        manager.shutdown()


@scenario(
    "load-spike-scale-up",
    "The closed elasticity loop end to end: the only serving replica "
    "is pinned slow, the burn engine breaches the serving p99 SLO, "
    "the autoscale controller scales the inference lane up, and the "
    "spike recovers — with recovery-time-to-SLO recorded for the "
    "bench trend gate.",
    spec="seed=11;inference.forward:delay:delay=0.3:match=w0",
)
def load_spike_scale_up(tmp, check: CheckFn) -> None:
    from rafiki_tpu import chaos, telemetry
    from rafiki_tpu.autoscale.actuators import InferenceWorkerLane
    from rafiki_tpu.autoscale.controller import (AutoscaleController,
                                                 LaneSpec, read_sensors)
    from rafiki_tpu.bus import InProcBus
    from rafiki_tpu.gateway import Gateway, GatewayConfig
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.obs.perf.slo import SloEngine, SloSpec
    from rafiki_tpu.predictor import Predictor
    from rafiki_tpu.worker.inference import InferenceWorker

    bus = InProcBus()
    stops: List[threading.Event] = []
    threads: List[threading.Thread] = []

    def spawn(wid):
        stop = threading.Event()
        w = InferenceWorker(bus, JOB, wid, _ConstModel([0.6, 0.4]),
                            stop_event=stop)
        th = threading.Thread(target=w.run, daemon=True,
                              name=f"chaos-as-{wid}")
        stops.append(stop)
        threads.append(th)
        th.start()
        return w, th

    # One replica, and the fault spec pins exactly it (match=w0): every
    # forward pays 0.3s, so serving p99 sits ~2x over the 150ms SLO.
    w0, th0 = spawn("w0")
    deadline = time.monotonic() + 10
    while "w0" not in bus.get_workers(JOB):
        if time.monotonic() >= deadline:
            raise RuntimeError("w0 never registered")
        time.sleep(0.005)
    predictor = Predictor(bus, JOB, timeout_s=8.0)
    gw = Gateway(predictor, GatewayConfig(min_replies=1, max_queue=32,
                                          max_inflight=8))
    # Private burn engine on the rollup's p99 GAUGE: a level source
    # recovers when the signal falls, unlike the cumulative hist_p99
    # reservoirs. The tight window makes breach AND recovery resolve
    # inside the scenario's few seconds of wall.
    engine = SloEngine([SloSpec("serving_p99_spike", "gauge:serving.p99_ms",
                                150.0, windows=(0.8,))], tick_s=0.0)
    lane = InferenceWorkerLane(
        bus, JOB,
        spawn_fn=lambda i: (f"as{i}",) + spawn(f"as{i}"),
        initial=[("w0", w0, th0)])
    ctl = AutoscaleController(
        lanes=[LaneSpec("inference", min_size=1, max_size=2,
                        up_threshold=1.0, down_threshold=0.0,
                        up_cooldown_s=1.0, down_cooldown_s=60.0)],
        sensor_fn=lambda: read_sensors(gateway=gw, slo_engine=engine),
        actuators={"inference": lane},
        seed=11, tick_s=0.2, tick_global_slo=False)
    breach_at = None
    scaled_at = None
    recovered_at = None
    try:
        t_end = time.monotonic() + 12.0
        while time.monotonic() < t_end:
            gw.predict([[1.0]])
            # Force-close the rollup bucket so every loop lap refreshes
            # the gauge the burn engine samples.
            gw.rollup.flush()
            now = time.monotonic()
            state = engine.tick(now)
            breaching = state["serving_p99_spike"]["breaching"]
            if breaching and breach_at is None:
                breach_at = now
            decisions = ctl.tick(now)
            if scaled_at is None and any(d.actuated and d.direction == "up"
                                         for d in decisions):
                scaled_at = now
            if (breach_at is not None and scaled_at is not None
                    and not breaching):
                recovered_at = now
                break
    finally:
        for stop in stops:
            stop.set()
        for th in threads:
            th.join(timeout=5)
    check("slo_breached", breach_at is not None,
          "serving p99 never breached against a 0.3s-pinned replica")
    check("scaled_up", scaled_at is not None and lane.size() == 2,
          f"lane size {lane.size()}, scaled_at={scaled_at}")
    check("slo_recovered", recovered_at is not None,
          "burn never cleared after scale-up")
    if breach_at is not None and recovered_at is not None:
        recovery_s = recovered_at - breach_at
        # The smoke reads this gauge right after run_scenario (the
        # runner resets telemetry BEFORE the body, not after) and
        # trends it through SCALE_r*.json.
        telemetry.set_gauge("autoscale.recovery_s", round(recovery_s, 3))
        check("recovery_within_budget", recovery_s < 8.0,
              f"recovery took {recovery_s:.2f}s")
    check("bounded_actuations", ctl.actuation_count("inference") <= 2,
          f"{ctl.actuation_count('inference')} actuations for one spike")
    recs = journal_mod.read_dir(journal_mod.journal.log_dir)
    check("decisions_journaled",
          any(r.get("kind") == "autoscale" and r.get("name") == "decision"
              and r.get("actuated") for r in recs),
          "no actuated autoscale/decision record")
    plane = chaos.active()
    fired = [] if plane is None else plane.schedule()
    check("spike_fault_fired",
          any(site == "inference.forward" and "w0" in key
              for site, _mode, _hit, key in fired),
          f"schedule: {fired}")


@scenario(
    "autoscale-flap-damping",
    "An adversarially oscillating pressure signal — plus injected "
    "sensor-plane faults — drives two controllers on a fake clock: "
    "with damping the actuation count stays bounded and guard "
    "intervals grow; the identical signal with damping disabled "
    "thrashes nearly every tick. The contrast is the proof.",
    spec="seed=13;autoscale.sensor:error:p=0.2",
)
def autoscale_flap_damping(tmp, check: CheckFn) -> None:
    from rafiki_tpu import chaos, telemetry
    from rafiki_tpu.autoscale.controller import AutoscaleController, LaneSpec

    class _StubLane:
        def __init__(self):
            self.n = 2
            self.calls = 0

        def size(self):
            return self.n

        def scale_to(self, n):
            self.n = n
            self.calls += 1

    TICKS = 120
    TICK_SPACING = 2.0

    def run(damping: bool):
        clock = {"t": 0.0}
        phase = {"i": 0}

        def sensors():
            # Worst-case square wave: full burn one tick, dead idle the
            # next. An undamped controller chases it forever.
            phase["i"] += 1
            high = phase["i"] % 2 == 1
            return {"slo_breaching": ["flap"] if high else [],
                    "slo_burn": 2.0 if high else 0.0,
                    "queue_frac": 0.0, "shed_rate": 0.0}

        lane = _StubLane()
        ctl = AutoscaleController(
            lanes=[LaneSpec("inference", min_size=1, max_size=8,
                            up_threshold=1.0, down_threshold=0.3,
                            up_cooldown_s=1.0, down_cooldown_s=1.0)],
            sensor_fn=sensors,
            actuators={"inference": lane},
            clock=lambda: clock["t"],
            seed=13, tick_s=TICK_SPACING, damping=damping,
            flap_window_s=600.0, flap_flips=2, flap_backoff=2.0,
            flap_guard_s=2.0, flap_guard_cap_s=64.0,
            tick_global_slo=False)
        act_ts: List[float] = []
        for _ in range(TICKS):
            decisions = ctl.tick()
            if any(d.actuated for d in decisions):
                act_ts.append(clock["t"])
            clock["t"] += TICK_SPACING
        return ctl, lane, act_ts

    damped_ctl, damped_lane, damped_ts = run(damping=True)
    undamped_ctl, undamped_lane, undamped_ts = run(damping=False)
    # Polarity 1: the undamped controller really thrashes — near one
    # actuation per non-faulted tick (this is what damping prevents;
    # without it the scenario would pass vacuously).
    check("undamped_flaps", undamped_lane.calls >= TICKS // 2,
          f"undamped actuated only {undamped_lane.calls}/{TICKS} ticks")
    # Polarity 2: same signal, damping on -> bounded actuation count.
    check("damped_bounded", damped_lane.calls <= TICKS // 4,
          f"damped actuated {damped_lane.calls}/{TICKS} ticks")
    check("damping_contrast",
          damped_lane.calls * 3 <= undamped_lane.calls,
          f"damped {damped_lane.calls} vs undamped {undamped_lane.calls}")
    # The exponential guard shows up as growing gaps between damped
    # actuations: the last gap must dwarf the first.
    gaps = [b - a for a, b in zip(damped_ts, damped_ts[1:])]
    check("guard_intervals_grow",
          bool(gaps) and max(gaps) >= 4 * min(gaps),
          f"damped actuation gaps: {gaps}")
    check("damped_holds_recorded",
          telemetry.get_counter("autoscale.damped_holds") >= 1.0,
          "no damped hold ever recorded")
    # The injected sensor faults landed, and every faulted tick held:
    # a controller must never actuate blind.
    check("sensor_faults_held",
          telemetry.get_counter("autoscale.sensor_errors") >= 1.0,
          "sensor-error chaos never fired")
    plane = chaos.active()
    fired = [] if plane is None else plane.schedule()
    check("sensor_fault_fired",
          any(site == "autoscale.sensor" for site, _mode, _hit, key in fired),
          f"schedule: {fired}")


# ---------------------------------------------------------------------------
# Control-plane crash scenarios (docs/recovery.md): the sweep runs in
# a subprocess of its own (scheduler/sweep_proc.py) so a supervisor
# kill takes out the WHOLE control plane — advisor state, pack
# assignments, heartbeats — and resume_sweep must prove a genuinely
# fresh process adopts the job from the MetaStore + sweep WAL +
# journals alone.
# ---------------------------------------------------------------------------

def _sweep_proc_env(extra: Optional[Dict[str, str]] = None,
                    chaos: bool = True) -> Dict[str, str]:
    """Child env for a sweep_proc subprocess: inherits the runner's
    installed chaos/journal env, pins the repo importable regardless of
    cwd, and (chaos=False) strips the fault spec for resume/reference
    children that must run unfaulted."""
    import os
    from pathlib import Path

    import rafiki_tpu

    env = dict(os.environ)
    env["PYTHONPATH"] = os.pathsep.join(
        p for p in (str(Path(rafiki_tpu.__file__).resolve().parents[1]),
                    env.get("PYTHONPATH", "")) if p)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if not chaos:
        env.pop("RAFIKI_CHAOS", None)
    return env


def _sweep_proc(mode: str, store, params, job_id: str, *, chips: int,
                trials_per_chip: int, env: Dict[str, str],
                advisor: Optional[str] = None,
                advisor_kwargs: Optional[str] = None,
                stale_after_s: Optional[float] = None,
                timeout: float = 240.0):
    import json as _json
    import subprocess
    import sys

    argv = [sys.executable, "-m", "rafiki_tpu.scheduler.sweep_proc", mode,
            "--db", str(store.path), "--params", str(params.directory),
            "--job", job_id, "--chips", str(chips),
            "--trials-per-chip", str(trials_per_chip)]
    if advisor:
        argv += ["--advisor", advisor]
    if advisor_kwargs:
        argv += ["--advisor-kwargs", advisor_kwargs]
    if stale_after_s is not None:
        argv += ["--stale-after-s", str(stale_after_s)]
    proc = subprocess.run(argv, env=env, capture_output=True, text=True,
                          timeout=timeout)
    summary = {}
    if proc.stdout.strip():
        try:
            summary = _json.loads(proc.stdout.strip().splitlines()[-1])
        except ValueError:
            summary = {}
    return proc, summary


@scenario(
    "supervisor-kill-mid-sweep",
    "SIGKILL the whole sweep-supervisor process mid-sweep (after its "
    "warmup claims, before any trial completes): resume_sweep in a "
    "fresh process must reconcile the WAL with zero double-claimed "
    "slots, rehydrate the GP advisor, adopt every orphan, and finish "
    "the job with the SAME best score and knob set as an unfaulted "
    "run under the same seeds — with a non-warmup post-resume "
    "propose_batch proving the advisor continued, not restarted.",
    spec="seed=23;supervisor.tick:kill:after=30:times=1:match=g0",
    env={"RAFIKI_CHECKPOINT_EVERY": "1",
         "RAFIKI_SUPERVISOR_HEARTBEAT_S": "0.2"},
)
def supervisor_kill_mid_sweep(tmp, check: CheckFn) -> None:
    import json as _json
    import subprocess
    import sys
    import time as _time

    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.scheduler.wal import read_wal, reconcile, wal_path

    # Budget == chips * trials_per_chip == GP n_initial: every claim is
    # a seed-deterministic warmup proposal made up-front, so ONE plain
    # unfaulted run is a complete best-score reference and the faulted
    # run's kill (supervisor.tick only exists post-claims) cannot
    # change which knobs were claimed.
    BUDGET, CHIPS, K = 4, 2, 2
    fd = tmp / "faulted"
    fd.mkdir(parents=True, exist_ok=True)
    store, params, model = _train_env(fd)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": BUDGET})

    p1, _ = _sweep_proc("run", store, params, job["id"], chips=CHIPS,
                        trials_per_chip=K, env=_sweep_proc_env(),
                        advisor="gp", advisor_kwargs='{"n_initial": 4}')
    check("supervisor_killed", p1.returncode == -9,
          f"run rc={p1.returncode}: {p1.stderr[-500:]}")

    _time.sleep(0.5)
    p2, summary = _sweep_proc("resume", store, params, job["id"],
                              chips=CHIPS, trials_per_chip=K,
                              env=_sweep_proc_env(chaos=False),
                              stale_after_s=0.4)
    check("resume_completed", p2.returncode == 0,
          f"resume rc={p2.returncode}: {p2.stderr[-800:]}")
    check("resume_adopted_orphans", summary.get("adopted", 0) >= 1, summary)
    check("resume_mode_wal", summary.get("mode") == "wal", summary)
    trials = _check_rows(check, store, job["id"], expect=BUDGET)

    # Acceptance (b): WAL-vs-store reconcile proves zero slots claimed
    # twice — every trial row covered by exactly one claim record.
    recs = read_wal(wal_path(store.path, job["id"]))
    for sub in store.get_sub_train_jobs(job["id"]):
        r = reconcile(recs, store.get_trials_of_sub_train_job(sub["id"]),
                      sub=sub, sub_id=sub["id"])
        check("wal_reconciles_clean", r.ok, r.summary())
        check("no_double_claims",
              all(n == 1 for n in r.claims.values()), r.summary())

    # Acceptance (a): unfaulted reference run, same seeds, own journal
    # dir so the faulted job's timeline stays uncontaminated.
    rd = tmp / "reference"
    rd.mkdir(parents=True, exist_ok=True)
    rstore, rparams, rmodel = _train_env(rd)
    rjob = _make_job(rstore, rmodel, {"MODEL_TRIAL_COUNT": BUDGET})
    renv = _sweep_proc_env(chaos=False)
    renv["RAFIKI_LOG_DIR"] = str(rd / "obs")
    p3, _ = _sweep_proc("run", rstore, rparams, rjob["id"], chips=CHIPS,
                        trials_per_chip=K, env=renv, advisor="gp",
                        advisor_kwargs='{"n_initial": 4}')
    check("reference_completed", p3.returncode == 0,
          f"reference rc={p3.returncode}: {p3.stderr[-500:]}")
    rtrials = rstore.get_trials_of_train_job(rjob["id"])
    best_f = max((t["score"] for t in trials
                  if t["score"] is not None), default=None)
    best_r = max((t["score"] for t in rtrials
                  if t["score"] is not None), default=None)
    check("best_score_matches_unfaulted",
          best_f is not None and best_f == best_r,
          f"faulted {best_f} vs unfaulted {best_r}")
    knobs_f = sorted(_json.dumps(t["knobs"], sort_keys=True)
                     for t in trials)
    knobs_r = sorted(_json.dumps(t["knobs"], sort_keys=True)
                     for t in rtrials)
    check("knob_set_matches_unfaulted", knobs_f == knobs_r,
          "resumed sweep explored different knobs than unfaulted run")

    # Acceptance (c): the post-resume propose_batch shows non-warmup
    # internals — the rehydrated GP drafted with constant-liar, it did
    # not restart from scratch.
    jrecs = journal_mod.read_dir(journal_mod.journal.log_dir)
    check("advisor_rehydrated",
          _journal_has(jrecs, "recovery", "rehydrated"),
          "no recovery/rehydrated journal record")
    batches = [r for r in jrecs if r.get("kind") == "advisor"
               and r.get("name") == "propose_batch"]
    check("post_resume_batch_non_warmup",
          any(b.get("strategy") == "constant_liar_min" for b in batches),
          f"batch strategies: {[b.get('strategy') for b in batches]}")
    check("kill_injected_journaled",
          any(r.get("kind") == "chaos" and r.get("mode") == "kill"
              and r.get("site") == "supervisor.tick" for r in jrecs),
          "no chaos/injected supervisor.tick kill in journals")

    # The crash->adopt->complete story reconstructs from the journals
    # alone via the obs CLI verb.
    p4 = subprocess.run(
        [sys.executable, "-m", "rafiki_tpu.obs", "--dir",
         str(journal_mod.journal.log_dir), "resume", job["id"]],
        env=_sweep_proc_env(chaos=False), capture_output=True, text=True,
        timeout=60)
    check("obs_resume_reconstructs", p4.returncode == 0
          and "resumed:" in p4.stdout,
          f"rc={p4.returncode}: {p4.stderr[-400:]}")


@scenario(
    "host-loss-mid-sweep",
    "Two whole-host losses in one 4-chip / 2-hosts sweep: host 1 "
    "(chips 2,3) is lost first via the host.loss chaos site and the "
    "survivors must re-pack its rows; then host 0 dies taking the "
    "supervisor with it (SIGKILL fired the moment the re-pack hits "
    "the journal — state-triggered, so the ordering is robust to "
    "machine speed), and resume_sweep must adopt the rest and finish "
    "the full budget with clean WAL accounting.",
    spec="seed=29;host.loss:kill:after=2:times=1:match=g0h1",
    env={"RAFIKI_CHECKPOINT_EVERY": "1",
         "RAFIKI_SUPERVISOR_HEARTBEAT_S": "0.2",
         "RAFIKI_MESH_CHIPS_PER_HOST": "2"},
)
def host_loss_mid_sweep(tmp, check: CheckFn) -> None:
    import signal
    import subprocess
    import sys
    import time as _time

    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.scheduler.wal import read_wal, reconcile, wal_path

    BUDGET, CHIPS, K = 8, 4, 2
    store, params, model = _train_env(tmp)
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": BUDGET})

    # Host 0's loss cannot be tick-scheduled: the epoch boundary that
    # unwinds host 1's aborted packs arrives at wildly machine-
    # dependent times (jit compile contention), and killing before the
    # re-pack would test the supervisor-kill path, not host ordering.
    # So the body watches the shared journal dir for the mesh/repack
    # record and THEN kills the supervisor process — the same SIGKILL
    # a real host loss delivers, triggered by cluster state.
    argv = [sys.executable, "-m", "rafiki_tpu.scheduler.sweep_proc", "run",
            "--db", str(store.path), "--params", str(params.directory),
            "--job", job["id"], "--chips", str(CHIPS),
            "--trials-per-chip", str(K), "--advisor", "random"]
    child = subprocess.Popen(argv, env=_sweep_proc_env(),
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
    log_dir = journal_mod.journal.log_dir
    deadline = _time.monotonic() + 120.0
    repacked = False
    while _time.monotonic() < deadline and child.poll() is None:
        if any(r.get("kind") == "mesh" and r.get("name") == "repack"
               for r in journal_mod.read_dir(log_dir)):
            repacked = True
            break
        _time.sleep(0.1)
    if child.poll() is None:
        child.send_signal(signal.SIGKILL)
    child.communicate(timeout=60)
    check("repack_seen_before_host0_loss", repacked,
          "mesh/repack never hit the journals before timeout/exit")
    check("supervisor_host_killed", child.returncode == -9,
          f"run rc={child.returncode}")

    # Survivors re-packed host 1's rows BEFORE host 0 died: the
    # host-loss and re-pack story is already in the journals.
    jrecs = journal_mod.read_dir(journal_mod.journal.log_dir)
    host_lost = [r for r in jrecs if r.get("kind") == "mesh"
                 and r.get("name") == "host_lost"]
    check("host1_loss_journaled",
          any(r.get("host") == 1 for r in host_lost),
          f"host_lost records: {host_lost}")
    check("survivors_repacked",
          _journal_has(jrecs, "mesh", "repack"),
          "no mesh/repack journal record after host loss")

    _time.sleep(0.5)
    p2, summary = _sweep_proc("resume", store, params, job["id"],
                              chips=CHIPS, trials_per_chip=K,
                              env=_sweep_proc_env(chaos=False),
                              stale_after_s=0.4)
    check("resume_completed", p2.returncode == 0,
          f"resume rc={p2.returncode}: {p2.stderr[-800:]}")
    check("resume_adopted_orphans", summary.get("adopted", 0) >= 1, summary)
    _check_rows(check, store, job["id"], expect=BUDGET)

    recs = read_wal(wal_path(store.path, job["id"]))
    for sub in store.get_sub_train_jobs(job["id"]):
        r = reconcile(recs, store.get_trials_of_sub_train_job(sub["id"]),
                      sub=sub, sub_id=sub["id"])
        check("wal_reconciles_clean", r.ok, r.summary())


# A wider-lr sibling of ChaosFF for the early-kill scenario. The GP's
# seed-0 warmup draws over this LINEAR lr range put {0.0127, 8.3e-4}
# on chip 0 (global round-robin) and {0.0054, 3.4e-4} on chip 1: chip
# 0's strong learner sets best-so-far ~0.95, and on the chaos-delayed
# chip 1 the 3.4e-4 member's flat chance-level curve is condemned by
# the predictor while its 0.0054 packmate's still-rising curve
# survives and gets speculated. 8 epochs keep a multi-epoch window
# open between the kill and pack completion for the state-triggered
# SIGKILL below.
EK_SOURCE = b"""
from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob
from rafiki_tpu.models.ff import _Mlp

class ChaosEkFF(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "hidden_units": FixedKnob(24),
            "learning_rate": FloatKnob(1e-5, 0.02, is_exp=False),
            "batch_size": FixedKnob(32),
            "epochs": FixedKnob(8),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(hidden_layers=1,
                    hidden_units=int(self.knobs["hidden_units"]),
                    num_classes=num_classes)
"""


def _uncorrected_spec_hashes(recs) -> set:
    """Hashes with an ``advisor/speculate`` record and no
    ``advisor/feedback`` record anywhere in the stream — the
    speculations a crash would leave in flight."""
    specs = {r.get("knobs_hash") for r in recs
             if r.get("kind") == "advisor" and r.get("name") == "speculate"}
    fed = {r.get("knobs_hash") for r in recs
           if r.get("kind") == "advisor" and r.get("name") == "feedback"}
    return specs - fed


@scenario(
    "early-kill-mid-pack-resume",
    "SIGKILL the sweep supervisor at the worst curve-advisor moment: "
    "a pack member was just early-killed by the learning-curve "
    "predictor and its surviving packmates' speculative scores sit in "
    "the GP uncorrected (the true scores never landed). Resume must "
    "reconcile the WAL with zero double-claimed slots, rehydrate the "
    "advisor from journals alone — real observations plus the "
    "in-flight speculations, byte-identical proposals proven by "
    "rehydrating twice from the same records — and finish the job "
    "with the SAME best score and knob set as an unfaulted kill-on "
    "run under the same seeds.",
    spec="seed=37;worker.epoch:delay:delay=0.25:match=mesh-c1",
    env={"RAFIKI_CHECKPOINT_EVERY": "1",
         "RAFIKI_SUPERVISOR_HEARTBEAT_S": "0.2",
         "RAFIKI_CURVE_KILL": "1",
         "RAFIKI_CURVE_SPECULATE": "1",
         # 5 observations before a verdict (the demo curves are noisy
         # at 1/64 val granularity) and a wide margin so only the
         # flat chance-level member is condemned, never its
         # still-rising packmate.
         "RAFIKI_CURVE_KILL_MIN_OBS": "5",
         "RAFIKI_CURVE_KILL_MARGIN": "0.35"},
)
def early_kill_mid_pack_resume(tmp, check: CheckFn) -> None:
    import json as _json
    import signal
    import subprocess
    import sys
    import time as _time

    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.scheduler.wal import read_wal, reconcile, wal_path
    from rafiki_tpu.store import MetaStore, ParamsStore

    # Budget == GP n_initial: every claim is a seed-deterministic
    # warmup proposal, so ONE unfaulted run is a complete reference
    # (supervisor-kill-mid-sweep's trick). Chip 0 runs undelayed and
    # sets best-so-far; the worker.epoch delay pinned to chip 1
    # (match=mesh-c1) holds its pack mid-flight until best exists, so
    # the doomed member's verdict reliably fires with a live packmate
    # still training.
    BUDGET, CHIPS, K = 4, 2, 2
    fd = tmp / "faulted"
    fd.mkdir(parents=True, exist_ok=True)
    store = MetaStore(fd / "meta.sqlite3")
    params = ParamsStore(fd / "params")
    model = store.create_model("chaosekff", "IMAGE_CLASSIFICATION", None,
                               EK_SOURCE, "ChaosEkFF")
    job = _make_job(store, model, {"MODEL_TRIAL_COUNT": BUDGET})

    # The SIGKILL cannot be tick-scheduled: the kill epoch arrives at
    # machine-dependent times (jit compile contention). Watch the
    # shared journal dir for the advisor/kill record AND an
    # uncorrected advisor/speculate record (the backfill that follows
    # the eviction speculates the surviving packmates), then kill the
    # supervisor — crash state: just-killed member, speculations in
    # flight.
    argv = [sys.executable, "-m", "rafiki_tpu.scheduler.sweep_proc", "run",
            "--db", str(store.path), "--params", str(params.directory),
            "--job", job["id"], "--chips", str(CHIPS),
            "--trials-per-chip", str(K), "--advisor", "gp",
            "--advisor-kwargs", '{"n_initial": 4}']
    child = subprocess.Popen(argv, env=_sweep_proc_env(),
                             stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True)
    log_dir = journal_mod.journal.log_dir
    deadline = _time.monotonic() + 150.0
    killed_seen = spec_in_flight = False
    while _time.monotonic() < deadline and child.poll() is None:
        recs = journal_mod.read_dir(log_dir)
        killed_seen = any(r.get("kind") == "advisor"
                          and r.get("name") == "kill" for r in recs)
        spec_in_flight = bool(_uncorrected_spec_hashes(recs))
        if killed_seen and spec_in_flight:
            break
        _time.sleep(0.02)
    if child.poll() is None:
        child.send_signal(signal.SIGKILL)
    child.communicate(timeout=60)
    check("kill_seen_before_crash", killed_seen,
          "no advisor/kill record before timeout/exit")
    check("speculation_in_flight_at_crash", spec_in_flight,
          "no uncorrected advisor/speculate record at crash point")
    check("supervisor_killed", child.returncode == -9,
          f"run rc={child.returncode}")

    # Byte-identity at the crash point: rehydrate the advisor TWICE
    # from the same frozen journal snapshot + store rows (real scores
    # first, then in-flight speculations — docs/early_kill.md) and the
    # post-resume proposals must byte-match. This is the acceptance
    # gate PR 15's replay contract owes the speculative plane.
    from rafiki_tpu.advisor.rehydrate import rehydrate_advisor
    from rafiki_tpu.advisor.service import AdvisorService
    from rafiki_tpu.model.base import load_model_class

    crash_recs = journal_mod.read_dir(log_dir)
    sub = store.get_sub_train_jobs(job["id"])[0]
    aid = sub.get("advisor_id")
    check("advisor_id_persisted", bool(aid), f"sub row: {sub}")
    model_row = store.get_model(sub["model_id"])
    model_cls = load_model_class(model_row["model_file"],
                                 model_row["model_class"])
    completed = [t for t in store.get_trials_of_train_job(job["id"])
                 if t["status"] == "COMPLETED" and t.get("score") is not None]
    batches = []
    for _ in range(2):
        svc = AdvisorService()
        rehydrate_advisor(svc, model_cls.get_knob_config(), kind="gp",
                          advisor_id=aid, completed=completed,
                          journal_records=crash_recs, seed=0,
                          engine_kwargs={"n_initial": 4},
                          job_id=job["id"])
        batches.append(_json.dumps(svc.get(aid).propose_batch(K),
                                   sort_keys=True))
    check("rehydrated_proposals_byte_match", batches[0] == batches[1],
          f"{batches[0][:200]} vs {batches[1][:200]}")

    _time.sleep(0.5)
    p2, summary = _sweep_proc("resume", store, params, job["id"],
                              chips=CHIPS, trials_per_chip=K,
                              env=_sweep_proc_env(chaos=False),
                              stale_after_s=0.4)
    check("resume_completed", p2.returncode == 0,
          f"resume rc={p2.returncode}: {p2.stderr[-800:]}")
    check("resume_adopted_orphans", summary.get("adopted", 0) >= 1, summary)

    trials = store.get_trials_of_train_job(job["id"])
    check("exact_trial_rows", len(trials) == BUDGET,
          f"{len(trials)} rows for budget {BUDGET}")
    check("no_duplicate_rows",
          len({t["id"] for t in trials}) == len(trials), "duplicate ids")
    bad = [t["id"] for t in trials
           if t["status"] not in ("COMPLETED", "ERRORED")]
    check("all_trials_terminal", not bad, f"non-terminal: {bad}")
    check("killed_trial_errored",
          any(t["status"] == "ERRORED" for t in trials),
          "no ERRORED row — the pre-crash kill vanished on resume")

    # WAL reconcile: zero double-claimed slots despite the kill +
    # crash + adoption churn.
    recs = read_wal(wal_path(store.path, job["id"]))
    for s in store.get_sub_train_jobs(job["id"]):
        r = reconcile(recs, store.get_trials_of_sub_train_job(s["id"]),
                      sub=s, sub_id=s["id"])
        check("wal_reconciles_clean", r.ok, r.summary())
        check("no_double_claims",
              all(n == 1 for n in r.claims.values()), r.summary())

    # Unfaulted kill-on reference under the same seeds, own journal
    # dir: same best score, same knob set, same kill.
    rd = tmp / "reference"
    rd.mkdir(parents=True, exist_ok=True)
    rstore = MetaStore(rd / "meta.sqlite3")
    rparams = ParamsStore(rd / "params")
    rmodel = rstore.create_model("chaosekff", "IMAGE_CLASSIFICATION", None,
                                 EK_SOURCE, "ChaosEkFF")
    rjob = _make_job(rstore, rmodel, {"MODEL_TRIAL_COUNT": BUDGET})
    renv = _sweep_proc_env(chaos=False)
    renv["RAFIKI_LOG_DIR"] = str(rd / "obs")
    p3, _ = _sweep_proc("run", rstore, rparams, rjob["id"], chips=CHIPS,
                        trials_per_chip=K, env=renv, advisor="gp",
                        advisor_kwargs='{"n_initial": 4}')
    check("reference_completed", p3.returncode == 0,
          f"reference rc={p3.returncode}: {p3.stderr[-500:]}")
    rtrials = rstore.get_trials_of_train_job(rjob["id"])
    best_f = max((t["score"] for t in trials
                  if t["score"] is not None), default=None)
    best_r = max((t["score"] for t in rtrials
                  if t["score"] is not None), default=None)
    check("best_score_matches_unfaulted",
          best_f is not None and best_f == best_r,
          f"faulted {best_f} vs unfaulted {best_r}")
    knobs_f = sorted(_json.dumps(t["knobs"], sort_keys=True)
                     for t in trials)
    knobs_r = sorted(_json.dumps(t["knobs"], sort_keys=True)
                     for t in rtrials)
    check("knob_set_matches_unfaulted", knobs_f == knobs_r,
          "resumed sweep explored different knobs than unfaulted run")


# ---------------------------------------------------------------------------
# Tenant isolation (docs/multitenancy.md)
# ---------------------------------------------------------------------------


def _tenant_recs(recs, name: str, tenant: str) -> List[dict]:
    return [r for r in recs
            if r.get("kind") == "tenant" and r.get("name") == name
            and r.get("tenant") == tenant]


@scenario(
    "noisy-neighbor-shed",
    "Tenant isolation under a noisy neighbor: an aggressor tenant "
    "floods a tenant-aware gateway at ~10x the victim's rate while "
    "every forward pays an injected delay. Weighted-fair admission "
    "with per-tenant quotas must shed the AGGRESSOR (tenant_quota, "
    "charged to the flooder) while the victim's p99 stays inside its "
    "gold budget and the victim sheds nothing — every invariant read "
    "from the per-tenant journals alone.",
    spec="seed=13;inference.forward:delay:delay=0.06",
)
def noisy_neighbor_shed(tmp, check: CheckFn) -> None:
    from rafiki_tpu.gateway import Gateway, GatewayConfig, ShedError
    from rafiki_tpu.obs import journal as journal_mod
    from rafiki_tpu.predictor import Predictor
    from rafiki_tpu.tenancy import TenantDirectory, TenantFabric

    VICTIM, AGGRESSOR = "victim", "aggressor"
    cluster = _ServingCluster(1)
    try:
        fabric = TenantFabric(TenantDirectory(
            tiers={VICTIM: "gold", AGGRESSOR: "batch"}))
        budget_ms = fabric.directory.tier_of(VICTIM).p99_budget_ms
        predictor = Predictor(cluster.bus, JOB, timeout_s=8.0)
        # TWO inflight slots so the quota actually binds: at
        # quota_frac 0.5 each tenant may hold ONE. Weighted mode caps
        # the aggressor at that one slot — the victim is always the
        # next eligible tenant and waits at most one in-flight forward.
        # Unweighted (the doctored smoke polarity) ignores the quota
        # and degrades to global FIFO, so the victim queues behind the
        # whole flood — which is exactly what blows the victim-p99
        # gate below. (max_inflight=1 would NOT separate the modes:
        # with a single slot every tenant's inflight is 0 at decision
        # time, the weighted charge ties at 0, and arbitration
        # collapses to the same FIFO tie-break.)
        gw = Gateway(predictor,
                     GatewayConfig(min_replies=1, max_inflight=2,
                                   max_queue=8),
                     tenancy=fabric)
        stop = threading.Event()

        def aggress():
            # The 10x spike: flood until stopped; sheds (the expected
            # outcome) back off briefly so the loop doesn't busy-spin.
            while not stop.is_set():
                try:
                    gw.predict([[1.0]], tenant=AGGRESSOR)
                except (ShedError, RuntimeError):
                    time.sleep(0.005)

        # 8 flooders against 2+8 capacity: deep queue pressure without
        # ever filling the shared queue, so the victim always gets to
        # ENQUEUE in both polarities — the gates then measure who the
        # arbitration serves and who it sheds, not who got in the door.
        flood = [threading.Thread(target=aggress, daemon=True,
                                  name=f"aggr-{i}") for i in range(8)]
        for th in flood:
            th.start()
        time.sleep(0.3)  # flood fully established before the victim
        victim_errors = 0
        for _ in range(25):
            try:
                gw.predict([[1.0]], tenant=VICTIM)
            except (ShedError, RuntimeError):
                victim_errors += 1
            time.sleep(0.02)
        stop.set()
        for th in flood:
            th.join(timeout=5)
        gw.drain(timeout=10.0)  # flushes the tenant/summary record
    finally:
        cluster.close()

    # Everything below reads ONLY the per-tenant journal records — the
    # isolation story must reconstruct without touching live objects.
    recs = journal_mod.read_dir(journal_mod.journal.log_dir)
    victim_lat = sorted(r.get("e2e_s", 0.0) * 1000.0
                        for r in _tenant_recs(recs, "request", VICTIM))
    victim_p99 = (victim_lat[min(len(victim_lat) - 1,
                                 int(0.99 * len(victim_lat)))]
                  if victim_lat else float("inf"))
    aggr_sheds = _tenant_recs(recs, "shed", AGGRESSOR)
    victim_sheds = _tenant_recs(recs, "shed", VICTIM)
    check("victim_served", len(victim_lat) >= 20 and victim_errors == 0,
          f"{len(victim_lat)} victim completions, "
          f"{victim_errors} errors/sheds at the caller")
    check("victim_p99_within_budget", victim_p99 <= budget_ms,
          f"victim p99 {victim_p99:.1f}ms vs gold budget {budget_ms}ms "
          f"({len(victim_lat)} samples)")
    check("aggressor_shed", len(aggr_sheds) > 0,
          "the flood never shed — no contention was created")
    check("shed_charged_to_aggressor_quota",
          any(r.get("reason") == "tenant_quota" for r in aggr_sheds),
          f"aggressor shed reasons: "
          f"{sorted({r.get('reason') for r in aggr_sheds})}")
    check("victim_never_shed", len(victim_sheds) == 0,
          f"{len(victim_sheds)} victim sheds: "
          f"{sorted({r.get('reason') for r in victim_sheds})}")
    summaries = [r for r in recs if r.get("kind") == "tenant"
                 and r.get("name") == "summary"]
    summary_aggr = (summaries[-1].get("tenants", {})
                    .get(AGGRESSOR, {}) if summaries else {})
    check("summary_reconciles_sheds",
          bool(summaries) and summary_aggr.get("shed") == len(aggr_sheds),
          f"summary={summary_aggr} vs {len(aggr_sheds)} tenant/shed recs")
