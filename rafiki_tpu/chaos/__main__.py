"""CLI: ``python -m rafiki_tpu.chaos run <scenario>|all`` / ``list``.

Runs recovery scenarios against an in-proc cluster and exits nonzero
on any failed invariant — the entrypoint scripts/chaos_smoke.py and
operators use to replay a fault schedule deterministically.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    # Before ANYTHING imports jax (analysis rule RF001): scenario
    # clusters run on whatever platform the env pins — CPU in CI.
    from rafiki_tpu.utils.backend import ensure_host_device_count, honor_env_platform

    honor_env_platform()
    # Mesh scenarios (docs/mesh_sweep.md) need a multi-chip pod; on the
    # CPU fake this is 8 virtual devices, same as the test suite.
    ensure_host_device_count(8)

    from rafiki_tpu.chaos.runner import (
        SCENARIOS, format_report, run_scenarios)

    parser = argparse.ArgumentParser(
        prog="python -m rafiki_tpu.chaos",
        description="Deterministic fault-injection scenario runner")
    sub = parser.add_subparsers(dest="command", required=True)
    sub.add_parser("list", help="list scenarios")
    runp = sub.add_parser("run", help="run scenarios")
    runp.add_argument("scenarios", nargs="+",
                      help="scenario names, or 'all'")
    runp.add_argument("--json", action="store_true",
                      help="machine-readable reports on stdout")
    args = parser.parse_args(argv)

    if args.command == "list":
        for name in sorted(SCENARIOS):
            print(f"{name}\n    {SCENARIOS[name].description}")
        return 0

    names = (sorted(SCENARIOS) if args.scenarios == ["all"]
             else args.scenarios)
    unknown = [n for n in names if n not in SCENARIOS]
    if unknown:
        print(f"unknown scenarios: {unknown}; "
              f"known: {sorted(SCENARIOS)}", file=sys.stderr)
        return 2
    reports = run_scenarios(names)
    if args.json:
        print(json.dumps([r.to_dict() for r in reports], indent=2))
    else:
        for r in reports:
            print(format_report(r))
    failed = [r.name for r in reports if not r.passed]
    if failed:
        print(f"\nFAILED: {failed}", file=sys.stderr)
        return 1
    print(f"\nall {len(reports)} scenario(s) passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
