"""Chaos plane: deterministic fault injection + recovery scenarios.

Two halves (docs/chaos.md):

* :mod:`rafiki_tpu.chaos.plane` — the ``RAFIKI_CHAOS``-driven fault
  registry and the ``hook()``/``decide()`` call-site API threaded
  through the bus, stores, workers, scheduler and serving path.
* :mod:`rafiki_tpu.chaos.scenarios` / :mod:`rafiki_tpu.chaos.runner` —
  the declarative scenario catalog and the runner that stands up an
  in-proc cluster, injects the scheduled faults and asserts recovery
  invariants (``python -m rafiki_tpu.chaos run <scenario>``).

Import cost matters: this package is imported by the bus and the
stores, so only ``plane`` (stdlib + telemetry) loads eagerly; the
scenario machinery — which pulls in schedulers and models — stays
behind ``python -m rafiki_tpu.chaos`` / explicit imports.
"""

from rafiki_tpu.chaos.plane import (  # noqa: F401
    ENV_VAR,
    ChaosError,
    ChaosSpecError,
    Fault,
    FaultPlane,
    active,
    decide,
    hook,
    install,
    perform,
    reset_from_env,
    uninstall,
)
