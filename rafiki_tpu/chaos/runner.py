"""Scenario runner: install the fault plane, run the body, report.

One scenario run is: set ``RAFIKI_CHAOS`` (subprocess workers inherit
it) plus the scenario's extra env, install a freshly parsed
:class:`FaultPlane` in THIS process, reset telemetry so counter
invariants read from zero, execute the body in a temp dir, then
restore everything — env, plane, nothing leaks into the caller. The
report carries every invariant verdict and the plane's fired-fault
schedule (the replay-determinism surface: same seed → same schedule).

Telemetry: each run emits a ``chaos.scenario`` span, observes the
wall-clock into the ``chaos.scenario_s`` histogram and — for scenarios
that recover from a fault rather than merely surface one — the time
into ``chaos.recovery_s``. Injected-fault counters (``chaos.injected``
and per site.mode) are incremented by the plane itself as faults fire.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
import time
import traceback
from pathlib import Path
from typing import Dict, List, Optional

from rafiki_tpu import telemetry
from rafiki_tpu.chaos.plane import ENV_VAR, FaultPlane, install, uninstall
from rafiki_tpu.chaos.scenarios import SCENARIOS
from rafiki_tpu.obs import journal as journal_mod
from rafiki_tpu.obs.journal import journal

# Scenarios whose pass means "the system RECOVERED" (vs. "the failure
# surfaced correctly"): their duration feeds the recovery histogram.
_RECOVERY_SCENARIOS = frozenset({
    "kill-mid-trial-resume", "kill-mid-pack-resume",
    "checkpoint-write-failure", "drain-under-load",
    "mesh-chip-loss-repack", "chip-loss-mid-sharded-trial",
    "collective-kill-mid-step",
    "mesh-degrades-single-chip", "load-spike-scale-up",
    "supervisor-kill-mid-sweep", "host-loss-mid-sweep",
})

# Subprocess-killing scenarios must be reconstructible from the
# journals ALONE (ISSUE 6 tentpole e): the runner gives each run a
# journal dir (inherited by workers via RAFIKI_LOG_DIR), then asserts
# the death/recovery story is readable back out of the merged files —
# including the flight record the scheduler dumps for the dead worker.
_JOURNALED_SCENARIOS = frozenset({
    "kill-mid-trial-resume", "kill-mid-pack-resume",
    "collective-kill-mid-step",
})


@dataclasses.dataclass
class CheckResult:
    name: str
    ok: bool
    detail: str = ""


@dataclasses.dataclass
class ScenarioReport:
    name: str
    passed: bool
    checks: List[CheckResult]
    schedule: List[tuple]          # fired faults: (site, mode, hit, key)
    duration_s: float
    error: Optional[str] = None    # traceback if the body raised
    # Last flight-recorder payload dumped during the run (the scenario
    # tempdir is gone by the time the report is read, so the payload is
    # carried, not the path). None when nothing dumped.
    flight_record: Optional[dict] = None
    # Digital-twin pre-gate forecast (obs/twin/pregate.py): the spec's
    # predicted serving impact, simulated offline BEFORE injection. None
    # for specs that touch no serving site, or if forecasting failed.
    twin_forecast: Optional[dict] = None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "passed": self.passed,
            "duration_s": round(self.duration_s, 3),
            "checks": [dataclasses.asdict(c) for c in self.checks],
            "schedule": [list(s) for s in self.schedule],
            "error": self.error,
            "flight_record": ({"reason": self.flight_record.get("reason"),
                               "role": self.flight_record.get("role"),
                               "pid": self.flight_record.get("pid")}
                              if self.flight_record else None),
            "twin_forecast": self.twin_forecast,
        }


def _set_env(values: Dict[str, str]) -> Dict[str, Optional[str]]:
    saved: Dict[str, Optional[str]] = {}
    for k, v in values.items():
        saved[k] = os.environ.get(k)
        os.environ[k] = v
    return saved


def _restore_env(saved: Dict[str, Optional[str]]) -> None:
    for k, old in saved.items():
        if old is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = old


def run_scenario(name: str) -> ScenarioReport:
    sc = SCENARIOS.get(name)
    if sc is None:
        raise KeyError(f"unknown chaos scenario {name!r}; "
                       f"one of {sorted(SCENARIOS)}")
    checks: List[CheckResult] = []

    def check(cname: str, ok, detail="") -> None:
        checks.append(CheckResult(cname, bool(ok), str(detail)))

    plane = FaultPlane.from_spec(sc.spec)  # parse FIRST: typos fail loudly
    twin_forecast = _twin_pregate(sc.spec)
    saved = _set_env(dict(sc.env, **{ENV_VAR: sc.spec}))
    install(plane)
    telemetry.reset()
    from rafiki_tpu.obs.ledger import ledger

    ledger.reset()  # goodput buckets read from zero, like the counters
    # The runner's journal gets re-pointed into each scenario's tempdir;
    # remember where it was so nothing leaks into the caller.
    prev_journal_dir = journal.log_dir if journal.configured else None
    prev_journal_role = journal.role
    flight: Optional[dict] = None
    error: Optional[str] = None
    t0 = time.monotonic()
    try:
        with telemetry.span("chaos.scenario", scenario=name):
            with tempfile.TemporaryDirectory(prefix=f"chaos-{name}-") as td:
                log_dir = Path(td) / "obs"
                saved_log = _set_env({journal_mod.ENV_VAR: str(log_dir)})
                journal.configure(log_dir, role="chaos-runner")
                try:
                    sc.fn(Path(td), check)
                finally:
                    _restore_env(saved_log)
                flights = sorted(log_dir.glob("flight-*.json"))
                if flights:
                    try:
                        flight = json.loads(flights[-1].read_text())
                    except (OSError, json.JSONDecodeError):
                        flight = None
                if name in _JOURNALED_SCENARIOS:
                    _journal_checks(check, log_dir, flights)
    except Exception:
        error = traceback.format_exc()
    finally:
        _restore_env(saved)
        uninstall()
        if prev_journal_dir is not None:
            journal.configure(prev_journal_dir, role=prev_journal_role)
        else:
            journal.close()
    # lint: disable=RF007 — fed to chaos.scenario_s; body runs under a span
    duration = time.monotonic() - t0
    telemetry.observe("chaos.scenario_s", duration)
    if name in _RECOVERY_SCENARIOS:
        telemetry.observe("chaos.recovery_s", duration)
    passed = error is None and bool(checks) and all(c.ok for c in checks)
    return ScenarioReport(name=name, passed=passed, checks=checks,
                          schedule=plane.schedule(), duration_s=duration,
                          error=error, flight_record=flight,
                          twin_forecast=twin_forecast)


def _twin_pregate(spec: str) -> Optional[dict]:
    """Ask the digital twin what this spec should do to serving before
    injecting it for real (docs/twin.md). Advisory only: any failure
    degrades to None — the pre-gate must never break the scenario it
    pre-games. Runs before install()/telemetry.reset so the forecast's
    simulated chaos decisions can't pollute the scenario's counters."""
    try:
        from rafiki_tpu.obs.twin import pregate
        return pregate.forecast(spec)
    except Exception:
        return None


def _journal_checks(check, log_dir: Path, flights: List[Path]) -> None:
    """The journals-alone reconstruction story for a kill scenario: the
    merged journal files must show the injection, the death, and the
    trial lifecycle — across at least the runner and one worker — and
    the scheduler must have dumped a flight record for the dead child."""
    recs = journal_mod.read_dir(log_dir)
    pids = {r.get("pid") for r in recs}
    check("journal_multi_process", len(pids) >= 2,
          f"records from {len(pids)} pid(s)")
    check("journal_records_kill_injection",
          any(r.get("kind") == "chaos" and r.get("mode") == "kill"
              for r in recs),
          "no chaos/injected kill record in the journals")
    ev = {r.get("name") for r in recs if r.get("kind") == "event"}
    check("journal_records_trial_lifecycle",
          {"trial_started", "trial_completed"} <= ev,
          f"event names journaled: {sorted(ev)}")
    check("journal_records_worker_death", "worker_died" in ev,
          f"event names journaled: {sorted(ev)}")
    check("flight_record_dumped", bool(flights),
          f"no flight-*.json under {log_dir}")


def run_scenarios(names: Optional[List[str]] = None) -> List[ScenarioReport]:
    return [run_scenario(n) for n in (names or sorted(SCENARIOS))]


def format_report(report: ScenarioReport) -> str:
    lines = [f"{'PASS' if report.passed else 'FAIL'}  {report.name}  "
             f"({report.duration_s:.1f}s)"]
    for c in report.checks:
        mark = "ok " if c.ok else "FAIL"
        tail = f"  -- {c.detail}" if (c.detail and not c.ok) else ""
        lines.append(f"  [{mark}] {c.name}{tail}")
    if report.schedule:
        lines.append(f"  injected ({len(report.schedule)} faults):")
        shown = report.schedule[:10]
        for site, mode, hit, key in shown:
            lines.append(f"    {site}:{mode} hit={hit} key={key!r}")
        if len(report.schedule) > len(shown):
            lines.append(f"    ... {len(report.schedule) - len(shown)} more")
    else:
        lines.append("  injected: (none fired)")
    if report.error:
        lines.append("  scenario raised:")
        lines.extend(f"    {line}" for line in report.error.splitlines())
    return "\n".join(lines)
