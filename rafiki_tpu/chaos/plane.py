"""Deterministic fault-injection plane (docs/chaos.md).

Recovery code that is only ever exercised by real outages is folklore,
not engineering: the supervise loop's restart path, the bus's lease
reaping, checkpoint resume — none of it is trustworthy until a fault
can be REPLAYED. This module is the injection half of the chaos
subsystem: a registry of parsed fault specs (``FaultPlane``) consulted
from call sites threaded through the bus, the stores, the workers, the
process scheduler and the serving path. The scenario half
(scenarios.py / runner.py) schedules faults against an in-proc cluster
and asserts the recovery invariants.

Design constraints, in priority order:

* **Inert by default.** With ``RAFIKI_CHAOS`` unset, every hook is a
  module-global ``None`` check — no parsing, no locks, no telemetry,
  no timing change on the hot paths (the bus ops and the train loop
  call hooks per message / per epoch).
* **Deterministic.** Every probabilistic decision draws from a
  ``random.Random`` seeded by ``(seed, site, mode, spec-index)`` and
  consumed one draw per *matching hit* of that spec — so a fixed seed
  replays the identical fault schedule regardless of wall clock, and
  (per site) regardless of how other sites interleave. ``schedule()``
  returns the fired record for replay assertions.
* **Process-local, env-propagated.** The plane initializes from the
  environment at import; subprocess workers inherit ``RAFIKI_CHAOS``
  (scheduler/process.py spawns with ``env=dict(os.environ)``), so a
  worker can deterministically SIGKILL *itself* at epoch N — which is
  how kill-at-epoch faults stay exact instead of racing an external
  killer against the train loop.

Spec grammar (full reference in docs/chaos.md)::

    RAFIKI_CHAOS="seed=7;worker.epoch:kill:after=1:unless=-r;bus.add_query:drop:p=0.3"

``<site>:<mode>[:opt]...`` entries separated by ``;``. Options:
``p=<float>`` fire probability (default 1), ``after=<int>`` skip the
first N matching hits, ``times=<int>`` max fires (default unlimited),
``delay=<float>`` sleep seconds for delay modes, ``match=<substr>`` /
``unless=<substr>`` filter on the hook key (e.g. a worker id — a
restarted worker's ``-r<N>`` suffix is how kill faults are scoped to
the first incarnation only).

Modes and who enacts them:

=========  ==============================================================
drop/skip  returned to the call site, which drops the message / skips
           the heartbeat
delay      ``hook()`` itself sleeps ``delay`` seconds (latency spike /
           stuck replica / slow disk)
error      ``hook()`` raises :class:`ChaosError` (an ``OSError`` — a
           failing store write)
kill/term  ``hook()`` signals the CURRENT process (SIGKILL/SIGTERM) —
           in-worker crash-at-epoch faults
preempt    never self-enacted; the process scheduler consumes it via
           :func:`decide` and SIGTERMs the worker subprocess, SIGKILL
           after the ``delay`` grace (simulated preemption)
=========  ==============================================================
"""

from __future__ import annotations

import os
import random
import signal
import threading
import time
from typing import Dict, List, Optional, Tuple

from rafiki_tpu import telemetry
from rafiki_tpu.obs.journal import journal as _journal

ENV_VAR = "RAFIKI_CHAOS"

# "nan" is caller-enacted (like drop/skip/preempt): the train loops'
# ``train.nan`` site turns a fired hook into a one-step gradient poison
# column (ops/train.py, docs/health.md); perform() just reports it.
_MODES = ("drop", "skip", "delay", "error", "kill", "term", "preempt", "nan")


class ChaosError(OSError):
    """The injected failure for ``error``-mode faults. An ``OSError``
    subclass so store-write call sites see the same exception shape a
    genuinely failing disk would produce."""


class ChaosSpecError(ValueError):
    """Raised for an unparseable ``RAFIKI_CHAOS`` spec — loudly, at
    install time: a typo'd fault spec silently injecting nothing would
    make a chaos scenario vacuously green."""


class Fault:
    """One parsed ``site:mode[:opts]`` entry plus its firing state."""

    __slots__ = ("site", "mode", "prob", "after", "times", "delay_s",
                 "match", "unless", "hits", "fired", "rng")

    def __init__(self, site: str, mode: str, prob: float = 1.0,
                 after: int = 0, times: Optional[int] = None,
                 delay_s: float = 0.05, match: Optional[str] = None,
                 unless: Optional[str] = None):
        self.site = site
        self.mode = mode
        self.prob = prob
        self.after = after
        self.times = times
        self.delay_s = delay_s
        self.match = match
        self.unless = unless
        self.hits = 0
        self.fired = 0
        self.rng: Optional[random.Random] = None

    def describe(self) -> str:
        opts = [f"p={self.prob}" if self.prob < 1.0 else "",
                f"after={self.after}" if self.after else "",
                f"times={self.times}" if self.times is not None else "",
                f"match={self.match}" if self.match else "",
                f"unless={self.unless}" if self.unless else ""]
        tail = ":".join(o for o in opts if o)
        return f"{self.site}:{self.mode}" + (f":{tail}" if tail else "")


def _parse_fault(entry: str, index: int) -> Fault:
    parts = entry.split(":")
    if len(parts) < 2:
        raise ChaosSpecError(
            f"chaos spec entry {entry!r} needs at least site:mode")
    site, mode = parts[0].strip(), parts[1].strip()
    if not site:
        raise ChaosSpecError(f"chaos spec entry {entry!r} has an empty site")
    if mode not in _MODES:
        raise ChaosSpecError(
            f"chaos spec entry {entry!r}: unknown mode {mode!r} "
            f"(one of {', '.join(_MODES)})")
    kwargs: Dict[str, object] = {}
    for opt in parts[2:]:
        if "=" not in opt:
            raise ChaosSpecError(
                f"chaos spec entry {entry!r}: option {opt!r} is not k=v")
        k, v = opt.split("=", 1)
        k = k.strip()
        try:
            if k == "p":
                kwargs["prob"] = float(v)
            elif k == "after":
                kwargs["after"] = int(v)
            elif k == "times":
                kwargs["times"] = int(v)
            elif k == "delay":
                kwargs["delay_s"] = float(v)
            elif k == "match":
                kwargs["match"] = v
            elif k == "unless":
                kwargs["unless"] = v
            else:
                raise ChaosSpecError(
                    f"chaos spec entry {entry!r}: unknown option {k!r}")
        except (TypeError, ValueError) as e:
            if isinstance(e, ChaosSpecError):
                raise
            raise ChaosSpecError(
                f"chaos spec entry {entry!r}: bad value for {k!r}: {v!r}")
    return Fault(site, mode, **kwargs)  # type: ignore[arg-type]


class FaultPlane:
    """A parsed fault registry with per-spec deterministic firing state.

    Decisions are made under one lock (hook sites span threads); the
    rng stream per spec is keyed by ``(seed, site, mode, index)`` and
    advanced once per matching hit, so two runs with the same seed and
    the same per-site hit sequences fire identically.
    """

    def __init__(self, faults: List[Fault], seed: int = 0,
                 spec: Optional[str] = None):
        self.seed = int(seed)
        self.spec = spec
        self.faults = list(faults)
        self._lock = threading.Lock()
        self._schedule: List[Tuple[str, str, int, str]] = []
        # Index faults by site: decide() must stay O(faults-on-site),
        # not O(all-faults), since hot paths call it per message.
        self._by_site: Dict[str, List[Fault]] = {}
        for i, f in enumerate(self.faults):
            f.rng = random.Random(f"{self.seed}:{f.site}:{f.mode}:{i}")
            self._by_site.setdefault(f.site, []).append(f)

    @classmethod
    def from_spec(cls, spec: str) -> "FaultPlane":
        """Parse ``seed=N;site:mode:opts;...``. Raises ChaosSpecError."""
        seed = 0
        faults: List[Fault] = []
        entries = [e.strip() for e in spec.split(";") if e.strip()]
        if not entries:
            raise ChaosSpecError(f"empty chaos spec {spec!r}")
        for i, entry in enumerate(entries):
            if entry.startswith("seed="):
                try:
                    seed = int(entry[len("seed="):])
                except ValueError:
                    raise ChaosSpecError(f"bad chaos seed in {entry!r}")
                continue
            faults.append(_parse_fault(entry, len(faults)))
        return cls(faults, seed=seed, spec=spec)

    def decide(self, site: str, key: str = "") -> Optional[Fault]:
        """The pure decision: does a fault fire at this hit of ``site``?

        Counts the hit against every spec registered for the site
        (match/unless-filtered), honors after/times, draws the spec's
        rng for probabilistic faults, records fired entries in the
        schedule and telemetry. Returns the firing Fault or None. The
        caller (or :func:`perform`) enacts the mode.
        """
        specs = self._by_site.get(site)
        if not specs:
            return None
        with self._lock:
            for f in specs:
                if f.match is not None and f.match not in key:
                    continue
                if f.unless is not None and f.unless in key:
                    continue
                f.hits += 1
                if f.hits <= f.after:
                    continue
                if f.times is not None and f.fired >= f.times:
                    continue
                if f.prob < 1.0 and f.rng.random() >= f.prob:
                    continue
                f.fired += 1
                self._schedule.append((site, f.mode, f.hits, key))
                telemetry.inc("chaos.injected")
                # Sites and modes are both closed sets from the spec
                # grammar, refining the literal aggregate above.
                # lint: disable=RF008 — bounded site×mode refinement of chaos.injected
                telemetry.inc(f"chaos.injected.{site}.{f.mode}")
                # Journal the injection: a chaos scenario must be
                # reconstructible from the journals alone (which process
                # got hit, at what site, on which hit count).
                _journal.record("chaos", "injected", site=site,
                                mode=f.mode, key=key, hit=f.hits)
                return f
        return None

    def schedule(self) -> List[Tuple[str, str, int, str]]:
        """The fired-fault record: (site, mode, hit_no, key) tuples in
        firing order — the replay-determinism assertion surface."""
        with self._lock:
            return list(self._schedule)


# ---------------------------------------------------------------------------
# Module-level plane: the thing hook call sites consult.
# ---------------------------------------------------------------------------

def _plane_from_env() -> Optional[FaultPlane]:
    spec = os.environ.get(ENV_VAR, "").strip()
    if not spec:
        return None
    return FaultPlane.from_spec(spec)


_PLANE: Optional[FaultPlane] = _plane_from_env()


def active() -> Optional[FaultPlane]:
    """The installed plane, or None when chaos is off."""
    return _PLANE


def install(plane: Optional[FaultPlane]) -> None:
    """Install a plane for this process (the scenario runner's entry;
    normal processes get theirs from the env at import)."""
    global _PLANE
    _PLANE = plane


def uninstall() -> None:
    install(None)


def reset_from_env() -> Optional[FaultPlane]:
    """Re-read ``RAFIKI_CHAOS`` (tests mutate the env after import)."""
    install(_plane_from_env())
    return _PLANE


def decide(site: str, key: str = "") -> Optional[Fault]:
    """Decision without enactment — for call sites that direct the
    fault at something other than the current process (the scheduler
    preempting a worker subprocess)."""
    plane = _PLANE
    if plane is None:
        return None
    return plane.decide(site, key)


def perform(fault: Fault) -> str:
    """Enact a self-directed fault; returns the mode for the caller to
    interpret (drop/skip are pure return values)."""
    if fault.mode == "delay":
        time.sleep(fault.delay_s)
        # An injected stall is downtime by definition: charge it to the
        # goodput ledger so chaos runs show up as degraded goodput.
        from rafiki_tpu.obs.ledger import ledger

        ledger.add("downtime_s", fault.delay_s)
    elif fault.mode == "error":
        raise ChaosError(
            f"chaos: injected {fault.site} failure ({fault.describe()})")
    elif fault.mode == "kill":
        os.kill(os.getpid(), signal.SIGKILL)
    elif fault.mode == "term":
        os.kill(os.getpid(), signal.SIGTERM)
    return fault.mode


def hook(site: str, key: str = "") -> Optional[str]:
    """The one-liner every instrumented call site uses. Inert path:
    one global read and a None check. Active path: decide, enact
    self-directed modes (sleep / raise / signal self), return the mode
    string so drop/skip call sites can act on it."""
    plane = _PLANE
    if plane is None:
        return None
    fault = plane.decide(site, key)
    if fault is None:
        return None
    return perform(fault)
