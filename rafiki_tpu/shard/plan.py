"""Group-width planning for sharded trials: param pytree -> NamedShardings.

The sweep's packing lane answers "how many small trials fit one chip";
this module answers the inverse question — "how many chips does one
big trial need". A :class:`ShardPlan` turns a model family's param
pytree (really: any train-state pytree) plus an HBM estimate into

  * the smallest group **width** whose per-chip share of the state
    fits under the HBM ceiling (``RAFIKI_SHARD_HBM_CEILING`` of the
    chip's capacity — the same 0.9 the training twin's what-if lane
    uses), and
  * per-leaf ``PartitionSpec``s over a 1-D ``("shard",)`` mesh axis:
    FSDP-style parameter sharding — each leaf is split along its
    largest width-divisible axis, small/indivisible leaves replicate.
    The dp batch axis is untouched (batches stay replicated across the
    group; a dp mesh can still shard them within each member).

The HBM estimate prefers the XLA cost model's ``peak_hbm_bytes`` from
a ``perf/cost`` capture (obs/perf/profiler.py) when the caller has
one; absent that it falls back to 4x the raw parameter bytes (params
+ grads + adam mu/nu — the serial loop's steady-state residency).

Placement is *shape-deterministic*: the axis chosen for a leaf is a
pure function of (shape, width). Reshard-on-restore
(shard/checkpoint.py) leans on this — a checkpoint written at width w
records each leaf's saved axis in its manifest, and a restore at
width w' recomputes its own placement from the same rule, so no
sharding state needs to survive outside the manifest.

The ``("model",)`` ensemble sketch in parallel/ensemble.py (stacked
trials, leading trial axis) is the degenerate ancestor of this:
there the leading axis is *semantic* (trial index); here the axis is
chosen per-leaf for capacity.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

ENV_HBM_CEILING = "RAFIKI_SHARD_HBM_CEILING"
ENV_MAX_WIDTH = "RAFIKI_SHARD_MAX_WIDTH"
ENV_FORCE_WIDTH = "RAFIKI_SHARD_WIDTH"

#: v5e per-chip HBM — single source shared with the twin's capacity math.
from rafiki_tpu.obs.twin.calibration import HBM_BYTES_PER_CHIP  # noqa: E402


def hbm_ceiling() -> float:
    return float(os.environ.get(ENV_HBM_CEILING, "0.9"))


def max_width() -> int:
    return int(os.environ.get(ENV_MAX_WIDTH, "8"))


def forced_width() -> int:
    """``RAFIKI_SHARD_WIDTH`` > 0 pins the group width (tests, chaos
    scenarios, and CPU smokes, where no real model trips the ceiling);
    0 (the default) solves it from the HBM estimate."""
    return int(os.environ.get(ENV_FORCE_WIDTH, "0"))


def shard_axis(shape: Tuple[int, ...], width: int) -> Optional[int]:
    """The axis of ``shape`` a width-``width`` group shards, or None to
    replicate. Deterministic: the largest axis whose dim is divisible
    by (and at least) the width — ties go to the earliest axis."""
    if width <= 1:
        return None
    best = None
    for a, d in enumerate(shape):
        if d % width == 0 and d >= width and d > 1:
            if best is None or d > shape[best]:
                best = a
    return best


def path_str(path) -> str:
    """A tree_map_with_path key path rendered to the same ``a/b/c``
    string flax's flatten_dict(to_state_dict(tree), sep="/") produces —
    the join key between live pytrees and serialized manifests."""
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        elif hasattr(p, "name"):
            parts.append(str(p.name))
        else:  # pragma: no cover - future key types
            parts.append(str(p))
    return "/".join(parts)


def state_bytes(tree: Any) -> int:
    """Raw bytes of every leaf in ``tree`` (shapes only — works on
    ShapeDtypeStructs from eval_shape as well as live arrays)."""
    import numpy as np

    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", ())
        dtype = np.dtype(getattr(leaf, "dtype", np.float32))
        total += int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    return total


def estimate_hbm_bytes(params: Any,
                       peak_hbm_bytes: Optional[float] = None) -> int:
    """HBM residency estimate for one trial: the XLA cost model's
    figure when a ``perf/cost`` capture exists, else 4x param bytes
    (params + grads + adam mu/nu)."""
    if peak_hbm_bytes:
        return int(peak_hbm_bytes)
    return 4 * state_bytes(params)


def solve_width(hbm_bytes: int, ceiling: Optional[float] = None,
                cap: Optional[int] = None) -> int:
    """Smallest power-of-two group width whose per-chip share of
    ``hbm_bytes`` fits under the ceiling. ``RAFIKI_SHARD_WIDTH``
    overrides (pinned width); the solve clamps at
    ``RAFIKI_SHARD_MAX_WIDTH`` even when the estimate wants more."""
    forced = forced_width()
    if forced > 0:
        return forced
    ceiling = hbm_ceiling() if ceiling is None else ceiling
    cap = max_width() if cap is None else cap
    budget = ceiling * HBM_BYTES_PER_CHIP
    width = 1
    while width < cap and hbm_bytes / width > budget:
        width *= 2
    return width


@dataclass(frozen=True)
class ShardPlan:
    """One trial's group placement: width + per-leaf partitioning rule.

    Frozen and cheap — a plan is derived data (shapes + an estimate),
    safe to recompute anywhere; the scheduler journals it once per
    group as ``shard/plan``.
    """

    width: int
    hbm_bytes: int = 0
    family: str = ""

    @classmethod
    def for_params(cls, params: Any, family: str = "",
                   peak_hbm_bytes: Optional[float] = None,
                   width: Optional[int] = None) -> "ShardPlan":
        hbm = estimate_hbm_bytes(params, peak_hbm_bytes)
        return cls(width=width if width else solve_width(hbm),
                   hbm_bytes=hbm, family=family)

    def hbm_frac(self) -> float:
        """Estimated per-chip HBM fraction at this plan's width."""
        if not self.hbm_bytes:
            return 0.0
        return self.hbm_bytes / self.width / HBM_BYTES_PER_CHIP

    def axis_of(self, shape: Tuple[int, ...]) -> Optional[int]:
        return shard_axis(tuple(shape), self.width)

    def spec_of(self, shape: Tuple[int, ...]):
        from jax.sharding import PartitionSpec as P

        a = self.axis_of(shape)
        if a is None:
            return P()
        return P(*([None] * a + ["shard"]))

    def axes_map(self, tree: Any) -> Dict[str, Optional[int]]:
        """Flat path -> shard axis (or None) for every leaf of ``tree``
        (live arrays or ShapeDtypeStructs)."""
        import jax

        out: Dict[str, Optional[int]] = {}

        def visit(path, leaf):
            out[path_str(path)] = self.axis_of(getattr(leaf, "shape", ()))
            return leaf

        jax.tree_util.tree_map_with_path(visit, tree)
        return out

    def spec_tree(self, tree: Any):
        """A pytree of PartitionSpecs congruent to ``tree``."""
        import jax

        return jax.tree_util.tree_map_with_path(
            lambda p, leaf: self.spec_of(getattr(leaf, "shape", ())), tree)

    def shardings(self, mesh, tree: Any):
        """A pytree of NamedShardings over ``mesh`` congruent to ``tree``."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        return jax.tree.map(lambda s: NamedSharding(mesh, s),
                            self.spec_tree(tree),
                            is_leaf=lambda x: isinstance(x, P))

    def note(self) -> None:
        """Journal the plan (``shard/plan``) and publish the headroom
        gauge — the lane's day-one observability contract."""
        from rafiki_tpu import telemetry
        from rafiki_tpu.obs.journal import journal

        telemetry.set_gauge("shard.hbm_frac", self.hbm_frac())
        journal.record("shard", "plan", family=self.family,
                       width=int(self.width), hbm_bytes=int(self.hbm_bytes),
                       hbm_frac=self.hbm_frac())


def group_mesh(devices):
    """A 1-D ``("shard",)`` mesh over the group's devices."""
    import numpy as np

    from jax.sharding import Mesh

    return Mesh(np.asarray(devices), ("shard",))
