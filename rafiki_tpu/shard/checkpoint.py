"""Sharded checkpoints: per-shard chunk manifests with reshard-on-restore.

A width-w group checkpoints as **w+1 params-store blobs**:

  * ``<trial>_ckpt_<epoch>``            the JSON manifest (the head —
                                        ParamsStore.latest_checkpoint
                                        finds it like any serial ckpt)
  * ``<trial>_ckpt_<epoch>_s<t>of<w>``  shard t's slice of every
                                        sharded leaf, RTPK1-packed
                                        (utils/serial.py); shard 0
                                        additionally carries the
                                        replicated leaves (rng, step
                                        counter, hyper scalars, adam
                                        count, indivisible leaves).

Each shard writes only bytes it already holds locally (its
``addressable_shards``), so a checkpoint never materializes the full
state on one host. Through the CAS store (store/cas.py) the blobs
dedup at chunk level and a torn/missing chunk fails the load loudly,
naming the chunk.

**Reshard-on-restore**: the manifest records, per leaf, the global
shape/dtype and the axis it was sliced along at width w. A restore at
any width w' builds each leaf with ``jax.make_array_from_callback``
against the *new* mesh: the callback is handed the byte ranges the new
placement needs and assembles exactly those from the overlapping saved
slices — gather/reslice by manifest, again never the whole tree at
once. Placement at w' is recomputed from the shape-deterministic rule
in shard/plan.py, so nothing beyond the manifest has to survive the
width change.

This module is the ONE sanctioned full-gather path for group-sharded
state (RF019 ``full-gather-hazard`` flags device_get/np.asarray of
group state anywhere else): :func:`gather_state` exists for the
trial-completion hand-off — installing the final state into a serial
loop for scoring/serving — where a single-host copy is the point.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from rafiki_tpu.shard.plan import ShardPlan, path_str, shard_axis
from rafiki_tpu.utils.serial import _np_dtype, dump_pytree, load_pytree

MANIFEST_FORMAT = "shard-manifest-v1"


def _flat_state(state: Any) -> Dict[str, Any]:
    """Flat ``path -> leaf`` view of a train-state pytree, with paths
    matching the RTPK1/flatten_dict convention."""
    import jax

    out: Dict[str, Any] = {}

    def visit(path, leaf):
        out[path_str(path)] = leaf
        return leaf

    jax.tree_util.tree_map_with_path(visit, state)
    return out


def _shard_ids(trial_id: str, epoch: int, width: int) -> List[str]:
    return [f"{trial_id}_ckpt_{epoch}_s{t}of{width}" for t in range(width)]


def _local_block(leaf: Any, axis: int, t: int, width: int) -> np.ndarray:
    """Shard t's slice of ``leaf`` along ``axis``, read from local shard
    data when the leaf is a sharded jax.Array (no cross-host gather)."""
    blk = leaf.shape[axis] // width
    lo, hi = t * blk, (t + 1) * blk
    for s in getattr(leaf, "addressable_shards", ()):
        idx = s.index
        sl = idx[axis] if len(idx) > axis else slice(None)
        start = sl.start if sl.start is not None else 0
        stop = sl.stop if sl.stop is not None else leaf.shape[axis]
        if start <= lo and hi <= stop:
            arr = np.asarray(s.data)
            sel = [slice(None)] * arr.ndim
            sel[axis] = slice(lo - start, hi - start)
            return np.ascontiguousarray(arr[tuple(sel)])
    arr = np.asarray(leaf)  # replicated / host-resident leaf
    sel = [slice(None)] * arr.ndim
    sel[axis] = slice(lo, hi)
    return np.ascontiguousarray(arr[tuple(sel)])


def save_sharded(store, trial_id: str, epoch: int, state: Any, width: int,
                 extra: Optional[Dict[str, Any]] = None) -> str:
    """Write a width-``width`` sharded checkpoint; returns the manifest
    params id (also the trial's checkpoint head for this epoch)."""
    from flax.traverse_util import unflatten_dict

    flat = _flat_state(state)
    spec = []
    per_shard: List[Dict[str, np.ndarray]] = [dict() for _ in range(width)]
    for k in sorted(flat):
        leaf = flat[k]
        shape = tuple(int(d) for d in getattr(leaf, "shape", ()))
        axis = shard_axis(shape, width)
        dtype = np.dtype(getattr(leaf, "dtype", np.float32)).name
        spec.append({"k": k, "shape": list(shape), "dtype": dtype,
                     "axis": axis})
        if axis is None:
            per_shard[0][k] = np.asarray(leaf)
        else:
            for t in range(width):
                per_shard[t][k] = _local_block(leaf, axis, t, width)
    shard_ids = _shard_ids(trial_id, epoch, width)
    for t, sid in enumerate(shard_ids):
        blob = dump_pytree(unflatten_dict(per_shard[t], sep="/"),
                           cast_f32_to_bf16=False)
        store.save(blob, params_id=sid)
    manifest = {"format": MANIFEST_FORMAT, "trial": trial_id,
                "width": int(width), "epoch": int(epoch), "spec": spec,
                "shards": shard_ids, "extra": extra or {}}
    return store.save_checkpoint(trial_id, epoch,
                                 json.dumps(manifest).encode())


def is_manifest(blob: bytes) -> bool:
    head = blob[:256]
    return head.lstrip()[:1] == b"{" and MANIFEST_FORMAT.encode() in head


def load_manifest(blob: bytes) -> Dict[str, Any]:
    try:
        manifest = json.loads(blob.decode())
    except Exception as exc:
        raise IOError(f"sharded checkpoint manifest unreadable: {exc}")
    if manifest.get("format") != MANIFEST_FORMAT:
        raise IOError("sharded checkpoint manifest has wrong format "
                      f"{manifest.get('format')!r} (want {MANIFEST_FORMAT})")
    if len(manifest.get("shards", [])) != int(manifest.get("width", -1)):
        raise IOError(
            "sharded checkpoint manifest is inconsistent: width="
            f"{manifest.get('width')} but {len(manifest.get('shards', []))} "
            "shard chunks listed — refusing a wrong-width restore")
    return manifest


class _ShardReader:
    """Lazy per-shard chunk loader with slice-shape validation: each
    chunk is fetched once (CAS integrity errors propagate, naming the
    chunk) and every sharded leaf in it must be exactly a
    1/width-of-global slice — a chunk doctored in from a different
    width fails here, naming the chunk and leaf."""

    def __init__(self, store, manifest: Dict[str, Any]):
        self._store = store
        self._man = manifest
        self._spec = {e["k"]: e for e in manifest["spec"]}
        self._cache: Dict[int, Dict[str, np.ndarray]] = {}

    def spec(self, key: str) -> Dict[str, Any]:
        return self._spec[key]

    def _load(self, t: int) -> Dict[str, np.ndarray]:
        if t in self._cache:
            return self._cache[t]
        sid = self._man["shards"][t]
        try:
            blob = self._store.load(sid)
        except (IOError, OSError, FileNotFoundError) as exc:
            raise IOError(f"sharded restore failed on shard chunk {sid}: "
                          f"{exc}")
        from flax.traverse_util import flatten_dict

        flat = flatten_dict(load_pytree(blob), sep="/")
        width = int(self._man["width"])
        for k, arr in flat.items():
            ent = self._spec.get(k)
            if ent is None:
                raise IOError(f"shard chunk {sid} carries unknown leaf "
                              f"{k!r} — manifest/chunk mismatch")
            axis = ent["axis"]
            want = list(ent["shape"])
            if axis is not None:
                want[axis] = want[axis] // width
            if list(arr.shape) != want:
                raise IOError(
                    f"shard chunk {sid} has a wrong-width slice for "
                    f"{k!r}: got {list(arr.shape)}, manifest (width="
                    f"{width}) expects {want}")
        self._cache[t] = flat
        return flat

    def leaf_range(self, key: str, lo: int, hi: int) -> np.ndarray:
        """The saved leaf restricted to [lo, hi) along its saved axis
        (full extent on other axes), assembled from exactly the chunks
        that overlap the range."""
        ent = self._spec[key]
        axis = ent["axis"]
        width = int(self._man["width"])
        if axis is None:
            arr = self._load(0)[key]
            return arr
        blk = ent["shape"][axis] // width
        parts = []
        for t in range(width):
            s_lo, s_hi = t * blk, (t + 1) * blk
            if s_hi <= lo or s_lo >= hi:
                continue
            arr = self._load(t)[key]
            sel = [slice(None)] * arr.ndim
            sel[axis] = slice(max(lo, s_lo) - s_lo, min(hi, s_hi) - s_lo)
            parts.append(arr[tuple(sel)])
        if not parts:
            raise IOError(f"sharded restore: no chunk covers "
                          f"[{lo}, {hi}) of leaf {key!r}")
        return parts[0] if len(parts) == 1 else np.concatenate(parts,
                                                               axis=axis)


def restore_sharded(store, manifest_blob: bytes, template_state: Any,
                    mesh, plan: ShardPlan) -> Any:
    """Restore a sharded checkpoint onto ``mesh`` at ``plan.width``
    (any width — the reshard), returning a state pytree congruent to
    ``template_state`` with every leaf already under its group
    NamedSharding. Each device's callback pulls only the saved slices
    overlapping its new index."""
    import jax

    from rafiki_tpu import telemetry
    from rafiki_tpu.obs.journal import journal

    manifest = load_manifest(manifest_blob)
    reader = _ShardReader(store, manifest)
    flat_tmpl = _flat_state(template_state)
    saved_keys = set(reader._spec)
    if set(flat_tmpl) != saved_keys:
        missing = sorted(set(flat_tmpl) - saved_keys)[:3]
        extra = sorted(saved_keys - set(flat_tmpl))[:3]
        raise IOError("sharded checkpoint does not match the trial's "
                      f"state tree (missing={missing}, extra={extra})")
    shardings = plan.shardings(mesh, template_state)
    flat_shardings = _flat_state(shardings)

    restored: Dict[str, Any] = {}
    for k in sorted(flat_tmpl):
        ent = reader.spec(k)
        shape = tuple(ent["shape"])
        dtype = _np_dtype(ent["dtype"])
        saved_axis = ent["axis"]
        sharding = flat_shardings[k]

        def cb(index, _k=k, _shape=shape, _dtype=dtype, _axis=saved_axis):
            if _axis is None:
                # replicated at save time; the new placement may still
                # slice it, so honor the requested index as-is.
                arr = reader.leaf_range(_k, 0, 1)
                arr = arr[tuple(index)] if len(index) else arr
            else:
                sl = index[_axis] if len(index) > _axis else slice(None)
                lo = sl.start if sl.start is not None else 0
                hi = sl.stop if sl.stop is not None else _shape[_axis]
                arr = reader.leaf_range(_k, lo, hi)
                # the gathered block already spans [lo, hi) on _axis;
                # apply the remaining dims of the requested index.
                rest = [index[d] if d != _axis else slice(None)
                        for d in range(len(index))]
                arr = arr[tuple(rest)] if rest else arr
            arr = np.asarray(arr, dtype=_dtype)
            if not _shape:
                # plain asarray here: ascontiguousarray promotes 0-d
                # to (1,) on numpy<2 and jax rejects the shard shape.
                return arr.reshape(())
            return np.ascontiguousarray(arr)

        restored[k] = jax.make_array_from_callback(shape, sharding, cb)

    # Rebuild on the template's own structure (leafless containers —
    # e.g. an empty hyper dict — survive; from_state_dict would not
    # round-trip them through a tuple state).
    state = jax.tree_util.tree_map_with_path(
        lambda p, _leaf: restored[path_str(p)], template_state)
    telemetry.inc("shard.reshard_restores")
    journal.record("shard", "reshard",
                   trial_id=str(manifest.get("trial") or ""),
                   from_width=int(manifest["width"]),
                   to_width=int(plan.width), epoch=int(manifest["epoch"]))
    return state


def gather_state(state: Any) -> Any:
    """Host copy of a (possibly group-sharded) train state — the ONE
    sanctioned full gather (trial completion: install into a serial
    loop for scoring/serving, or build the final ``dump_parameters``
    blob). Leaf-at-a-time, so peak host memory is one leaf over the
    state's own footprint."""
    import jax

    return jax.tree.map(lambda leaf: np.asarray(jax.device_get(leaf)), state)
