"""Sharded-trial lane: one big model FSDP-sharded across a chip group.

The sweep plane packs many small trials per chip (ops/train.py's
packed lane); this package is the inverse lane for models whose train
state outgrows one chip's HBM:

* :mod:`rafiki_tpu.shard.plan` — :class:`ShardPlan`: param pytree +
  HBM estimate -> smallest group width under the ceiling + per-leaf
  ``NamedSharding``s over a ``("shard",)`` axis.
* :mod:`rafiki_tpu.shard.loop` — :class:`ShardedTrainLoop` /
  :func:`train_sharded`: the group-wide epoch loop, bit-identical to
  the serial loop at every width.
* :mod:`rafiki_tpu.shard.checkpoint` — per-shard chunk manifests with
  **reshard-on-restore**: a width-w checkpoint restores at any width
  w', which is how a group that loses a chip resumes on its survivors
  (scheduler/mesh.py's GroupHandle; docs/sharding.md).
"""

from rafiki_tpu.shard.checkpoint import (gather_state, is_manifest,
                                         load_manifest, restore_sharded,
                                         save_sharded)
from rafiki_tpu.shard.loop import (GroupAborted, ShardedTrainLoop,
                                   sharded_program_key, train_sharded)
from rafiki_tpu.shard.plan import (ShardPlan, group_mesh, shard_axis,
                                   solve_width)

__all__ = [
    "GroupAborted",
    "ShardPlan",
    "ShardedTrainLoop",
    "gather_state",
    "group_mesh",
    "is_manifest",
    "load_manifest",
    "restore_sharded",
    "save_sharded",
    "shard_axis",
    "sharded_program_key",
    "solve_width",
    "train_sharded",
]
