"""ShardedTrainLoop: one trial's state FSDP-sharded across a chip group.

Mirrors ops/train.py's jitted/donated epoch contract — same step
closures (``_make_step_fns``), same scan body, same rng chain and
shuffle derivation, same chaos/poison column — but the train state
lives under group-wide ``NamedSharding`` from a :class:`ShardPlan`,
so a model whose params + optimizer state exceed one chip's HBM
trains by borrowing the group's aggregate capacity.

Execution model (and why it is bit-exact): each epoch is ONE
``shard_map`` over the ``("shard",)`` mesh. Every member all-gathers
the sharded leaves to full tensors, runs the *identical* per-trial
scan the serial Program runs (data movement only — gathers reorder no
arithmetic), then re-slices its own 1/width of the updated state.
Compute is intentionally replicated (ZeRO-3 with a replicated batch):
the lane exists for HBM capacity, not step-time scaling, and the
redundancy buys the property everything downstream leans on — a
width-w epoch is **bit-identical** to width-w' and to the serial loop
(pinned by tests/test_shard.py, and what lets chip-loss recovery at
reduced width match an unfaulted run exactly). A dp mesh still
composes per-member for real batch scaling; that is the documented
follow-on (docs/sharding.md).

State placement never materializes the full tree on one host: init is
jitted with sharded ``out_shardings`` (each member initializes its
slice), restores arrive pre-sharded from shard/checkpoint.py, and the
one sanctioned gather (trial completion) lives there too.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Hashable, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
import optax

from rafiki_tpu import telemetry
from rafiki_tpu.obs.health import sentinel as _sentinel
from rafiki_tpu.ops.train import (_make_step_fns, device_dataset_cap_bytes,
                                  get_program, mesh_cache_key)
from rafiki_tpu.shard.plan import ShardPlan, group_mesh, path_str

try:  # jax>=0.6 spells it jax.shard_map and renames check_rep
    from jax import shard_map  # type: ignore[attr-defined]

    _SHARD_MAP_KW = {"check_vma": False}
except ImportError:  # pragma: no cover - version-dependent import
    from jax.experimental.shard_map import shard_map

    _SHARD_MAP_KW = {"check_rep": False}


class GroupAborted(RuntimeError):
    """A group member was lost; the epoch loop stopped at the epoch
    boundary AFTER that epoch's checkpoint went durable. ``epoch`` is
    the last completed (and checkpointed) epoch — resume restores it
    and continues at ``epoch + 1``, at whatever width survives."""

    def __init__(self, epoch: int):
        super().__init__(f"sharded trial aborted after epoch {epoch}")
        self.epoch = int(epoch)


def sharded_program_key(program_key: Hashable, width: int,
                        dynamic_lr: bool) -> Hashable:
    """Cache key for a group-sharded program. The leading tag keeps the
    namespace disjoint from serial keys and ``("packed", ...)`` keys by
    construction (same pattern as ops.train.packed_program_key)."""
    return ("sharded", int(width), program_key, bool(dynamic_lr))


class _ShardedProgram:
    """The compiled, trial-independent half of a sharded loop: jit'd
    (donated) epoch/eval/init callables plus the per-leaf sharding
    tables. Cached process-wide via ops.train.get_program under a
    ``("sharded", ...)`` key, like any Program."""

    def __init__(self, init_fn, apply_fn, loss_fn,
                 optimizer: optax.GradientTransformation, mesh,
                 plan: ShardPlan, dynamic_lr: bool,
                 hyper_keys: Tuple[str, ...]):
        from jax.sharding import NamedSharding, PartitionSpec as P

        self.mesh = mesh
        self.plan = plan
        self.optimizer = optimizer
        width = int(mesh.devices.size)
        self.width = width
        train_step, eval_step, predict, init_all = _make_step_fns(
            init_fn, apply_fn, loss_fn, optimizer, dynamic_lr)

        def make_state(init_rng, rng, hyper_dev):
            params, opt_state = init_all(init_rng)
            return (params, opt_state, jnp.zeros((), jnp.int32), rng,
                    hyper_dev)

        probe_rng = jax.random.PRNGKey(0)
        probe_hyper = {k: jnp.float32(0.0) for k in hyper_keys}
        abs_state = jax.eval_shape(make_state, probe_rng, probe_rng,
                                   probe_hyper)
        axes = plan.axes_map(abs_state)
        spec_state = plan.spec_tree(abs_state)
        self.state_sharding = jax.tree.map(
            lambda s: NamedSharding(mesh, s), spec_state,
            is_leaf=lambda x: isinstance(x, P))
        self.replicated = NamedSharding(mesh, P())

        def gather(local):
            def g(path, x):
                a = axes.get(path_str(path))
                if a is None:
                    return x
                return jax.lax.all_gather(x, "shard", axis=a, tiled=True)

            return jax.tree_util.tree_map_with_path(g, local)

        def reslice(full):
            i = jax.lax.axis_index("shard")

            def s(path, x):
                a = axes.get(path_str(path))
                if a is None:
                    return x
                size = x.shape[a] // width
                return jax.lax.dynamic_slice_in_dim(x, i * size, size, axis=a)

            return jax.tree_util.tree_map_with_path(s, full)

        # Per-member epoch body: gather -> the EXACT serial scan
        # (ops.train.Program.train_epoch's body) -> reslice. X/Y/idx/
        # poison are replicated (in_specs P()), so every member runs
        # the full serial computation — see the module docstring for
        # why that redundancy is the point.
        def train_epoch(state, X, Y, idx, poison):
            full = gather(state)

            def body(st, xs):
                ib, pz = xs
                batch = {"x": jnp.take(X, ib, axis=0),
                         "y": jnp.take(Y, ib, axis=0)}
                if pz is not None:
                    batch["_health_poison"] = pz
                return train_step(st, batch)

            full, ms = jax.lax.scan(body, full, (idx, poison))
            rest, health = _sentinel.split(ms)
            out = {k: v[-1] for k, v in rest.items()}
            out.update(_sentinel.reduce_epoch(health))
            return reslice(full), out

        def eval_epoch(state, X, Y, idx):
            params = gather(state)[0]

            def body(carry, ib):
                batch = {"x": jnp.take(X, ib, axis=0),
                         "y": jnp.take(Y, ib, axis=0)}
                c, n = eval_step(params, batch)
                return (carry[0] + c, carry[1] + n), None

            zero = jnp.zeros((), jnp.int32)
            (c, n), _ = jax.lax.scan(body, (zero, zero), idx)
            return c, n

        P0 = P()
        self.train_epoch = jax.jit(
            shard_map(train_epoch, mesh=mesh,
                      in_specs=(spec_state, P0, P0, P0, P0),
                      out_specs=(spec_state, P0), **_SHARD_MAP_KW),
            donate_argnums=(0,))
        self.eval_epoch = jax.jit(
            shard_map(eval_epoch, mesh=mesh,
                      in_specs=(spec_state, P0, P0, P0),
                      out_specs=(P0, P0), **_SHARD_MAP_KW))
        self.init = jax.jit(make_state, out_shardings=self.state_sharding)


class ShardedTrainLoop:
    """Drives epochs of one group-sharded trial.

    Same constructor contract as ops.train.TrainLoop where it applies;
    differences: ``devices`` (the group members, their count is the
    width) replaces ``mesh``, a :class:`ShardPlan` pins the placement,
    and ``packing_key`` (the repr of the scheduler's ``("sharded",
    family, width)`` bucket key) rides the perf records so the train
    twin can calibrate group samples separately.
    """

    def __init__(self, init_fn, apply_fn, loss_fn, optimizer=None,
                 devices=None, seed: int = 0,
                 hyper: Optional[Dict[str, float]] = None,
                 program_key: Optional[Hashable] = None,
                 plan: Optional[ShardPlan] = None,
                 packing_key: Optional[str] = None,
                 initial_state=None):
        if not devices:
            raise ValueError("ShardedTrainLoop needs the group's devices")
        self.devices = list(devices)
        self.width = len(self.devices)
        self.mesh = group_mesh(self.devices)
        self.plan = plan if plan is not None else ShardPlan(width=self.width)
        if self.plan.width != self.width:
            raise ValueError(f"plan width {self.plan.width} != group width "
                             f"{self.width}")
        self.packing_key = packing_key
        dynamic_lr = hyper is not None and "lr" in hyper
        if optimizer is None:
            optimizer = optax.scale_by_adam() if dynamic_lr else optax.adam(1e-3)
        hyper_keys = tuple(sorted(hyper or {}))

        def build() -> _ShardedProgram:
            return _ShardedProgram(init_fn, apply_fn, loss_fn, optimizer,
                                   self.mesh, self.plan, dynamic_lr,
                                   hyper_keys)

        if program_key is not None:
            self._perf_key = (sharded_program_key(program_key, self.width,
                                                  dynamic_lr),
                              mesh_cache_key(self.mesh))
            self.program = get_program(self._perf_key, build)
        else:
            self._perf_key = ("sharded", "anon", id(self))
            self.program = build()
        self.optimizer = self.program.optimizer

        if initial_state is not None:
            self.adopt(initial_state)
            return
        hyper_dev = {k: jnp.float32(v) for k, v in (hyper or {}).items()}
        rng = jax.random.PRNGKey(seed)
        rng, init_rng = jax.random.split(rng)
        self.state = self.program.init(init_rng, rng, hyper_dev)

    @property
    def params(self):
        return self.state[0]

    def adopt(self, state) -> None:
        """Adopt a full state (a reshard-restore's output, or host
        arrays) — re-placed under the group shardings if needed."""
        self.state = jax.device_put(state, self.program.state_sharding)

    def _device_dataset(self, dataset):
        """(x, y) replicated across the group, cached per mesh on the
        dataset object (same idiom as ops.train.get_device_dataset)."""
        cache = dataset.__dict__.setdefault("_shard_device_arrays", {})
        key = mesh_cache_key(self.mesh)
        if key not in cache:
            cache[key] = (
                jax.device_put(np.asarray(dataset.x), self.program.replicated),
                jax.device_put(np.asarray(dataset.y), self.program.replicated))
        return cache[key]

    def _check_dataset(self, dataset, batch_size: int) -> None:
        if dataset.size < batch_size:
            raise ValueError(
                f"Dataset has {dataset.size} examples < batch_size="
                f"{batch_size}; the epoch would run zero steps")
        if getattr(dataset, "mask", None) is not None:
            raise NotImplementedError(
                "sharded loop runs the device-resident scan path only; "
                "masked (corpus) datasets are not supported")
        if dataset.x.nbytes + dataset.y.nbytes > device_dataset_cap_bytes():
            raise NotImplementedError(
                "sharded loop requires a device-resident dataset "
                "(RAFIKI_DEVICE_DATASET_MAX_MB)")

    def run_epoch(self, dataset, batch_size: int,
                  epoch_seed: int) -> Dict[str, float]:
        """One epoch over the group. Same shuffle derivation, poison
        column and metric shape as the serial fast path — the bit-parity
        contract."""
        self._check_dataset(dataset, batch_size)
        import os as _os

        from rafiki_tpu import chaos as _chaos

        # Collective chaos site, same keying as the dp path: a kill
        # lands while the group is inside (or entering) its gathers.
        _chaos.hook("collective.step",
                    key=f"p{jax.process_index()}:"
                        f"{_os.environ.get('RAFIKI_WORKER_ID', '')}")
        t_epoch = time.monotonic()
        _chaos.hook("train.epoch", key=str(self._perf_key))
        n_steps = dataset.size // batch_size
        poison = self._chaos_poison(n_steps)
        X, Y = self._device_dataset(dataset)
        perm = np.random.default_rng(epoch_seed).permutation(dataset.size)
        idx = perm[: n_steps * batch_size].reshape(
            n_steps, batch_size).astype(np.int32)
        if not getattr(self, "_warm", False):
            from rafiki_tpu.obs.perf import profiler as _profiler

            _profiler.capture_cost(self._perf_key, self.program.train_epoch,
                                   self.state, X, Y, idx, poison,
                                   kind="sharded")
        self.state, metrics = self.program.train_epoch(
            self.state, X, Y, idx, poison)
        out = {k: float(v) for k, v in metrics.items()
               if not k.startswith(_sentinel.PREFIX)}
        self._record_epoch(t_epoch)
        return out

    def _chaos_poison(self, n_steps: int) -> np.ndarray:
        from rafiki_tpu import chaos as _chaos

        poison = np.ones(n_steps, np.float32)
        if (_chaos.active() is not None
                and _chaos.hook("train.nan",
                                key=str(self._perf_key)) is not None):
            poison[n_steps // 2] = np.nan
        return poison

    def _record_epoch(self, t0: float) -> None:
        from rafiki_tpu.obs.ledger import ledger
        from rafiki_tpu.obs.perf import profiler, slo

        # lint: disable=RF007 — epoch wall split into ledger buckets
        dt = time.monotonic() - t0
        cold = not getattr(self, "_warm", False)
        self._warm = True
        telemetry.observe("train.cold_epoch_s" if cold else "train.epoch_s",
                          dt)
        telemetry.inc("train.step_s", dt)
        telemetry.set_gauge("shard.group_width", self.width)
        ledger.add("compile_s" if cold else "step_s", dt)
        profiler.note_epoch(self._perf_key, dt, cold=cold, kind="sharded",
                            packing_key=self.packing_key,
                            group_width=self.width)
        slo.maybe_tick()

    def evaluate(self, dataset, batch_size: int) -> float:
        """Full-batch accuracy over the group (the remainder rows are
        dropped — exact scoring goes through the detached serial loop
        installed at trial completion)."""
        self._check_dataset(dataset, batch_size)
        X, Y = self._device_dataset(dataset)
        n_steps = dataset.size // batch_size
        idx = np.arange(n_steps * batch_size, dtype=np.int32).reshape(
            n_steps, batch_size)
        c, n = self.program.eval_epoch(self.state, X, Y, idx)
        return int(c) / max(int(n), 1)


def train_sharded(model, dataset_uri: str, devices,
                  plan: Optional[ShardPlan] = None,
                  checkpoint_sink=None, abort=None,
                  resume_from=None) -> Tuple["ShardedTrainLoop",
                                             List[Dict[str, float]]]:
    """Train one JaxModel template as a group-sharded trial — the
    sharded-lane analog of ``JaxModel.train``.

    * ``checkpoint_sink(epoch, loop)`` fires after every epoch with the
      live loop; the sink decides cadence and calls
      ``shard.checkpoint.save_sharded(store, trial_id, epoch,
      loop.state, loop.width)`` itself (the sharded analog of the
      serial ``_ckpt_sink(epoch, dump_checkpoint)`` contract).
    * ``abort`` (threading.Event) is checked at each epoch boundary
      AFTER the sink ran — a set flag raises :class:`GroupAborted`
      with the last durable epoch, the group-loss ordering contract.
    * ``resume_from=(params_store, trial_id)`` restores the newest
      sharded checkpoint at THIS group's width via reshard-on-restore
      and continues after its epoch.

    On completion the model gets a detached serial TrainLoop holding
    the gathered final state, so ``evaluate``/``dump_parameters``/
    ``predict`` behave exactly as after a serial ``train()``. Returns
    ``(loop, per-epoch metrics history)``.
    """
    from rafiki_tpu.model.log import logger
    from rafiki_tpu.shard import checkpoint as shard_ckpt

    ds = model._prepared_dataset(dataset_uri)
    model._dataset_meta = dict(ds.meta)
    num_classes, input_shape = model._dataset_arch(ds)
    model._planned_steps = model.epochs * max(1, ds.size // model.batch_size)
    fns = model._loop_fns(num_classes, input_shape)
    model._module = fns["module"]
    model._arch = (num_classes, tuple(input_shape))
    if plan is None:
        plan = ShardPlan(width=len(devices), family=type(model).__name__)
    pk_repr = repr(("sharded", type(model).__name__, plan.width))
    loop = ShardedTrainLoop(
        fns["init_fn"], fns["apply_eval"], fns["loss_fn"], fns["optimizer"],
        devices=devices, seed=model._seed, hyper=fns["hyper"],
        program_key=fns["program_key"], plan=plan, packing_key=pk_repr)

    start_epoch = 0
    if resume_from is not None:
        store, trial_id = resume_from
        latest = store.latest_checkpoint(trial_id)
        if latest is not None and shard_ckpt.is_manifest(latest[1]):
            state = shard_ckpt.restore_sharded(store, latest[1], loop.state,
                                               loop.mesh, plan)
            loop.adopt(state)
            start_epoch = int(latest[0]) + 1

    history: List[Dict[str, float]] = []
    logger.define_plot("Training", ["loss", "acc"], x_axis="epoch")
    for epoch in range(start_epoch, model.epochs):
        metrics = loop.run_epoch(ds, model.batch_size,
                                 epoch_seed=model._seed + epoch)
        logger.log(epoch=epoch, **metrics)
        history.append(dict(metrics, epoch=epoch))
        model._epochs_done = epoch
        if checkpoint_sink is not None:
            checkpoint_sink(epoch, loop)
        if abort is not None and abort.is_set():
            raise GroupAborted(epoch)
    # Completion hand-off: the ONE sanctioned gather — install the
    # final state into a serial loop so scoring/serving run unchanged.
    from rafiki_tpu.ops.train import TrainLoop

    host_state = shard_ckpt.gather_state(loop.state)
    model._loop = TrainLoop(
        fns["init_fn"], fns["apply_eval"], fns["loss_fn"], fns["optimizer"],
        mesh=None, seed=model._seed, hyper=fns["hyper"],
        program_key=fns["program_key"], initial_state=host_state)
    return loop, history
