"""Admission control: a bounded inflight budget + bounded wait queue.

The predict path used to accept unlimited concurrent requests — under
saturating offered load every request queued forever and ALL of them
blew their deadline. Admission control inverts that: at most
``max_inflight`` requests execute at once, at most ``max_queue`` wait
for a slot, and a waiter that cannot possibly get a slot before its
deadline is shed immediately. Shed requests surface as HTTP 429 with a
``Retry-After`` hint, so well-behaved clients back off instead of
retry-storming a saturated predictor.
"""

from __future__ import annotations

import threading
import time
from typing import Optional


class ShedError(RuntimeError):
    """Request refused by admission control (or a draining gateway)."""

    def __init__(self, reason: str, retry_after_s: float = 1.0):
        super().__init__(f"request shed: {reason}")
        self.reason = reason
        self.retry_after_s = retry_after_s


class AdmissionController:
    """Counting semaphore with a bounded, deadline-aware wait queue."""

    def __init__(self, max_inflight: int = 8, max_queue: int = 32):
        if max_inflight < 1:
            raise ValueError("max_inflight must be >= 1")
        self.max_inflight = max_inflight
        self.max_queue = max(0, max_queue)
        self._cv = threading.Condition()
        self._inflight = 0
        self._waiting = 0
        self._closed = False

    # -- admission -----------------------------------------------------------

    def admit(self, deadline: float, retry_after_s: float = 1.0) -> float:
        """Block until an inflight slot is free, the monotonic
        ``deadline`` passes, or the controller closes (drain). Returns
        the seconds spent waiting; raises :class:`ShedError` instead of
        admitting a request that already lost its deadline race."""
        t0 = time.monotonic()
        with self._cv:
            if self._closed:
                raise ShedError("draining", retry_after_s)
            if self._inflight < self.max_inflight and self._waiting == 0:
                self._inflight += 1
                return 0.0
            if self._waiting >= self.max_queue:
                raise ShedError("queue_full", retry_after_s)
            if time.monotonic() >= deadline:
                raise ShedError("deadline", retry_after_s)
            self._waiting += 1
            try:
                while self._inflight >= self.max_inflight:
                    if self._closed:
                        raise ShedError("draining", retry_after_s)
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise ShedError("deadline", retry_after_s)
                    self._cv.wait(remaining)
                if self._closed:  # drain raced the slot we just won
                    raise ShedError("draining", retry_after_s)
                self._inflight += 1
            finally:
                self._waiting -= 1
        return time.monotonic() - t0

    def release(self) -> None:
        with self._cv:
            self._inflight -= 1
            self._cv.notify_all()

    # -- drain ---------------------------------------------------------------

    def close(self) -> None:
        """Stop admitting: new arrivals and queued waiters shed with
        reason ``draining``; inflight requests run to completion."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def wait_idle(self, timeout: Optional[float] = None) -> bool:
        """Block until every inflight request finished (drain flush).
        Returns False if ``timeout`` elapsed first."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cv:
            while self._inflight > 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    return False
                self._cv.wait(remaining)
        return True

    # -- introspection -------------------------------------------------------

    @property
    def inflight(self) -> int:
        with self._cv:
            return self._inflight

    @property
    def waiting(self) -> int:
        with self._cv:
            return self._waiting

    @property
    def closed(self) -> bool:
        with self._cv:
            return self._closed
