"""Serving gateway: the frontend layer between the predictor HTTP app
and the bus — admission control with per-request deadlines, quorum
fan-out with hedged stragglers, per-worker circuit breakers, routing
policies, and graceful drain. See docs/serving.md.
"""

from rafiki_tpu.gateway.admission import AdmissionController, ShedError
from rafiki_tpu.gateway.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker
from rafiki_tpu.gateway.gateway import (DEADLINE_RESERVE_FRAC,
                                        LATENCY_EWMA_ALPHA, POLICIES,
                                        RETRY_AFTER_FLOOR_S, Gateway,
                                        GatewayConfig)
from rafiki_tpu.gateway.microbatch import (FLUSH_REASONS, BatchMember,
                                           MicroBatcher)

__all__ = [
    "AdmissionController", "ShedError",
    "CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN",
    "Gateway", "GatewayConfig", "POLICIES",
    "DEADLINE_RESERVE_FRAC", "LATENCY_EWMA_ALPHA", "RETRY_AFTER_FLOOR_S",
    "MicroBatcher", "BatchMember", "FLUSH_REASONS",
]
