"""Deadline-aware dynamic microbatching for the serving gateway.

The replicated fan-out path sends one bus envelope per query per
worker; on the multiprocess bus each envelope is a Manager-proxy
round-trip, so the wire tax scales with ``queries × workers`` — the
``serving.fanout_cost_s`` overhead PR 10 measures. With a stacked
(single-worker, device-resident) ensemble the forward itself is one
XLA launch, which makes the wire the dominant cost; the cure is to
coalesce admitted requests into ONE fan-out.

:class:`MicroBatcher` is that coalescer. Admitted requests (each
already holding its admission slot — the inflight budget still bounds
concurrency) enqueue their queries and block; a dedicated flusher
thread flushes a combined batch when:

* **size** — pending queries reach ``max_batch``;
* **deadline** — the oldest member has waited ``max_wait_s``, or ANY
  member's deadline minus the expected service reserve is due — a
  request's budget is never burned waiting for co-batchers;
* **drain** — the gateway is draining: flush what's pending now.

The flush executes one batched fan-out (the gateway's
``_execute_batch``) and scatters per-member slices back; each member
thread then finishes its own bookkeeping (hop-chain absorb under its
OWN trace id, rollup, journal) so waterfalls still stitch per request.

``max_batch=1`` disables batching entirely — the gateway keeps the
classic per-request fan-out and this module is never constructed.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional

#: Flush triggers — a closed enum; each maps to one literal counter in
#: the gateway (serving.microbatch.flush_*) and rides the journal.
FLUSH_REASONS = ("size", "deadline", "drain")

#: Floor on the flusher's timed wait so a mis-set max_wait can never
#: busy-spin the flush loop.
_MIN_WAIT_S = 0.0005


class BatchMember:
    """One admitted request riding a microbatch."""

    __slots__ = ("queries", "deadline", "prefix", "enq_t", "done",
                 "outputs", "chains", "error", "flush_reason", "report",
                 "elapsed_s")

    def __init__(self, queries: List[Any], deadline: float,
                 prefix: List[List[Any]], enq_t: float):
        self.queries = queries
        self.deadline = deadline          # monotonic absolute
        self.prefix = prefix              # this request's hop marks
        self.enq_t = enq_t
        self.done = threading.Event()
        self.outputs: Optional[List[Any]] = None
        self.chains = None                # worker -> full member chain
        self.error: Optional[BaseException] = None
        self.flush_reason: Optional[str] = None
        self.report = None                # shared BatchGatherReport
        self.elapsed_s = 0.0              # flush -> scatter wall

    def wait(self, timeout_s: float) -> bool:
        return self.done.wait(timeout_s)


class MicroBatcher:
    """Coalesce admitted requests into size/deadline-bounded batches.

    ``execute(members, flush_reason)`` runs in the flusher thread and
    must fill every member (outputs or error) and set its event; an
    exception it raises is fanned to all members of that batch.
    """

    def __init__(self, execute: Callable[[List[BatchMember], str], None],
                 max_batch: int, max_wait_s: float,
                 reserve_fn: Optional[Callable[[], float]] = None,
                 clock: Callable[[], float] = time.monotonic):
        if max_batch < 2:
            raise ValueError("MicroBatcher needs max_batch >= 2; "
                             "max_batch=1 means batching is off")
        self._execute = execute
        self.max_batch = max_batch
        self.max_wait_s = max(0.0, max_wait_s)
        self._reserve_fn = reserve_fn or (lambda: 0.0)
        self._clock = clock
        self._cond = threading.Condition()
        self._pending: List[BatchMember] = []
        self._closing = False
        self._stopped = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="gateway-microbatch")
        self._thread.start()

    # -- producer side -------------------------------------------------------

    def submit(self, queries: List[Any], deadline: float,
               prefix: List[List[Any]]) -> BatchMember:
        """Enqueue one admitted request; returns its member handle.
        The caller blocks on ``member.wait()`` — admission slot held."""
        m = BatchMember(list(queries), deadline, prefix, self._clock())
        with self._cond:
            if self._stopped:
                raise RuntimeError("microbatcher stopped")
            self._pending.append(m)
            self._cond.notify()
        return m

    def drain(self) -> None:
        """Flush whatever is pending immediately (reason ``drain``).
        New submits still work until :meth:`stop` — the gateway sheds
        them upstream once draining."""
        with self._cond:
            self._closing = True
            self._cond.notify()

    def stop(self) -> None:
        with self._cond:
            self._closing = True
            self._stopped = True
            self._cond.notify()
        self._thread.join(timeout=2.0)

    @property
    def pending(self) -> int:
        with self._cond:
            return len(self._pending)

    # -- flusher -------------------------------------------------------------

    def _flush_due(self, now: float) -> Optional[str]:
        """The reason to flush NOW, or None to keep waiting."""
        if sum(len(m.queries) for m in self._pending) >= self.max_batch:
            return "size"
        if self._closing:
            return "drain"
        if now >= self._flush_at():
            return "deadline"
        return None

    def _flush_at(self) -> float:
        """When the pending batch must flush: the oldest member's
        max-wait expiry, capped by every member's deadline minus the
        expected service reserve — waiting never burns a budget the
        fan-out itself needs."""
        reserve = self._reserve_fn()
        t = min(m.enq_t for m in self._pending) + self.max_wait_s
        for m in self._pending:
            t = min(t, m.deadline - reserve)
        return t

    def _take(self) -> List[BatchMember]:
        """FIFO members up to ``max_batch`` queries (always >= 1 member
        — one oversized request still ships alone). Caller (the flusher
        loop) holds ``self._cond``."""
        batch: List[BatchMember] = []
        n = 0
        while self._pending:
            m = self._pending[0]
            if batch and n + len(m.queries) > self.max_batch:
                break
            # lint: disable=RF004 — sole caller holds self._cond
            batch.append(self._pending.pop(0))
            n += len(m.queries)
        return batch

    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._pending:
                    if self._stopped:
                        return
                    self._cond.wait(0.1)
                now = self._clock()
                reason = self._flush_due(now)
                if reason is None:
                    self._cond.wait(max(_MIN_WAIT_S, self._flush_at() - now))
                    continue
                batch = self._take()
            try:
                self._execute(batch, reason)
            except BaseException as e:  # noqa: BLE001 — fanned to members
                for m in batch:
                    if not m.done.is_set():
                        m.error = e
                        m.done.set()
                if not isinstance(e, Exception):
                    raise  # interrupts propagate after members unblock
