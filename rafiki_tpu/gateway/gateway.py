"""The serving gateway: admission control, deadline-aware routing and
quorum fan-out between the HTTP frontend and the bus.

The predict path used to be ``PredictorApp → Predictor.predict``
directly: unbounded concurrency, wait-for-all gathers, and fan-out to
every registered worker until its lease expired. The gateway is the
layer TPU serving stacks treat as table stakes:

  * **admission control** — bounded inflight budget + bounded wait
    queue with per-request deadlines; overflow is shed *immediately*
    (HTTP 429 + Retry-After upstream) instead of queuing forever;
  * **deadline-aware quorum gather** — fan out, wait for
    ``min_replies`` (default ceil(k/2)), grant stragglers a short
    hedge grace, ensemble what arrived: p99 tracks the median replica;
  * **per-worker circuit breakers** — consecutive zero-reply batches
    open a worker's breaker and it stops receiving fan-out *before*
    its heartbeat lease expires;
  * **routing policies** — ``replicate-all`` (ensemble, the default)
    or ``least-loaded`` (single replica by bus queue depth, for
    throughput-mode jobs);
  * **graceful drain** — stop admitting, flush inflight, flip
    ``/healthz`` to draining.

One Gateway fronts one inference job's Predictor. All counters flow
through both gateway-local stats (``GET /gateway``) and the global
telemetry registry (``GET /metrics``), registered as the ``gateway``
collector so breaker state shows up in every snapshot.
"""

from __future__ import annotations

import dataclasses
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import threading

from rafiki_tpu import chaos, telemetry
from rafiki_tpu.gateway.admission import AdmissionController, ShedError
from rafiki_tpu.gateway.breaker import CircuitBreaker
from rafiki_tpu.gateway.microbatch import BatchMember, MicroBatcher
from rafiki_tpu.obs import context as trace_context
from rafiki_tpu.obs.anatomy import hops as _hops
from rafiki_tpu.obs.anatomy.timeseries import ServingRollup
from rafiki_tpu.obs.journal import journal as _journal
from rafiki_tpu.predictor.predictor import default_quorum
from rafiki_tpu.tenancy.qos import ANON_TENANT

POLICIES = ("replicate-all", "least-loaded")

# Queueing constants the digital twin (rafiki_tpu/obs/twin/) mirrors.
# Exported module-level — NOT inlined below — so the simulator imports
# the live values and a tuning change here moves the twin's admission
# model in the same commit (docs/twin.md).
#: Fraction of a request's deadline the admission queue may consume
#: before the expected service time no longer fits (shed-early rule).
DEADLINE_RESERVE_FRAC = 0.5
#: Smoothing weight of the newest sample in the gateway latency EWMA.
LATENCY_EWMA_ALPHA = 0.2
#: Minimum Retry-After hint — clients must never busy-spin.
RETRY_AFTER_FLOOR_S = 0.1
#: Blackout-retry probe: when a gather returns ZERO replies from EVERY
#: worker (a dead fan-out set — e.g. a SIGKILLed stacked worker) and
#: retries remain, the next attempt's gather budget is clamped to
#: ``max(MIN, FACTOR × latency EWMA)`` instead of the full deadline, so
#: the request re-routes within its own budget instead of burning it
#: all waiting on a corpse. Only engages once an EWMA exists — with no
#: latency model there is no basis to declare blackout early. The 1s
#: floor keeps the probe a DEATH detector, not a straggler detector:
#: merely-slow forwards (latency spikes the hedge/breaker machinery
#: owns) must finish inside the probe, or their retry wait would smear
#: the tail out of the forward hop and corrupt attribution.
BLACKOUT_PROBE_FACTOR = 8.0
BLACKOUT_PROBE_MIN_S = 1.0
#: Pause between blackout attempts: long enough for a stale lease to
#: age out of the fan-out set / a fallback worker to register.
BLACKOUT_BACKOFF_S = 0.2


@dataclasses.dataclass
class GatewayConfig:
    max_inflight: int = 8           # concurrent predict batches
    max_queue: int = 32             # waiters beyond the inflight budget
    default_deadline_s: Optional[float] = None  # None → predictor.timeout_s
    min_replies: Optional[int] = None  # gather quorum; None → ceil(k/2)
    hedge_grace_s: float = 0.25     # straggler grace once quorum arrived
    policy: str = "replicate-all"
    breaker_failures: int = 3       # consecutive misses before opening
    breaker_cooldown_s: float = 5.0
    max_queries_per_request: int = 1024  # HTTP app: 413 above this
    # Dynamic microbatching (docs/serving.md): >1 coalesces admitted
    # requests into one bus fan-out of up to max_batch queries, flushed
    # after at most max_batch_wait_ms (or sooner when a member deadline
    # demands it). 1 = off: classic per-request fan-out.
    max_batch: int = 1
    max_batch_wait_ms: float = 5.0
    # Bounded re-route attempts when a gather comes back with ZERO
    # replies from every worker (dead fan-out set — the stacked-worker
    # loss case). 0 = single attempt, pre-microbatching behaviour.
    blackout_retries: int = 3

    def __post_init__(self):
        if self.policy not in POLICIES:
            raise ValueError(
                f"unknown routing policy {self.policy!r}; one of {POLICIES}")
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {self.max_batch}")

    @classmethod
    def from_config(cls, cfg, **overrides) -> "GatewayConfig":
        """Build from the framework Config (rafiki_tpu/config.py),
        with per-job overrides on top (services manager plumbing)."""
        base = dict(
            max_inflight=cfg.gateway_max_inflight,
            max_queue=cfg.gateway_max_queue,
            default_deadline_s=cfg.predict_timeout_s,
            hedge_grace_s=cfg.gateway_hedge_grace_s,
            policy=cfg.gateway_policy,
            breaker_failures=cfg.gateway_breaker_failures,
            breaker_cooldown_s=cfg.gateway_breaker_cooldown_s,
            max_queries_per_request=cfg.max_queries_per_request,
            max_batch=cfg.gateway_max_batch,
            max_batch_wait_ms=cfg.gateway_max_batch_wait_ms,
        )
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(overrides) - known
        if unknown:
            raise ValueError(f"unknown gateway config keys: {sorted(unknown)}")
        base.update(overrides)
        return cls(**base)


class Gateway:
    """Serving frontend for one inference job's predictor."""

    def __init__(self, predictor, config: Optional[GatewayConfig] = None,
                 tenancy=None):
        self.predictor = predictor
        self.cfg = config or GatewayConfig()
        # Multi-tenant opt-in (docs/multitenancy.md): a TenantFabric
        # swaps the plain admission controller for the weighted-fair
        # tenant-aware subclass, built against the same capacity knobs.
        # No fabric → byte-identical single-tenant behaviour.
        self.tenancy = tenancy
        if tenancy is not None:
            self.admission = tenancy.build_admission(self.cfg.max_inflight,
                                                     self.cfg.max_queue)
        else:
            self.admission = AdmissionController(self.cfg.max_inflight,
                                                 self.cfg.max_queue)
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._draining = False
        # Gateway-local counters: the numbers `GET /gateway` serves.
        # The same events also flow into the global telemetry registry
        # so `/metrics` agrees with them (acceptance criterion c).
        self._admitted = 0
        self._shed: Dict[str, int] = {}
        self._hedged = 0
        self._timeouts = 0
        self._latency_ewma_s: Optional[float] = None
        # Continuous serving time-series (docs/serving_anatomy.md):
        # every outcome lands in a per-second rollup journaled as
        # serving/ts, with admission/breaker context merged per row.
        self.rollup = ServingRollup(context_fn=self._rollup_context)
        # Dynamic microbatcher (rafiki_tpu/gateway/microbatch.py): only
        # constructed when batching is on — max_batch=1 keeps the
        # classic per-request fan-out with zero new moving parts.
        self._batcher: Optional[MicroBatcher] = None
        if self.cfg.max_batch > 1:
            self._batcher = MicroBatcher(
                self._execute_batch, self.cfg.max_batch,
                self.cfg.max_batch_wait_ms / 1000.0,
                reserve_fn=self._expected_service_s)
        # Latest gateway wins the collector slot: one predictor process
        # serves one job, and tests that build several gateways only
        # ever assert on the live one.
        telemetry.register_collector("gateway", self.stats)
        telemetry.register_collector("serving", self.rollup.collector)
        # Durable knob record: the digital twin's calibration extractor
        # (scripts/twin_calibrate.py) reads the LIVE limits out of the
        # journals instead of guessing defaults — a journal dir is a
        # complete capacity-model input on its own (docs/twin.md).
        _journal.record("gateway", "config",
                        max_inflight=self.cfg.max_inflight,
                        max_queue=self.cfg.max_queue,
                        default_deadline_s=self.cfg.default_deadline_s,
                        min_replies=self.cfg.min_replies,
                        hedge_grace_s=self.cfg.hedge_grace_s,
                        policy=self.cfg.policy,
                        breaker_failures=self.cfg.breaker_failures,
                        breaker_cooldown_s=self.cfg.breaker_cooldown_s,
                        max_batch=self.cfg.max_batch,
                        max_batch_wait_ms=self.cfg.max_batch_wait_ms,
                        blackout_retries=self.cfg.blackout_retries,
                        tenants_enabled=self.tenancy is not None,
                        tenant_quota_frac=(
                            self.tenancy.directory.quota_frac
                            if self.tenancy is not None else None))

    # -- the predict path ----------------------------------------------------

    def predict(self, queries: List[Any],
                deadline_s: Optional[float] = None,
                trace_id: Optional[str] = None,
                tenant: Optional[str] = None) -> List[Any]:
        """Admit → route → quorum-gather → feed breakers. Raises
        :class:`ShedError` when admission refuses, RuntimeError when
        the job has no live workers.

        This is the trace edge: a request either carries a caller
        trace id (``X-Rafiki-Trace-Id`` upstream) or gets a fresh one
        here, and everything downstream — bus envelopes, worker spans,
        journal records in every process — stitches to it. The tenant
        edge too (``X-Rafiki-Tenant``): with a :class:`TenantFabric`
        attached, the tenant id rides the same thread-local into bus
        envelopes, and admission/shed/latency are charged per tenant
        (docs/multitenancy.md)."""
        with trace_context.trace(trace_id):
            with trace_context.tenant_scope(tenant):
                return self._predict(queries, deadline_s, tenant)

    def _predict(self, queries: List[Any],
                 deadline_s: Optional[float],
                 tenant: Optional[str] = None) -> List[Any]:
        # Open this request's hop-mark prefix (docs/serving_anatomy.md):
        # admit/queue marks stamped here ride into every bus envelope
        # the fan-out produces. Cleared in the finally — a stale prefix
        # would leak this request's marks into the thread's next chain.
        _hops.begin()
        _hops.add("admit")
        try:
            return self._predict_admitted(queries, deadline_s, tenant)
        finally:
            _hops.clear()

    def _predict_admitted(self, queries: List[Any],
                          deadline_s: Optional[float],
                          tenant: Optional[str] = None) -> List[Any]:
        fabric = self.tenancy
        if deadline_s is None and fabric is not None:
            # Tenant-aware deadline default: the tier's deadline (gold
            # shorter than batch) before the gateway-wide fallback.
            deadline_s = fabric.directory.tier_of(tenant).deadline_s
        deadline_s = (deadline_s or self.cfg.default_deadline_s
                      or self.predictor.timeout_s)
        deadline = time.monotonic() + deadline_s
        with self._lock:
            draining = self._draining
        if draining:
            self._count_shed("draining")
            if fabric is not None:
                fabric.accounting.shed(tenant or ANON_TENANT, "draining")
            raise ShedError("draining", self._retry_after())
        # Deadline-aware admission: don't hold a waiter past the point
        # where the expected service time no longer fits its deadline —
        # shedding NOW beats admitting a request doomed to time out.
        reserve = min(self._expected_service_s(),
                      deadline_s * DEADLINE_RESERVE_FRAC)
        try:
            if fabric is not None:
                waited = self.admission.admit(
                    deadline - reserve, retry_after_s=self._retry_after(),
                    tenant=tenant)
            else:
                waited = self.admission.admit(
                    deadline - reserve, retry_after_s=self._retry_after())
        except ShedError as e:
            self._count_shed(e.reason)
            if fabric is not None:
                # Charged to THIS tenant: the per-tenant shed ledger is
                # how noisy-neighbor-shed proves who paid for a spike.
                fabric.accounting.shed(tenant or ANON_TENANT, e.reason)
            raise
        _hops.add("queue")  # admission granted: the queue wait is over
        with self._lock:
            self._admitted += 1
        telemetry.inc("gateway.admitted")
        if fabric is not None:
            fabric.accounting.admitted(tenant or ANON_TENANT, waited)
        if waited:
            telemetry.observe("gateway.queue_wait_s", waited)
        # Chaos: an injected delay here is a frontend latency spike that
        # eats into the request's own deadline — it exercises the
        # deadline-aware gather (the predictor gets whatever budget is
        # left) while the request holds an inflight slot, which is what
        # drain-under-load scenarios need to stretch.
        chaos.hook("gateway.predict", self.predictor.job_id)
        if self._batcher is not None:
            return self._predict_batched(queries, deadline, tenant, waited)
        t0 = time.monotonic()
        try:
            # The gateway span is the trace root on the serving path:
            # bus envelopes fanned out under it carry its span_id as
            # parent_span, so the stitched trace hangs together.
            with telemetry.span("gateway.predict",
                                job_id=self.predictor.job_id,
                                queries=len(queries)):
                report = self._fanout(queries, deadline)
        finally:
            if fabric is not None:
                self.admission.release(tenant)
            else:
                self.admission.release()
        # lint: disable=RF007 — breaker EWMA input; region is under the span
        elapsed = time.monotonic() - t0
        self._absorb(report, elapsed)
        # End-to-end latency reservoir: the p99 the gateway latency SLO
        # evaluates (docs/perf.md). The gather span measures the same
        # region but span summaries don't feed SLO sources directly.
        telemetry.observe("gateway.predict_s", elapsed)
        ok = report.timeouts == 0
        self.rollup.observe(latency_s=elapsed,
                            outcome="ok" if ok else "error")
        if fabric is not None:
            # The tenant ledger charges CALLER-observed latency: admission
            # wait + service. Queue wait under contention is the whole
            # noisy-neighbor signal — charging service time alone would
            # let an interference victim's p99 read as healthy.
            fabric.accounting.completed(tenant or ANON_TENANT,
                                        waited + elapsed, ok)
        # Independent end-to-end record for hop-sum reconciliation:
        # obs waterfall / obs tails cross-check the stitched chain's
        # total against this gateway-measured elapsed for the trace.
        _journal.record("serving", "request", queries=len(queries),
                        e2e_s=round(elapsed, 6), ok=ok,
                        hedged=report.hedged, timeouts=report.timeouts,
                        tenant=tenant)
        from rafiki_tpu.obs.perf import slo as _slo

        _slo.maybe_tick()
        return report.outputs

    def _predict_batched(self, queries: List[Any], deadline: float,
                         tenant: Optional[str] = None,
                         waited: float = 0.0) -> List[Any]:
        """Microbatched path: ride a shared fan-out, keep per-request
        observability. The admission slot is held for the whole wait —
        the inflight budget still bounds concurrency."""
        fabric = self.tenancy
        member = self._batcher.submit(queries, deadline,
                                      prefix=_hops.prefix_marks())
        try:
            # +2s slack over the deadline: the flusher itself bounds the
            # fan-out by the member deadlines; this guard only catches a
            # wedged flusher rather than blocking forever.
            if not member.wait(max(0.0, deadline - time.monotonic()) + 2.0):
                raise RuntimeError("microbatch flush timed out")
        finally:
            if fabric is not None:
                self.admission.release(tenant)
            else:
                self.admission.release()
        if member.error is not None:
            raise member.error
        report = member.report
        # lint: disable=RF007 — e2e latency; flush region is under the span
        elapsed = time.monotonic() - member.enq_t
        telemetry.observe("gateway.predict_s", elapsed)
        ok = report.timeouts == 0
        self.rollup.observe(latency_s=elapsed,
                            outcome="ok" if ok else "error")
        if fabric is not None:
            # Caller-observed latency, same rule as the direct path.
            fabric.accounting.completed(tenant or ANON_TENANT,
                                        waited + elapsed, ok)
        # Re-absorb the shared flush chain under THIS request's trace
        # (prefix + bat + shared worker chain + dec): every member gets
        # a stitchable waterfall even though the wire saw one envelope.
        if member.chains:
            _hops.absorb(uuid.uuid4().hex, member.chains)
        _journal.record("serving", "request", queries=len(queries),
                        e2e_s=round(elapsed, 6), ok=ok,
                        hedged=report.hedged, timeouts=report.timeouts,
                        batched=True, flush_reason=member.flush_reason,
                        tenant=tenant)
        from rafiki_tpu.obs.perf import slo as _slo

        _slo.maybe_tick()
        return member.outputs

    def _execute_batch(self, members: List[BatchMember],
                       flush_reason: str) -> None:
        """Flusher-thread body: one batched fan-out for all members,
        then scatter per-member output slices and hop chains."""
        t0 = time.monotonic()
        bat = _hops.mark("bat")  # shared flush instant for every member
        flat = [q for m in members for q in m.queries]
        deadline = min(m.deadline for m in members)
        telemetry.observe("serving.microbatch.size", float(len(flat)))
        telemetry.observe("serving.microbatch.fill_ratio",
                          len(flat) / float(self.cfg.max_batch))
        if flush_reason == "size":
            telemetry.inc("serving.microbatch.flush_size")
        elif flush_reason == "deadline":
            telemetry.inc("serving.microbatch.flush_deadline")
        else:
            telemetry.inc("serving.microbatch.flush_drain")
        with telemetry.span("gateway.predict",
                            job_id=self.predictor.job_id,
                            queries=len(flat), members=len(members)):
            report = self._fanout(flat, deadline, batched=True)
        # lint: disable=RF007 — breaker EWMA input; region is under the span
        elapsed = time.monotonic() - t0
        self._absorb(report, elapsed)
        shared = getattr(report, "chains", None)
        dec = getattr(report, "dec_mark", None)
        off = 0
        for m in members:
            n = len(m.queries)
            m.outputs = report.outputs[off:off + n]
            off += n
            if shared:
                m.chains = {w: list(m.prefix) + [bat] + list(ch)
                            + ([dec] if dec else [])
                            for w, ch in shared.items()}
            m.flush_reason = flush_reason
            m.report = report
            m.elapsed_s = elapsed
            m.done.set()

    def _fanout(self, queries: List[Any], deadline: float,
                batched: bool = False):
        """Route + gather, with bounded blackout re-routes: a gather
        that ends with ZERO replies from ANY worker (a dead fan-out
        set, e.g. a SIGKILLed stacked worker) re-routes and retries
        while retries and deadline budget remain, instead of dropping
        an admitted request on the floor."""
        attempts = max(0, self.cfg.blackout_retries)
        ewma = self._expected_service_s()
        if not ewma:
            # No latency model yet (first request / cold gateway): no
            # basis to cut a gather short, so no probing retries.
            attempts = 0
        for attempt in range(attempts + 1):
            remaining = max(0.0, deadline - time.monotonic())
            retries_left = attempts - attempt
            if retries_left:
                budget = min(remaining, max(BLACKOUT_PROBE_MIN_S,
                                            BLACKOUT_PROBE_FACTOR * ewma))
            else:
                budget = remaining
            try:
                workers, quorum = self._route()
                if batched:
                    report = self.predictor.predict_batch_detailed(
                        queries, workers=workers, timeout_s=budget,
                        min_replies=quorum,
                        hedge_grace_s=self.cfg.hedge_grace_s)
                else:
                    report = self.predictor.predict_detailed(
                        queries, workers=workers, timeout_s=budget,
                        min_replies=quorum,
                        hedge_grace_s=self.cfg.hedge_grace_s)
            except RuntimeError:
                # No live workers RIGHT NOW — with retries left (and a
                # history of successful service) wait out the lease
                # flap / fallback-worker spawn instead of failing.
                if not retries_left:
                    raise
                report = None
            if report is not None and report.replies:
                return report
            if not retries_left:
                return report
            self._note_blackout(report, attempt)
            time.sleep(min(BLACKOUT_BACKOFF_S,
                           max(0.0, deadline - time.monotonic())))
        raise RuntimeError("unreachable")  # pragma: no cover

    def _note_blackout(self, report, attempt: int) -> None:
        """Feed a blackout attempt into breakers + journal so the
        re-route is reconstructible post-mortem."""
        if report is not None:
            for w in report.workers:
                br = self._breaker(w)
                state_before = br.snapshot().get("state")
                br.record_failure()
                state_after = br.snapshot().get("state")
                if state_after != state_before:
                    _journal.record("gateway", "breaker_transition",
                                    worker_id=w, from_state=state_before,
                                    to_state=state_after)
        telemetry.inc("gateway.blackout_retries")
        _journal.record("gateway", "blackout_retry", attempt=attempt + 1,
                        workers=(list(report.workers) if report is not None
                                 else []))

    # -- routing -------------------------------------------------------------

    def _route(self) -> Tuple[List[str], int]:
        """Pick the fan-out set (breaker-filtered) and gather quorum."""
        workers = self.predictor.live_workers()
        allowed = [w for w in workers if self._breaker(w).allow()]
        if not allowed:
            # Every breaker open/probing: routing nowhere would turn a
            # brown-out into a black-out. Fan out to the full live set
            # as a forced probe instead.
            allowed = workers
        if self.cfg.policy == "least-loaded" and allowed:
            depth_of = getattr(self.predictor.bus, "queue_depth", None)
            if depth_of is not None:
                allowed = [min(allowed, key=depth_of)]
            else:  # bus without depth support: fall back to first
                allowed = allowed[:1]
            return allowed, 1
        quorum = (self.cfg.min_replies if self.cfg.min_replies is not None
                  else default_quorum(len(allowed)))
        return allowed, quorum

    def _breaker(self, worker_id: str) -> CircuitBreaker:
        with self._lock:
            br = self._breakers.get(worker_id)
            if br is None:
                br = self._breakers[worker_id] = CircuitBreaker(
                    self.cfg.breaker_failures, self.cfg.breaker_cooldown_s)
            return br

    def _absorb(self, report, elapsed_s: float) -> None:
        """Feed one batch's gather report into breakers and stats."""
        n_queries = len(report.outputs)
        for w in report.workers:
            br = self._breaker(w)
            state_before = br.snapshot().get("state")
            if report.replies.get(w, 0) > 0:
                br.record_success(latency_s=elapsed_s)
            else:
                br.record_failure()
            state_after = br.snapshot().get("state")
            if state_after != state_before:
                # Breaker decisions are journal-worthy: a post-mortem
                # needs to see WHY fan-out avoided a worker.
                _journal.record("gateway", "breaker_transition",
                                worker_id=w, from_state=state_before,
                                to_state=state_after)
        with self._lock:
            self._hedged += report.hedged
            self._timeouts += report.timeouts
            if report.timeouts == 0 and n_queries:
                prev = self._latency_ewma_s
                a = LATENCY_EWMA_ALPHA
                self._latency_ewma_s = (elapsed_s if prev is None
                                        else (1 - a) * prev + a * elapsed_s)
        if report.hedged:
            telemetry.inc("gateway.hedged", report.hedged)

    # -- deadline bookkeeping ------------------------------------------------

    def _expected_service_s(self) -> float:
        with self._lock:
            return self._latency_ewma_s or 0.0

    def _retry_after(self) -> float:
        """Back-off hint: roughly one queue-drain time at current
        service latency, floored so clients never spin."""
        with self._lock:
            ewma = self._latency_ewma_s or 0.1
        backlog = self.admission.waiting + 1
        return round(max(RETRY_AFTER_FLOOR_S,
                         ewma * backlog / self.cfg.max_inflight), 3)

    def _rollup_context(self) -> Dict[str, Any]:
        """Live context merged into each serving/ts row: queue depth,
        inflight, and the per-worker breaker states."""
        with self._lock:
            breakers = {w: b.snapshot().get("state")
                        for w, b in self._breakers.items()}
        return {"queue_depth": self.admission.waiting,
                "inflight": self.admission.inflight,
                "breakers": breakers,
                "breakers_open": sum(1 for s in breakers.values()
                                     if s != "closed")}

    def _count_shed(self, reason: str) -> None:
        with self._lock:
            self._shed[reason] = self._shed.get(reason, 0) + 1
        self.rollup.observe(outcome="shed")
        telemetry.inc("gateway.shed")
        # Reasons are a closed enum of admission code paths, refining
        # the stable literal gateway.shed aggregate above.
        # lint: disable=RF008 — bounded shed-reason enum under a literal aggregate
        telemetry.inc(f"gateway.shed_{reason}")
        _journal.record("gateway", "shed", reason=reason)

    # -- drain ---------------------------------------------------------------

    @property
    def draining(self) -> bool:
        with self._lock:
            return self._draining

    def drain(self, timeout: Optional[float] = 10.0) -> bool:
        """Stop admitting (new requests and queued waiters shed with
        reason ``draining``), then flush inflight requests. Returns
        True when everything inflight finished within ``timeout``.
        ``/healthz`` reports draining from the first moment."""
        with self._lock:
            already = self._draining
            self._draining = True
        if not already:
            telemetry.inc("gateway.drains")
        if self._batcher is not None:
            # Flush pending microbatch members before closing admission:
            # they already hold slots, so wait_idle covers them.
            self._batcher.drain()
        self.admission.close()
        done = self.admission.wait_idle(timeout)
        if self.tenancy is not None:
            # Durable counter summary (tenant/summary): the record
            # `obs tenants --check` reconciles per-record tallies with.
            self.tenancy.accounting.flush()
        return done

    # -- introspection -------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """JSON-able state for ``GET /gateway`` and the telemetry
        collector: admission counters, routing config, breaker state."""
        with self._lock:
            shed = dict(self._shed)
            out: Dict[str, Any] = {
                "policy": self.cfg.policy,
                "draining": self._draining,
                "admitted": self._admitted,
                "shed": shed,
                "shed_total": sum(shed.values()),
                "hedged": self._hedged,
                "timeouts": self._timeouts,
                "latency_ewma_s": (None if self._latency_ewma_s is None
                                   else round(self._latency_ewma_s, 6)),
                "limits": {
                    "max_inflight": self.cfg.max_inflight,
                    "max_queue": self.cfg.max_queue,
                    "default_deadline_s": self.cfg.default_deadline_s,
                    "hedge_grace_s": self.cfg.hedge_grace_s,
                    "min_replies": self.cfg.min_replies,
                    "max_queries_per_request":
                        self.cfg.max_queries_per_request,
                    "max_batch": self.cfg.max_batch,
                    "max_batch_wait_ms": self.cfg.max_batch_wait_ms,
                    "blackout_retries": self.cfg.blackout_retries,
                },
                "breakers": {w: b.snapshot()
                             for w, b in self._breakers.items()},
            }
        out["inflight"] = self.admission.inflight
        out["waiting"] = self.admission.waiting
        return out

    def sensors(self) -> Dict[str, Any]:
        """Autoscale sensor view (docs/autoscale.md): the admission
        pressure numbers the controller folds into every
        ``autoscale/decision`` snapshot — queue depth (absolute and as
        a fraction of capacity), inflight, cumulative shed rate, and
        breaker state. Cheap by contract: read on every control tick."""
        with self._lock:
            admitted = self._admitted
            shed = sum(self._shed.values())
            ewma = self._latency_ewma_s
            draining = self._draining
            breakers_open = sum(
                1 for b in self._breakers.values()
                if b.snapshot().get("state") != "closed")
        waiting = self.admission.waiting
        total = admitted + shed
        out = {
            "queue_depth": waiting,
            "queue_frac": waiting / max(1, self.cfg.max_queue),
            "inflight": self.admission.inflight,
            "shed_rate": (shed / total) if total else 0.0,
            "latency_ewma_s": ewma,
            "breakers_open": breakers_open,
            "draining": draining,
        }
        if self.tenancy is not None:
            # Tenant aggregates (worst burn, tenant shed rate) ride the
            # same snapshot: the arbiter lane's pressure inputs.
            out.update(self.tenancy.sensors())
        return out
