"""Per-worker circuit breakers for the serving fan-out.

A SIGKILLed worker keeps its bus registration until its heartbeat
lease expires (bus/queues.py); during that window the predictor still
fans out to it and every gather waits on a reply that will never come.
The breaker closes that window from the *reply* side: consecutive
batches with zero replies from a worker open its breaker, and the
gateway stops routing to it immediately — before the lease expires.
After a cooldown the breaker goes half-open and admits ONE probe
batch; a reply closes it, another miss re-opens it for a full
cooldown.

States (the classic three): ``closed`` (healthy, route freely) →
``open`` (skip this worker) → ``half-open`` (one probe outstanding).

The clock is injectable so the open→half-open transition is testable
without sleeping.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional

from rafiki_tpu import telemetry

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 3, cooldown_s: float = 5.0,
                 clock: Callable[[], float] = time.monotonic):
        self.failure_threshold = max(1, failure_threshold)
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        # Lifetime reply/miss tallies — surfaced in gateway stats so an
        # operator can see WHY a breaker opened, not just that it did.
        self.successes = 0
        self.failures = 0
        # EWMA of observed batch latency for this worker's replies.
        self._latency_ewma_s = None

    # -- routing decision ----------------------------------------------------

    def allow(self) -> bool:
        """May the gateway fan out to this worker right now?"""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self.cooldown_s:
                    self._state = HALF_OPEN
                    self._probe_inflight = True
                    telemetry.inc("gateway.breaker_half_open")
                    return True  # this caller carries the probe
                return False
            # HALF_OPEN: exactly one probe at a time.
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    # -- outcome feedback ----------------------------------------------------

    def record_success(self, latency_s: Optional[float] = None) -> None:
        with self._lock:
            self.successes += 1
            self._consecutive_failures = 0
            self._probe_inflight = False
            if latency_s is not None:
                prev = self._latency_ewma_s
                self._latency_ewma_s = (latency_s if prev is None
                                        else 0.8 * prev + 0.2 * latency_s)
            if self._state != CLOSED:
                self._state = CLOSED
                telemetry.inc("gateway.breaker_closed")

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            self._consecutive_failures += 1
            self._probe_inflight = False
            tripped = (self._state == HALF_OPEN
                       or (self._state == CLOSED
                           and self._consecutive_failures
                           >= self.failure_threshold))
            if tripped:
                self._state = OPEN
                self._opened_at = self._clock()
                telemetry.inc("gateway.breaker_opened")

    # -- introspection -------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "state": self._state,
                "consecutive_failures": self._consecutive_failures,
                "successes": self.successes,
                "failures": self.failures,
                "latency_ewma_s": (None if self._latency_ewma_s is None
                                   else round(self._latency_ewma_s, 6)),
            }
