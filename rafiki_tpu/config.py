"""Typed framework configuration with environment-variable overrides.

Reference parity: rafiki/config.py + scripts/.env.sh (unverified paths):
the reference spreads configuration over env vars injected into
containers; here one dataclass is the single source of truth and every
field can be overridden via RAFIKI_TPU_<FIELD>.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path


def _env(name: str, default, cast):
    raw = os.environ.get(f"RAFIKI_TPU_{name.upper()}")
    if raw is None:
        return default
    if cast is bool:
        return raw.lower() in ("1", "true", "yes", "on")
    return cast(raw)


@dataclasses.dataclass
class Config:
    # Storage
    data_dir: Path = Path(os.environ.get("RAFIKI_TPU_DATA_DIR", "~/.rafiki_tpu")).expanduser()

    # Control plane
    admin_host: str = "127.0.0.1"
    admin_port: int = 3000
    predictor_port_base: int = 30000

    # Superadmin seed (reference seeds a superadmin on first boot)
    superadmin_email: str = "superadmin@rafiki"
    superadmin_password: str = "rafiki"

    # Auth
    jwt_secret: str = "rafiki-tpu-secret"
    jwt_ttl_hours: int = 24

    # Scheduling
    poll_interval_s: float = 0.1
    trial_heartbeat_s: float = 5.0
    worker_stale_after_s: float = 60.0

    # Serving
    predict_timeout_s: float = 10.0
    inference_batch_size: int = 64

    # Serving gateway (rafiki_tpu/gateway/; see docs/serving.md)
    gateway_max_inflight: int = 8
    gateway_max_queue: int = 32
    gateway_hedge_grace_s: float = 0.25
    gateway_policy: str = "replicate-all"
    gateway_breaker_failures: int = 3
    gateway_breaker_cooldown_s: float = 5.0
    max_queries_per_request: int = 1024
    # Dynamic microbatching (docs/serving.md): coalesce admitted
    # requests into one bus fan-out. 1 = off (per-request fan-out);
    # RAFIKI_TPU_GATEWAY_MAX_BATCH / _MAX_BATCH_WAIT_MS override.
    gateway_max_batch: int = 1
    gateway_max_batch_wait_ms: float = 5.0

    # Compute
    default_dtype: str = "bfloat16"
    # Storage dtype for serving params blobs (dump_parameters). The
    # default bfloat16 halves the device→host fetch and is math-
    # identical for templates that compute in bf16 (params are cast
    # down at every conv/dense anyway); set "float32" to keep masters.
    serving_params_dtype: str = "bfloat16"

    @property
    def db_path(self) -> Path:
        return self.data_dir / "meta.sqlite3"

    @property
    def params_dir(self) -> Path:
        return self.data_dir / "params"

    @property
    def logs_dir(self) -> Path:
        return self.data_dir / "logs"

    @property
    def datasets_dir(self) -> Path:
        return self.data_dir / "datasets"

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        for f in dataclasses.fields(cls):
            cur = getattr(cfg, f.name)
            cast = type(cur) if not isinstance(cur, Path) else (lambda s: Path(s).expanduser())
            setattr(cfg, f.name, _env(f.name, cur, cast))
        return cfg

    def ensure_dirs(self) -> "Config":
        for d in (self.data_dir, self.params_dir, self.logs_dir, self.datasets_dir):
            Path(d).mkdir(parents=True, exist_ok=True)
        return self


_default: Config | None = None


def get_config() -> Config:
    global _default
    if _default is None:
        _default = Config.from_env()
    return _default


def set_config(cfg: Config) -> None:
    global _default
    _default = cfg
