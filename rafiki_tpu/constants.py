"""Enums shared across the framework.

Reference parity: rafiki/constants.py (unverified path; reference mount
was empty — see SURVEY.md provenance warning). The reference defines
UserType, ServiceType, BudgetType and per-entity status enums; we keep
the same vocabulary so client code translates 1:1.
"""

from __future__ import annotations

import enum


class UserType(str, enum.Enum):
    SUPERADMIN = "SUPERADMIN"
    ADMIN = "ADMIN"
    MODEL_DEVELOPER = "MODEL_DEVELOPER"
    APP_DEVELOPER = "APP_DEVELOPER"


class TaskType(str, enum.Enum):
    IMAGE_CLASSIFICATION = "IMAGE_CLASSIFICATION"
    POS_TAGGING = "POS_TAGGING"
    GENERIC = "GENERIC"


class BudgetType(str, enum.Enum):
    # Reference: MODEL_TRIAL_COUNT / GPU_COUNT / TIME_HOURS.
    # TPU-native: CHIP_COUNT replaces GPU_COUNT (one trial per chip).
    MODEL_TRIAL_COUNT = "MODEL_TRIAL_COUNT"
    CHIP_COUNT = "CHIP_COUNT"
    GPU_COUNT = "GPU_COUNT"  # accepted alias for CHIP_COUNT (reference compat)
    TIME_HOURS = "TIME_HOURS"


class TrainJobStatus(str, enum.Enum):
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"
    COMPLETED = "COMPLETED"


class TrialStatus(str, enum.Enum):
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    ERRORED = "ERRORED"
    TERMINATED = "TERMINATED"


class InferenceJobStatus(str, enum.Enum):
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"


class ServiceType(str, enum.Enum):
    TRAIN_WORKER = "TRAIN_WORKER"
    INFERENCE_WORKER = "INFERENCE_WORKER"
    ADVISOR = "ADVISOR"
    PREDICTOR = "PREDICTOR"
    # The sweep supervisor's liveness lease (docs/recovery.md): a
    # RUNNING job whose SUPERVISOR heartbeats all went stale is a
    # crashed control plane — the resume reaper's detection signal.
    SUPERVISOR = "SUPERVISOR"


class ServiceStatus(str, enum.Enum):
    STARTED = "STARTED"
    RUNNING = "RUNNING"
    STOPPED = "STOPPED"
    ERRORED = "ERRORED"
