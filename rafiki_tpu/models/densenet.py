"""DenseNet-BC template for CIFAR-10-class images.

Reference analog: examples/models/image_classification/PyDenseNet.py
(unverified — a torch DenseNet on CIFAR-10).

TPU-first notes: dense blocks are concat-heavy; XLA fuses the concats
and the 1x1 bottleneck convs keep channel counts MXU-friendly.
GroupNorm replaces BatchNorm (see vgg.py rationale). Knobs expose the
classic (depth, growth rate) DenseNet-BC axes.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax.numpy as jnp

from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob


class _DenseLayer(nn.Module):
    growth: int
    dtype: object

    @nn.compact
    def __call__(self, x):
        h = nn.GroupNorm(num_groups=math.gcd(8, x.shape[-1]), dtype=self.dtype)(x)
        h = nn.relu(h)
        h = nn.Conv(4 * self.growth, (1, 1), dtype=self.dtype, use_bias=False)(h)
        h = nn.GroupNorm(num_groups=math.gcd(8, h.shape[-1]), dtype=self.dtype)(h)
        h = nn.relu(h)
        h = nn.Conv(self.growth, (3, 3), padding="SAME", dtype=self.dtype, use_bias=False)(h)
        return jnp.concatenate([x, h], axis=-1)


class _Transition(nn.Module):
    out_ch: int
    dtype: object

    @nn.compact
    def __call__(self, x):
        x = nn.GroupNorm(num_groups=math.gcd(8, x.shape[-1]), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.out_ch, (1, 1), dtype=self.dtype, use_bias=False)(x)
        if min(x.shape[1], x.shape[2]) >= 2:
            x = nn.avg_pool(x, (2, 2), strides=(2, 2))
        return x


class _DenseNet(nn.Module):
    depth: int       # total conv layers; (depth-4) % 3 == 0 for 3 blocks
    growth: int
    num_classes: int
    reduction: float = 0.5
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        n = (self.depth - 4) // 6  # bottleneck layers per block (each = 2 convs)
        ch = 2 * self.growth
        x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype, use_bias=False)(x)
        for block in range(3):
            for _ in range(max(1, n)):
                x = _DenseLayer(self.growth, self.dtype)(x)
            if block < 2:
                out_ch = max(8, int(x.shape[-1] * self.reduction))
                x = _Transition(out_ch, self.dtype)(x)
        x = nn.GroupNorm(num_groups=math.gcd(8, x.shape[-1]), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = x.mean(axis=(1, 2))  # global average pool
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class DenseNet(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "depth": CategoricalKnob([22, 40, 58], affects_shape=True),
            "growth": CategoricalKnob([12, 24], affects_shape=True),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([64, 128], affects_shape=True),
            "epochs": IntegerKnob(1, 10),
            "seed": FixedKnob(0),
        }

    def build_module(self, num_classes, input_shape):
        return _DenseNet(
            depth=int(self.knobs["depth"]),
            growth=int(self.knobs["growth"]),
            num_classes=num_classes,
        )

if __name__ == "__main__":
    # Dev harness run (`python -m rafiki_tpu.models.X`): pin the
    # platform first or the image's sitecustomize TPU hijack hangs
    # backend init when the tunnel is down.
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    from rafiki_tpu.model.dev import test_model_class

    test_model_class(
        DenseNet, "IMAGE_CLASSIFICATION",
        "synthetic://images?classes=10&n=1024&w=32&h=32&c=3&seed=0",
        "synthetic://images?classes=10&n=256&w=32&h=32&c=3&seed=1",
        knobs=dict(depth=22, growth=12, learning_rate=3e-3, batch_size=64,
                   epochs=2, seed=0),
    )
