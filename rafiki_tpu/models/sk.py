"""Host-side sklearn templates.

Reference analogs: examples/models/image_classification/SkDt.py and
SkSvm.py (unverified) — decision tree / SVM templates proving the model
contract is framework-agnostic. These run on the host CPU; they exist
for capability parity (not every AutoML workload is a neural net) and
as contract tests that BaseModel does not assume JAX.
"""

from __future__ import annotations

import pickle
from typing import Any, List

import numpy as np

from rafiki_tpu.model.base import BaseModel
from rafiki_tpu.model.dataset import dataset_utils
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob


class _SkImageModel(BaseModel):
    """Shared plumbing: flatten images, fit an sklearn classifier."""

    def __init__(self, **knobs):
        super().__init__(**knobs)
        self._clf = None
        self._classes = None

    def _make_clf(self):
        raise NotImplementedError

    def train(self, dataset_uri: str) -> None:
        ds = dataset_utils.load(dataset_uri)
        x = ds.x.reshape((ds.size, -1))
        self._clf = self._make_clf()
        self._clf.fit(x, ds.y)
        self._classes = ds.classes

    def evaluate(self, dataset_uri: str) -> float:
        ds = dataset_utils.load(dataset_uri)
        x = ds.x.reshape((ds.size, -1))
        return float((self._clf.predict(x) == ds.y).mean())

    def predict(self, queries: List[Any]) -> List[List[float]]:
        x = np.asarray(queries, dtype=np.float32).reshape((len(queries), -1))
        if hasattr(self._clf, "predict_proba"):
            probs = self._clf.predict_proba(x)
            # align to full class range (sklearn drops absent classes)
            out = np.zeros((len(queries), self._classes))
            out[:, self._clf.classes_] = probs
            return out.tolist()
        preds = self._clf.predict(x)
        out = np.zeros((len(queries), self._classes))
        out[np.arange(len(queries)), preds] = 1.0
        return out.tolist()

    def dump_parameters(self) -> bytes:
        return pickle.dumps({"clf": self._clf, "classes": self._classes})

    def load_parameters(self, blob: bytes) -> None:
        payload = pickle.loads(blob)
        self._clf = payload["clf"]
        self._classes = payload["classes"]


class SkDt(_SkImageModel):
    """Decision tree (reference: SkDt.py)."""

    @staticmethod
    def get_knob_config():
        return {
            "max_depth": IntegerKnob(2, 16),
            "criterion": CategoricalKnob(["gini", "entropy"]),
            "seed": FixedKnob(0),
        }

    def _make_clf(self):
        from sklearn.tree import DecisionTreeClassifier

        return DecisionTreeClassifier(
            max_depth=int(self.knobs["max_depth"]),
            criterion=self.knobs["criterion"],
            random_state=int(self.knobs["seed"]),
        )


class SkSvm(_SkImageModel):
    """Linear/RBF SVM (reference: SkSvm.py)."""

    @staticmethod
    def get_knob_config():
        return {
            "C": FloatKnob(1e-2, 1e2, is_exp=True),
            "kernel": CategoricalKnob(["linear", "rbf"]),
            "seed": FixedKnob(0),
        }

    def _make_clf(self):
        from sklearn.svm import SVC

        # No probability=True (deprecated in sklearn 1.9): predictions
        # ensemble as one-hot votes via the predict() fallback path.
        return SVC(C=float(self.knobs["C"]), kernel=self.knobs["kernel"],
                   random_state=int(self.knobs["seed"]))
