"""FeedForward MLP template (reference analog: examples/models/
image_classification/TfFeedForward.py, unverified — an MLP over
flattened images with knobs for hidden layer count/units, log-scale
learning rate, batch size, epochs).

TPU notes: dense layers map straight onto the MXU; compute in bfloat16,
params float32. ``hidden_units``/``hidden_layers`` affect shapes →
flagged ``affects_shape`` so the scheduler can bucket trials by
compiled-program signature.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob


class _Mlp(nn.Module):
    hidden_layers: int
    hidden_units: int
    num_classes: int
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.reshape((x.shape[0], -1)).astype(self.dtype)
        for _ in range(self.hidden_layers):
            x = nn.Dense(self.hidden_units, dtype=self.dtype)(x)
            x = nn.relu(x)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class FeedForward(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "hidden_layers": IntegerKnob(1, 3, affects_shape=True),
            "hidden_units": CategoricalKnob([32, 64, 128, 256], affects_shape=True),
            "learning_rate": FloatKnob(1e-4, 1e-1, is_exp=True),
            "batch_size": CategoricalKnob([32, 64, 128], affects_shape=True),
            "epochs": IntegerKnob(1, 5),
            "seed": FixedKnob(0),
        }

    def build_module(self, num_classes, input_shape):
        return _Mlp(
            hidden_layers=int(self.knobs["hidden_layers"]),
            hidden_units=int(self.knobs["hidden_units"]),
            num_classes=num_classes,
        )


if __name__ == "__main__":
    # Dev harness run (`python -m rafiki_tpu.models.X`): pin the
    # platform first or the image's sitecustomize TPU hijack hangs
    # backend init when the tunnel is down.
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    from rafiki_tpu.model.dev import test_model_class
    from rafiki_tpu.model.dataset import synthetic_images

    test_model_class(
        FeedForward,
        task="IMAGE_CLASSIFICATION",
        train_dataset_uri="synthetic://images?classes=10&n=2048&seed=0",
        test_dataset_uri="synthetic://images?classes=10&n=512&seed=1",
        queries=[synthetic_images(n=4, seed=2).x[i] for i in range(4)],
    )
