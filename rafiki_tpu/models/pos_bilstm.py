"""BiLSTM POS tagger template.

Reference analog: examples/models/pos_tagging/PyBiLstm.py (unverified)
— a torch embedding + BiLSTM + per-token classifier.

TPU notes: flax ``nn.RNN`` lowers the recurrence to ``lax.scan`` — a
single compiled loop, no per-step Python. Sequences are fixed-length
(L static) with -1-masked labels, so one XLA program serves every
batch. Embedding + projection matmuls run in bfloat16 on the MXU.
"""

from __future__ import annotations

from typing import Any, List

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob


class _BiLstmTagger(nn.Module):
    vocab: int
    embed_dim: int
    hidden: int
    num_tags: int
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        h = nn.Embed(self.vocab, self.embed_dim, dtype=self.dtype)(x)
        h = nn.Bidirectional(
            nn.RNN(nn.LSTMCell(self.hidden)),
            nn.RNN(nn.LSTMCell(self.hidden)),
        )(h)
        return nn.Dense(self.num_tags, dtype=self.dtype)(h.astype(self.dtype))


class PosBiLstm(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "embed_dim": CategoricalKnob([32, 64, 128], affects_shape=True),
            "hidden": CategoricalKnob([32, 64, 128], affects_shape=True),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([16, 32, 64], affects_shape=True),
            "epochs": IntegerKnob(1, 10),
            "seed": FixedKnob(0),
        }

    def _input_dtype(self):
        return np.int32

    def build_module(self, num_classes, input_shape):
        vocab = int(self._dataset_meta.get("vocab", 1) or 1)
        return _BiLstmTagger(
            vocab=max(vocab, 2),
            embed_dim=int(self.knobs["embed_dim"]),
            hidden=int(self.knobs["hidden"]),
            num_tags=num_classes,
        )

    def predict(self, queries: List[Any]) -> List[List[int]]:
        """queries: list of variable-length token-id sequences →
        per-token tag ids (argmax over the tag distribution)."""
        if self._loop is None:
            raise RuntimeError("Model has no parameters: call train() or load_parameters() first")
        _, (length,) = self._arch
        out: List[List[int]] = []
        x = np.zeros((len(queries), length), dtype=np.int32)
        lens = []
        for i, q in enumerate(queries):
            toks = np.asarray(q, dtype=np.int32)[:length]
            x[i, : len(toks)] = toks
            lens.append(len(toks))
        probs = self._loop.predict_proba(x, self.batch_size)  # (N, L, tags)
        for i, n in enumerate(lens):
            out.append(np.argmax(probs[i, :n], axis=-1).astype(int).tolist())
        return out


if __name__ == "__main__":
    # Dev harness run (`python -m rafiki_tpu.models.X`): pin the
    # platform first or the image's sitecustomize TPU hijack hangs
    # backend init when the tunnel is down.
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    from rafiki_tpu.model.dev import test_model_class

    test_model_class(
        PosBiLstm, "POS_TAGGING",
        "synthetic://corpus?vocab=100&tags=8&n=256&len=16&seed=0",
        "synthetic://corpus?vocab=100&tags=8&n=64&len=16&seed=1",
        queries=[[5, 9, 3], [17, 2]],
        knobs=dict(embed_dim=32, hidden=32, learning_rate=5e-3, batch_size=32,
                   epochs=3, seed=0),
    )
