"""Small text-classification transformer template.

No reference analog: the reference zoo stops at CNNs and a BiLSTM
tagger. This family exists as the zoo's first *sharded-lane* citizen
(docs/sharding.md): its knob grid reaches dimensions whose train state
outgrows one chip's HBM, and it declares a :class:`ShardPlan` via
``shard_plan`` so the sweep scheduler can route big configurations to
a chip group. Small configurations stay ordinary packable trials —
the lane choice is the plan's solved width, not the family.

TPU notes: embedding + attention + MLP matmuls run in bfloat16 on the
MXU; params stay float32. Sequences are fixed length (one XLA program
per shape bucket) with one label per sequence — `synthetic://text`
data. The embed/MLP dims are multiples of 8 so every FSDP width the
plan can pick divides them cleanly.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp
import numpy as np

from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import (CategoricalKnob, FixedKnob, FloatKnob,
                                    IntegerKnob)


class _Encoder(nn.Module):
    vocab: int
    embed_dim: int
    num_heads: int
    num_layers: int
    num_classes: int
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False):
        length = x.shape[-1]
        h = nn.Embed(self.vocab, self.embed_dim, dtype=self.dtype)(x)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (length, self.embed_dim))
        h = h + pos.astype(self.dtype)
        for _ in range(self.num_layers):
            a = nn.LayerNorm()(h).astype(self.dtype)
            a = nn.SelfAttention(num_heads=self.num_heads,
                                 dtype=self.dtype,
                                 deterministic=True)(a)
            h = h + a
            m = nn.LayerNorm()(h).astype(self.dtype)
            m = nn.Dense(4 * self.embed_dim, dtype=self.dtype)(m)
            m = nn.gelu(m)
            m = nn.Dense(self.embed_dim, dtype=self.dtype)(m)
            h = h + m
        h = nn.LayerNorm()(h)
        h = h.mean(axis=1).astype(self.dtype)  # mean pool over tokens
        return nn.Dense(self.num_classes, dtype=self.dtype)(h)


class Transformer(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "embed_dim": CategoricalKnob([32, 64, 128], affects_shape=True),
            "num_heads": CategoricalKnob([2, 4], affects_shape=True),
            "num_layers": IntegerKnob(1, 2, affects_shape=True),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([16, 32, 64], affects_shape=True),
            "epochs": IntegerKnob(1, 5),
            "seed": FixedKnob(0),
        }

    def _input_dtype(self):
        return np.int32

    def build_module(self, num_classes, input_shape):
        vocab = int(self._dataset_meta.get("vocab", 1) or 1)
        return _Encoder(
            vocab=max(vocab, 2),
            embed_dim=int(self.knobs["embed_dim"]),
            num_heads=int(self.knobs["num_heads"]),
            num_layers=int(self.knobs["num_layers"]),
            num_classes=num_classes,
        )

    def shard_plan(self, ds):
        """Solve this configuration's group width from the param tree's
        shapes alone (eval_shape — nothing is materialized). Width 1
        (the usual answer for this small grid) keeps the trial in the
        serial/packed lanes; tests and smokes pin wider groups via
        ``RAFIKI_SHARD_WIDTH``."""
        import jax

        from rafiki_tpu.shard import ShardPlan

        num_classes, input_shape = self._dataset_arch(ds)
        fns = self._loop_fns(num_classes, input_shape)
        abs_params = jax.eval_shape(fns["init_fn"], jax.random.PRNGKey(0))
        return ShardPlan.for_params(abs_params, family=type(self).__name__)


if __name__ == "__main__":
    # Dev harness run (`python -m rafiki_tpu.models.X`): pin the
    # platform first or the image's sitecustomize TPU hijack hangs
    # backend init when the tunnel is down.
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    from rafiki_tpu.model.dev import test_model_class

    test_model_class(
        Transformer, "TEXT_CLASSIFICATION",
        "synthetic://text?vocab=81&classes=5&n=512&len=16&seed=0",
        "synthetic://text?vocab=81&classes=5&n=128&len=16&seed=1",
        queries=[[5, 9, 3] * 5 + [1], [17, 2] * 8],
        knobs=dict(embed_dim=32, num_heads=2, num_layers=1,
                   learning_rate=5e-3, batch_size=32, epochs=3, seed=0),
    )
