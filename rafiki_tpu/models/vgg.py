"""VGG template for CIFAR-10-class images.

Reference analog: examples/models/image_classification/TfVgg16.py
(unverified — a TF1 VGG16 on CIFAR-10, knobs for lr/batch/epochs).

TPU-first re-design notes:
  * NHWC + 3x3 convs map directly onto the MXU via XLA's conv tiling;
    compute dtype bfloat16, params float32.
  * GroupNorm instead of BatchNorm: no running statistics, so the
    model stays a pure function of (params, batch) — no mutable
    collections threaded through jit — and accuracy on CIFAR-scale
    data is comparable. This is a deliberate architectural departure
    from the reference's BN.
  * ``depth`` knob selects the VGG config (11/13/16); ``width_mult``
    scales channel counts so the advisor can trade FLOPs for accuracy.
  * pooling stops once the spatial dim reaches 1, so the same template
    works on small synthetic images in tests.
"""

from __future__ import annotations

import math

import flax.linen as nn
import jax.numpy as jnp

from rafiki_tpu.model.base import JaxModel
from rafiki_tpu.model.knobs import CategoricalKnob, FixedKnob, FloatKnob, IntegerKnob

_CFGS = {
    11: [64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    13: [64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"],
    16: [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"],
}


class _Vgg(nn.Module):
    depth: int
    width_mult: float
    num_classes: int
    dropout: float
    dtype: object = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = False, dropout_rate=None):
        x = x.astype(self.dtype)
        for v in _CFGS[self.depth]:
            if v == "M":
                if min(x.shape[1], x.shape[2]) >= 2:
                    x = nn.max_pool(x, (2, 2), strides=(2, 2))
                continue
            ch = max(8, int(v * self.width_mult))
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype, use_bias=False)(x)
            x = nn.GroupNorm(num_groups=math.gcd(8, ch), dtype=self.dtype)(x)
            x = nn.relu(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(max(64, int(512 * self.width_mult)), dtype=self.dtype)(x)
        x = nn.relu(x)
        # dropout_rate may be a TRACED scalar (rafiki_tpu.ops.dropout),
        # so a dropout sweep shares one compiled program; falls back to
        # the static attribute when called without one.
        if train:
            from rafiki_tpu.ops.train import dropout as _dropout

            rate = self.dropout if dropout_rate is None else dropout_rate
            x = _dropout(x, rate, self.make_rng("dropout"), deterministic=False)
        return nn.Dense(self.num_classes, dtype=self.dtype)(x)


class Vgg(JaxModel):
    @staticmethod
    def get_knob_config():
        return {
            "depth": CategoricalKnob([11, 13, 16], affects_shape=True),
            "width_mult": CategoricalKnob([0.25, 0.5, 1.0], affects_shape=True),
            "dropout": FloatKnob(0.0, 0.5),
            "learning_rate": FloatKnob(1e-4, 3e-2, is_exp=True),
            "batch_size": CategoricalKnob([64, 128, 256], affects_shape=True),
            "epochs": IntegerKnob(1, 10),
            "seed": FixedKnob(0),
        }

    def build_module(self, num_classes, input_shape):
        return _Vgg(
            depth=int(self.knobs["depth"]),
            width_mult=float(self.knobs["width_mult"]),
            num_classes=num_classes,
            dropout=float(self.knobs["dropout"]),
        )

if __name__ == "__main__":
    # Dev harness run (`python -m rafiki_tpu.models.X`): pin the
    # platform first or the image's sitecustomize TPU hijack hangs
    # backend init when the tunnel is down.
    from rafiki_tpu.utils.backend import honor_env_platform

    honor_env_platform()
    from rafiki_tpu.model.dev import test_model_class

    test_model_class(
        Vgg, "IMAGE_CLASSIFICATION",
        "synthetic://images?classes=10&n=1024&w=32&h=32&c=3&seed=0",
        "synthetic://images?classes=10&n=256&w=32&h=32&c=3&seed=1",
        knobs=dict(depth=11, width_mult=0.25, dropout=0.1, learning_rate=1e-3,
                   batch_size=64, epochs=4, seed=0),
    )
