"""Model zoo: TPU-native model templates mirroring the reference's
examples/models/ (SURVEY.md §2 "Example models", unverified paths):

  FeedForward  ← TfFeedForward.py  (MLP, MNIST-class images)
  Vgg          ← TfVgg16.py        (VGG CNN, CIFAR-10-class images)
  DenseNet     ← PyDenseNet.py     (DenseNet-BC CNN, CIFAR-10)
  SkDt / SkSvm ← SkDt.py, SkSvm.py (sklearn host models)
  PosBiLstm    ← PyBiLstm.py       (BiLSTM POS tagger)
  PosBigramHmm ← BigramHmm.py      (bigram HMM POS tagger)
  Transformer  — no reference analog: text-classifier encoder, the
                 zoo's sharded-lane citizen (docs/sharding.md)
"""

from rafiki_tpu.models.ff import FeedForward

__all__ = ["FeedForward"]


def _optional():
    # Heavier templates are imported lazily by the registry below.
    pass


MODEL_REGISTRY = {
    "FeedForward": ("rafiki_tpu.models.ff", "FeedForward"),
    "Vgg": ("rafiki_tpu.models.vgg", "Vgg"),
    "DenseNet": ("rafiki_tpu.models.densenet", "DenseNet"),
    "SkDt": ("rafiki_tpu.models.sk", "SkDt"),
    "SkSvm": ("rafiki_tpu.models.sk", "SkSvm"),
    "PosBiLstm": ("rafiki_tpu.models.pos_bilstm", "PosBiLstm"),
    "PosBigramHmm": ("rafiki_tpu.models.pos_hmm", "PosBigramHmm"),
    "Transformer": ("rafiki_tpu.models.transformer", "Transformer"),
}


def get_model_class(name: str) -> type:
    import importlib

    if name not in MODEL_REGISTRY:
        raise ValueError(f"Unknown model template {name!r}; known: {sorted(MODEL_REGISTRY)}")
    mod_name, cls_name = MODEL_REGISTRY[name]
    try:
        return getattr(importlib.import_module(mod_name), cls_name)
    except ModuleNotFoundError as e:
        raise ValueError(f"Model template {name!r} is not available: {e}") from e
