"""Bigram HMM POS tagger (host model).

Reference analog: examples/models/pos_tagging/BigramHmm.py (unverified)
— count-based emission/transition tables with Viterbi decoding. Pure
numpy; exists for task-family parity (POS_TAGGING) and as a non-neural
baseline for the advisor to compare against.
"""

from __future__ import annotations

import pickle
from typing import Any, List

import numpy as np

from rafiki_tpu.model.base import BaseModel
from rafiki_tpu.model.dataset import dataset_utils
from rafiki_tpu.model.knobs import FixedKnob, FloatKnob


class PosBigramHmm(BaseModel):
    @staticmethod
    def get_knob_config():
        return {
            "smoothing": FloatKnob(1e-3, 1.0, is_exp=True),
            "seed": FixedKnob(0),
        }

    def __init__(self, **knobs):
        super().__init__(**knobs)
        self._emit = None       # (tags, vocab) log emission
        self._trans = None      # (tags+1, tags) log transition (row -1 = start)
        self._tags = 0
        self._vocab = 0

    def train(self, dataset_uri: str) -> None:
        ds = dataset_utils.load(dataset_uri)
        alpha = float(self.knobs["smoothing"])
        tags = ds.classes
        vocab = int(ds.meta.get("vocab", int(ds.x.max()) + 1))
        emit = np.full((tags, vocab), alpha)
        trans = np.full((tags + 1, tags), alpha)
        for i in range(ds.size):
            prev = tags  # start state
            for j in range(ds.x.shape[1]):
                if ds.mask is not None and not ds.mask[i, j]:
                    break
                tok, tag = int(ds.x[i, j]), int(ds.y[i, j])
                emit[tag, tok] += 1
                trans[prev, tag] += 1
                prev = tag
        self._emit = np.log(emit / emit.sum(axis=1, keepdims=True))
        self._trans = np.log(trans / trans.sum(axis=1, keepdims=True))
        self._tags, self._vocab = tags, vocab

    def _viterbi(self, tokens: np.ndarray) -> List[int]:
        n = len(tokens)
        if n == 0:
            return []
        T = self._tags
        dp = np.zeros((n, T))
        bp = np.zeros((n, T), dtype=np.int32)
        tok0 = min(int(tokens[0]), self._vocab - 1)
        dp[0] = self._trans[T] + self._emit[:, tok0]
        for t in range(1, n):
            tok = min(int(tokens[t]), self._vocab - 1)
            scores = dp[t - 1][:, None] + self._trans[:T]
            bp[t] = scores.argmax(axis=0)
            dp[t] = scores.max(axis=0) + self._emit[:, tok]
        path = [int(dp[-1].argmax())]
        for t in range(n - 1, 0, -1):
            path.append(int(bp[t, path[-1]]))
        return path[::-1]

    def evaluate(self, dataset_uri: str) -> float:
        ds = dataset_utils.load(dataset_uri)
        correct = total = 0
        for i in range(ds.size):
            mask = ds.mask[i] if ds.mask is not None else np.ones(ds.x.shape[1], bool)
            toks = ds.x[i][mask]
            gold = ds.y[i][mask]
            pred = self._viterbi(toks)
            correct += int((np.asarray(pred) == gold).sum())
            total += len(gold)
        return correct / max(total, 1)

    def predict(self, queries: List[Any]) -> List[List[int]]:
        """queries: list of token-id sequences → list of tag-id sequences."""
        return [self._viterbi(np.asarray(q, dtype=np.int64)) for q in queries]

    def dump_parameters(self) -> bytes:
        return pickle.dumps({"emit": self._emit, "trans": self._trans,
                             "tags": self._tags, "vocab": self._vocab})

    def load_parameters(self, blob: bytes) -> None:
        p = pickle.loads(blob)
        self._emit, self._trans = p["emit"], p["trans"]
        self._tags, self._vocab = p["tags"], p["vocab"]
