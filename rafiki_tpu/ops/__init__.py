"""JAX/XLA compute path: jit'd step factories, losses, metrics.

This layer replaces the reference's delegation to TF1/PyTorch CUDA
kernels (SURVEY.md §2 language note) with first-party JAX programs:
everything that touches the device goes through here or through
``rafiki_tpu.parallel``.
"""

from rafiki_tpu.ops.train import (
    DYNAMIC_KNOBS,
    Program,
    TrainLoop,
    clear_program_cache,
    cross_entropy_loss,
    dropout,
    get_program,
    make_eval_step,
    make_predict_fn,
    make_train_step,
    program_cache_stats,
)

__all__ = [
    "DYNAMIC_KNOBS",
    "Program",
    "TrainLoop",
    "clear_program_cache",
    "cross_entropy_loss",
    "dropout",
    "get_program",
    "make_train_step",
    "make_eval_step",
    "make_predict_fn",
    "program_cache_stats",
]
