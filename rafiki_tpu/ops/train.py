"""Generic jit'd training machinery shared by all JAX model templates.

Reference contrast: in Rafiki the inner epoch/step loop lives inside
each model template's ``train()`` (TF session.run / torch .backward(),
100% of GPU time — SURVEY.md §3.1). Here the loop is first-party and
TPU-shaped:

  * one compiled XLA program per (knob-signature, batch-shape); the
    step is ``jax.jit`` with donated carry state, so params/opt-state
    stay resident in HBM and the host only ships input batches;
  * optional within-trial data parallelism: pass a ``Mesh`` and batches
    are sharded over the ``"dp"`` axis while state is replicated — XLA
    inserts the gradient all-reduce (psum over ICI) automatically from
    the sharding annotations (no hand-written collectives needed);
  * compute dtype is bfloat16 by default (MXU-native), parameters and
    the optimizer state stay float32.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Batch = Dict[str, np.ndarray]
Params = Any
LossFn = Callable[[Params, Dict[str, jnp.ndarray], jax.Array], Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                       valid: Optional[jnp.ndarray] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Masked softmax cross entropy + accuracy.

    logits: (..., C) float; labels: (...) int32, -1 = ignore;
    valid: optional (...) bool combined with the label mask.
    Returns (mean loss, mean accuracy) over unmasked elements.
    """
    mask = labels >= 0
    if valid is not None:
        mask = jnp.logical_and(mask, valid)
    labels_safe = jnp.where(mask, labels, 0)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels_safe[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1)
    loss = jnp.where(mask, nll, 0.0).sum() / denom
    correct = (jnp.argmax(logits, axis=-1) == labels_safe) & mask
    acc = correct.sum() / denom
    return loss, acc


@dataclass
class _ShardingPlan:
    """Shardings for (state, batch) on an optional dp mesh."""

    mesh: Optional[Mesh]
    state_sharding: Optional[NamedSharding]
    batch_sharding: Optional[NamedSharding]

    @classmethod
    def build(cls, mesh: Optional[Mesh]) -> "_ShardingPlan":
        if mesh is None:
            return cls(None, None, None)
        return cls(
            mesh=mesh,
            state_sharding=NamedSharding(mesh, P()),           # replicated
            batch_sharding=NamedSharding(mesh, P("dp")),        # batch-sharded
        )

    def put_batch(self, batch: Batch) -> Dict[str, jax.Array]:
        if self.batch_sharding is None:
            return {k: jnp.asarray(v) for k, v in batch.items()}
        return {k: jax.device_put(v, self.batch_sharding) for k, v in batch.items()}

    def put_state(self, state):
        if self.state_sharding is None:
            return state
        return jax.device_put(state, self.state_sharding)


def make_train_step(loss_fn: LossFn, optimizer: optax.GradientTransformation,
                    plan: _ShardingPlan):
    """Build the donated, jit'd SGD step.

    state = (params, opt_state, step, rng). The whole carry is donated:
    XLA reuses the HBM buffers in place, so per-step host traffic is
    just the input batch.
    """

    def step(state, batch):
        params, opt_state, step_i, rng = state
        rng, sub = jax.random.split(rng)
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(params, batch, sub)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        metrics = dict(metrics, loss=loss)
        return (params, opt_state, step_i + 1, rng), metrics

    kwargs = {}
    if plan.mesh is not None:
        # Shardings are pytree-prefixes: replicate all of state, shard all of batch.
        kwargs = dict(
            in_shardings=(plan.state_sharding, plan.batch_sharding),
            out_shardings=(plan.state_sharding, plan.state_sharding),
        )
    return jax.jit(step, donate_argnums=(0,), **kwargs)


def make_eval_step(apply_fn, plan: _ShardingPlan):
    """Jit'd eval step returning (#correct, #valid) so the host can sum."""

    def step(params, batch):
        logits = apply_fn(params, batch)
        labels = batch["y"]
        mask = labels >= 0
        if "valid" in batch:
            v = batch["valid"]
            mask = jnp.logical_and(mask, v.reshape(v.shape + (1,) * (mask.ndim - v.ndim)))
        labels_safe = jnp.where(mask, labels, 0)
        correct = (jnp.argmax(logits, axis=-1) == labels_safe) & mask
        return correct.sum(), mask.sum()

    kwargs = {}
    if plan.mesh is not None:
        kwargs = dict(in_shardings=(plan.state_sharding, plan.batch_sharding))
    return jax.jit(step, **kwargs)


def make_predict_fn(apply_fn, plan: _ShardingPlan):
    """Jit'd forward returning probabilities."""

    def fwd(params, batch):
        logits = apply_fn(params, batch)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    kwargs = {}
    if plan.mesh is not None:
        kwargs = dict(in_shardings=(plan.state_sharding, plan.batch_sharding))
    return jax.jit(fwd, **kwargs)


class TrainLoop:
    """Drives epochs of jit'd steps over a Dataset for one trial.

    Parameters
    ----------
    init_fn: rng -> params
    apply_fn: (params, batch) -> logits
    loss_fn: (params, batch, rng) -> (loss, metrics dict)
    optimizer: optax transform
    mesh: optional dp Mesh (within-trial data parallelism). With a mesh
        of k devices the global batch is sharded k ways; gradients are
        all-reduced over ICI by XLA (from sharding annotations).
    """

    def __init__(self, init_fn, apply_fn, loss_fn, optimizer,
                 mesh: Optional[Mesh] = None, seed: int = 0):
        self.plan = _ShardingPlan.build(mesh)
        self.apply_fn = apply_fn
        self.optimizer = optimizer
        self._train_step = make_train_step(loss_fn, optimizer, self.plan)
        self._eval_step = make_eval_step(apply_fn, self.plan)
        self._predict = make_predict_fn(apply_fn, self.plan)
        rng = jax.random.PRNGKey(seed)
        rng, init_rng = jax.random.split(rng)
        params = init_fn(init_rng)
        opt_state = optimizer.init(params)
        self.state = self.plan.put_state((params, opt_state, jnp.zeros((), jnp.int32), rng))

    @property
    def params(self):
        return self.state[0]

    @params.setter
    def params(self, params):
        _, opt_state, step, rng = self.state
        self.state = (self.plan.put_state(params), opt_state, step, rng)

    def run_epoch(self, dataset, batch_size: int, epoch_seed: int,
                  on_metrics: Optional[Callable[[int, Dict[str, float]], None]] = None) -> Dict[str, float]:
        if dataset.size < batch_size:
            raise ValueError(
                f"Dataset has {dataset.size} examples < batch_size={batch_size}; "
                f"the epoch would run zero steps")
        count = 0
        metrics = None
        for i, batch in enumerate(dataset.batches(batch_size, shuffle=True, seed=epoch_seed,
                                                  drop_remainder=True)):
            batch.pop("valid", None)
            dev_batch = self.plan.put_batch(batch)
            self.state, metrics = self._train_step(self.state, dev_batch)
            count += 1
            if on_metrics is not None and (i % 50 == 0):
                on_metrics(i, {k: float(v) for k, v in metrics.items()})
        # Final-step metrics are the epoch result (one host sync per epoch).
        return {k: float(v) for k, v in metrics.items()} if count else {}

    def evaluate(self, dataset, batch_size: int) -> float:
        total_correct = 0
        total = 0
        for batch in dataset.batches(batch_size, shuffle=False, drop_remainder=False):
            dev_batch = self.plan.put_batch(batch)
            c, n = self._eval_step(self.state[0], dev_batch)
            total_correct += int(c)
            total += int(n)
        return total_correct / max(total, 1)

    def predict_proba(self, x: np.ndarray, batch_size: int, extra: Optional[Batch] = None) -> np.ndarray:
        """Forward a query array; pads to full batches, returns (N, ..., C) probs."""
        n = x.shape[0]
        outs = []
        for start in range(0, n, batch_size):
            chunk = x[start : start + batch_size]
            pad = batch_size - chunk.shape[0]
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, axis=0)])
            batch = {"x": chunk}
            if extra:
                batch.update(extra)
            probs = np.asarray(self._predict(self.state[0], self.plan.put_batch(batch)))
            outs.append(probs[: batch_size - pad] if pad else probs)
        return np.concatenate(outs) if outs else np.zeros((0,))
