"""Generic jit'd training machinery shared by all JAX model templates.

Reference contrast: in Rafiki the inner epoch/step loop lives inside
each model template's ``train()`` (TF session.run / torch .backward(),
100% of GPU time — SURVEY.md §3.1). Here the loop is first-party and
TPU-shaped:

  * one compiled XLA program per *program key* — NOT per trial. The
    compiled steps live in a :class:`Program`, cached process-wide by
    :func:`get_program`, so back-to-back trials whose traced
    computation is identical reuse the same executables with zero
    retrace/recompile (SURVEY.md §7 "compile-time vs trial throughput:
    this is where the ≥8x trials/hour target is won or lost");
  * high-churn continuous hyperparameters (learning rate, warmup
    horizon, dropout rate) are *dynamic*: they ride in the train state
    as traced f32 scalars instead of baking into the XLA program, so
    an AutoML sweep over them hits one compiled program;
  * the step is ``jax.jit`` with donated carry state, so params /
    opt-state stay resident in HBM and the host only ships batches;
  * optional within-trial data parallelism: pass a ``Mesh`` and batches
    are sharded over the ``"dp"`` axis while state is replicated — XLA
    inserts the gradient all-reduce (psum over ICI) automatically from
    the sharding annotations (no hand-written collectives needed);
  * compute dtype is bfloat16 by default (MXU-native), parameters and
    the optimizer state stay float32.
"""

from __future__ import annotations

import inspect
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Hashable, Iterable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rafiki_tpu import telemetry
from rafiki_tpu.obs.health import DivergenceError, HealthMonitor
from rafiki_tpu.obs.health import sentinel as _sentinel

Batch = Dict[str, np.ndarray]
Params = Any
# Canonical loss signature: (params, batch, rng, hyper) -> (loss, metrics).
# 3-arg (params, batch, rng) losses are auto-wrapped for compatibility.
LossFn = Callable[..., Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]]

# Knob names that are structurally dynamic in the standard template
# path: they reach the computation only through the traced hyper dict
# (lr / warmup via the update scaling, dropout via apply), or never
# reach the trace at all (epochs = python loop count, seed = init rng).
# Model templates must not bake these into module attributes.
DYNAMIC_KNOBS = frozenset({"learning_rate", "warmup_steps", "dropout", "epochs", "seed"})


def cross_entropy_loss(logits: jnp.ndarray, labels: jnp.ndarray,
                       valid: Optional[jnp.ndarray] = None) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Masked softmax cross entropy + accuracy.

    logits: (..., C) float; labels: (...) int32, -1 = ignore;
    valid: optional (...) bool combined with the label mask.
    Returns (mean loss, mean accuracy) over unmasked elements.
    """
    mask = labels >= 0
    if valid is not None:
        mask = jnp.logical_and(mask, valid)
    labels_safe = jnp.where(mask, labels, 0)
    logits = logits.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels_safe[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1)
    loss = jnp.where(mask, nll, 0.0).sum() / denom
    correct = (jnp.argmax(logits, axis=-1) == labels_safe) & mask
    acc = correct.sum() / denom
    return loss, acc


def dropout(x: jnp.ndarray, rate, rng, deterministic: bool) -> jnp.ndarray:
    """Inverted dropout with a *traced* rate.

    Unlike ``flax.linen.Dropout`` (whose rate is a static module
    attribute → every distinct rate is a distinct XLA program), the
    rate here may be a traced scalar, so an AutoML sweep over dropout
    reuses one compiled program.
    """
    if deterministic or rng is None:
        return x
    rate = jnp.asarray(rate, jnp.float32)
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    scale = jnp.where(rate < 1.0, 1.0 / jnp.maximum(1.0 - rate, 1e-6), 0.0)
    return jnp.where(keep, x * scale.astype(x.dtype), jnp.zeros_like(x))


@dataclass
class _ShardingPlan:
    """Shardings for (state, batch) on an optional dp mesh."""

    mesh: Optional[Mesh]
    state_sharding: Optional[NamedSharding]
    batch_sharding: Optional[NamedSharding]

    @classmethod
    def build(cls, mesh: Optional[Mesh]) -> "_ShardingPlan":
        if mesh is None:
            return cls(None, None, None)
        return cls(
            mesh=mesh,
            state_sharding=NamedSharding(mesh, P()),           # replicated
            batch_sharding=NamedSharding(mesh, P("dp")),        # batch-sharded
        )

    def put_batch(self, batch: Batch) -> Dict[str, jax.Array]:
        if self.batch_sharding is None:
            return {k: jnp.asarray(v) for k, v in batch.items()}
        if not self.batch_sharding.is_fully_addressable:
            # Mesh spans processes (multi-host dp): device_put cannot
            # target non-addressable devices; materialize only this
            # process's shards of the (identical-everywhere) batch.
            from rafiki_tpu.parallel.multihost import global_put

            return global_put(batch, self.batch_sharding)
        return {k: jax.device_put(v, self.batch_sharding) for k, v in batch.items()}

    def put_state(self, state):
        if self.state_sharding is None:
            return state
        if not self.state_sharding.is_fully_addressable:
            # Multi-host: leave host leaves alone — jit treats host
            # values as replicated, and device leaves were produced by
            # the jitted init with the right global sharding already.
            return state
        return jax.device_put(state, self.state_sharding)


def _as_hyper_loss(loss_fn: LossFn) -> LossFn:
    """Accept both (params, batch, rng) and (params, batch, rng, hyper)."""
    try:
        n = len(inspect.signature(loss_fn).parameters)
    except (TypeError, ValueError):
        n = 4
    if n >= 4:
        return loss_fn
    return lambda params, batch, rng, hyper: loss_fn(params, batch, rng)


def effective_lr(hyper: Dict[str, jnp.ndarray], step_i) -> jnp.ndarray:
    """Linear warmup to hyper["lr"] over hyper["warmup"] steps — all
    traced, so warmup horizon and peak lr never force a recompile."""
    warmup = jnp.maximum(hyper.get("warmup", jnp.float32(1.0)), 1.0)
    frac = jnp.minimum((step_i.astype(jnp.float32) + 1.0) / warmup, 1.0)
    return hyper["lr"] * frac


def _make_step_fns(init_fn, apply_fn, loss_fn: LossFn,
                   optimizer: optax.GradientTransformation,
                   dynamic_lr: bool):
    """The single-trial step closures shared by :class:`Program` and
    :class:`PackedProgram`: (train_step, eval_step, predict, init_all).
    Pure per-trial functions — the packed path vmaps them over a
    leading trial axis instead of re-deriving the math."""
    loss4 = _as_hyper_loss(loss_fn)

    def train_step(state, batch):
        params, opt_state, step_i, rng, hyper = state
        batch = dict(batch)
        poison = batch.pop("_health_poison", None)
        if poison is not None and getattr(poison, "ndim", 0):
            # dp-mesh batches carry the poison as a batch-length column
            # (a rank-0 leaf cannot satisfy the P("dp") batch-sharding
            # prefix); every element is the same step multiplier.
            poison = poison[0]
        rng, sub = jax.random.split(rng)
        (loss, metrics), grads = jax.value_and_grad(loss4, has_aux=True)(
            params, batch, sub, hyper)
        if poison is not None:
            # Chaos ``train.nan`` carrier (docs/chaos.md): the poison is
            # a per-step f32 multiplier, 1.0 everywhere except the
            # target step (NaN). Multiply-by-1.0 is IEEE bit-exact, so
            # unpoisoned steps — and unpoisoned pack members, whose
            # whole column is ones — stay bit-identical to a clean run.
            grads = jax.tree.map(lambda g: g * poison.astype(g.dtype), grads)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        if dynamic_lr:
            lr = effective_lr(hyper, step_i)
            updates = jax.tree.map(lambda u: (-lr).astype(u.dtype) * u, updates)
        params = optax.apply_updates(params, updates)
        # Health sentinels ride the metric dict as device scalars —
        # unconditionally, so every cached program shares one trace and
        # one metric structure; they read the step's intermediates but
        # never touch the rng chain or the update math (bit-neutral).
        metrics = dict(metrics, loss=loss,
                       **_sentinel.bundle(loss, grads, updates, params))
        return (params, opt_state, step_i + 1, rng, hyper), metrics

    def eval_step(params, batch):
        logits = apply_fn(params, batch)
        labels = batch["y"]
        mask = labels >= 0
        if "valid" in batch:
            v = batch["valid"]
            mask = jnp.logical_and(mask, v.reshape(v.shape + (1,) * (mask.ndim - v.ndim)))
        labels_safe = jnp.where(mask, labels, 0)
        correct = (jnp.argmax(logits, axis=-1) == labels_safe) & mask
        return correct.sum(), mask.sum()

    def predict(params, batch):
        logits = apply_fn(params, batch)
        return jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    def init_all(rng):
        params = init_fn(rng)
        return params, optimizer.init(params)

    return train_step, eval_step, predict, init_all


class Program:
    """The compiled, trial-independent half of a training loop.

    Holds the jit'd init / train / eval / predict callables plus the
    optimizer and sharding plan. A Program is safe to share across
    trials (and across worker threads) whose traced computation is
    identical: per-trial state (params, opt state, rng, hyper scalars)
    lives in :class:`TrainLoop`, never here.

    Two lr modes:
      * ``dynamic_lr=True`` (standard template path): ``optimizer`` is
        lr-free (e.g. ``optax.scale_by_adam()``); the step scales
        updates by ``-effective_lr(hyper, step)``. Trials differing in
        lr / warmup share this Program.
      * ``dynamic_lr=False`` (custom ``make_optimizer`` overrides): the
        optimizer carries its own lr; reuse requires identical knobs.
    """

    def __init__(self, init_fn, apply_fn, loss_fn: LossFn,
                 optimizer: optax.GradientTransformation,
                 plan: _ShardingPlan, dynamic_lr: bool = True):
        self.plan = plan
        self.optimizer = optimizer
        self.dynamic_lr = dynamic_lr
        self.apply_fn = apply_fn
        train_step, eval_step, predict, init_all = _make_step_fns(
            init_fn, apply_fn, loss_fn, optimizer, dynamic_lr)

        # Whole-epoch programs over a DEVICE-RESIDENT dataset (single-
        # device path): one lax.scan per epoch, per-step batches
        # gathered on device from shuffled indices — the host ships
        # only the permutation, not n_steps batches. Over a slow
        # host<->device link the per-step feed dominates the step
        # itself; on real hardware this still removes n_steps dispatch
        # round-trips per epoch.
        def train_epoch(state, X, Y, idx, poison=None):
            # ``poison`` is the optional (n_steps,) chaos train.nan
            # column; None (a leafless scan xs node) and array calls
            # are two separate traces of one Program, so clean runs
            # never carry the poison multiply.
            def body(st, xs):
                ib, pz = xs
                batch = {"x": jnp.take(X, ib, axis=0),
                         "y": jnp.take(Y, ib, axis=0)}
                if pz is not None:
                    batch["_health_poison"] = pz
                return train_step(st, batch)

            state, ms = jax.lax.scan(body, state, (idx, poison))
            # Final-step metrics are the epoch result (parity with the
            # python-loop path); the health series reduces on-device to
            # its epoch-boundary summary (docs/health.md).
            rest, health = _sentinel.split(ms)
            out = {k: v[-1] for k, v in rest.items()}
            out.update(_sentinel.reduce_epoch(health))
            return state, out

        def eval_epoch(params, X, Y, idx):
            def body(carry, ib):
                batch = {"x": jnp.take(X, ib, axis=0),
                         "y": jnp.take(Y, ib, axis=0)}
                c, n = eval_step(params, batch)
                return (carry[0] + c, carry[1] + n), None

            zero = jnp.zeros((), jnp.int32)
            (c, n), _ = jax.lax.scan(body, (zero, zero), idx)
            return c, n

        tkw: Dict[str, Any] = {}
        ekw: Dict[str, Any] = {}
        ikw: Dict[str, Any] = {}
        if plan.mesh is not None:
            tkw = dict(in_shardings=(plan.state_sharding, plan.batch_sharding),
                       out_shardings=(plan.state_sharding, plan.state_sharding))
            ekw = dict(in_shardings=(plan.state_sharding, plan.batch_sharding))
            ikw = dict(out_shardings=plan.state_sharding)
        self.train_step = jax.jit(train_step, donate_argnums=(0,), **tkw)
        self.eval_step = jax.jit(eval_step, **ekw)
        self.predict = jax.jit(predict, **ekw)
        self.init = jax.jit(init_all, **ikw)
        self.train_epoch = jax.jit(train_epoch, donate_argnums=(0,))
        self.eval_epoch = jax.jit(eval_epoch)


# ---------------------------------------------------------------------------
# Process-wide program cache
# ---------------------------------------------------------------------------
#
# Key insight for AutoML throughput: a worker process runs many trials
# back to back; without reuse, every trial pays a full XLA retrace +
# recompile (measured ~13s for VGG16 on a v5e chip vs ~1.2s of actual
# training). The cache below makes the second same-key trial free.
#
# Granularity note: the per-key lock deduplicates *Program
# construction* (the traced-closure objects); the XLA executables
# inside compile lazily at each jitted callable's first call per
# (shape, device) signature. That is the right granularity here:
# LocalScheduler's concurrent worker threads run on *different*
# devices, whose executables are necessarily distinct compiles, while
# same-device repeat trials (the steady state) hit the jit cache.
# Cross-process dedup is the persistent XLA compilation cache's job
# (utils.backend.enable_compilation_cache).
#
# The cache is capped (LRU): a long sweep over shape-affecting knobs
# evicts the oldest programs instead of pinning every compiled
# executable for the process lifetime. Live TrainLoops keep their
# Program via their own reference, so eviction is always safe.

_PROGRAM_CACHE_CAP = 64

_programs: "Dict[Hashable, Program]" = {}  # insertion-ordered → LRU via re-insert
_build_locks: Dict[Hashable, threading.Lock] = {}
# last_miss_ts (epoch seconds, comparable to the meta store's trial
# timestamps) lets the bench separate trials that ran entirely after
# the final cold compile — the honest steady-state population.
_stats = {"hits": 0, "misses": 0, "evictions": 0, "last_miss_ts": 0.0}
_guard = threading.Lock()


def mesh_cache_key(mesh: Optional[Mesh]) -> Hashable:
    if mesh is None:
        return None
    return (tuple(mesh.axis_names), tuple(str(d) for d in mesh.devices.flat))


def get_program(key: Hashable, builder: Callable[[], Program]) -> Program:
    """Return the cached Program for ``key``, building it (once, even
    under concurrent callers) if absent.

    Contract: ``key`` must fully determine the builder's inputs
    (init/apply/loss closures, optimizer, sharding plan) — on a hit the
    caller's builder is IGNORED in favor of the cached Program. The
    JaxModel path guarantees this by keying every knob that can reach
    the trace; direct callers must do the same.
    """
    with _guard:
        prog = _programs.get(key)
        if prog is not None:
            _programs[key] = _programs.pop(key)  # refresh LRU position
            _stats["hits"] += 1
            telemetry.inc("program_cache.hits")
            return prog
        lock = _build_locks.setdefault(key, threading.Lock())
    with lock:
        with _guard:
            prog = _programs.get(key)
            if prog is not None:
                _stats["hits"] += 1
                telemetry.inc("program_cache.hits")
                return prog
        try:
            with telemetry.span("program.build"):
                prog = builder()
        except BaseException:
            # Drop the build lock entry when the builder raises (e.g. a
            # knob combo whose trace fails) — _build_locks must not
            # outgrow the LRU-capped _programs.
            with _guard:
                _build_locks.pop(key, None)
            raise
        with _guard:
            # Publish and retire the build lock atomically: popping the
            # lock before publishing would let a concurrent caller
            # install a fresh lock and build a duplicate.
            _programs[key] = prog
            _stats["misses"] += 1
            _stats["last_miss_ts"] = time.time()
            _build_locks.pop(key, None)
            evicted = 0
            while len(_programs) > _PROGRAM_CACHE_CAP:
                _programs.pop(next(iter(_programs)))
                _stats["evictions"] += 1
                evicted += 1
        telemetry.inc("program_cache.misses")
        if evicted:
            telemetry.inc("program_cache.evictions", evicted)
    return prog


def program_cache_stats() -> Dict[str, int]:
    with _guard:
        return dict(_stats, size=len(_programs))


# The cache's lifetime stats surface through the telemetry registry
# too: /metrics and BENCH snapshots see hit/miss/eviction/size without
# a second bookkeeping path (the counters above cover deltas; this
# collector is the authoritative absolute view, reset-proof).
telemetry.register_collector("program_cache", program_cache_stats)


def clear_program_cache() -> None:
    with _guard:
        _programs.clear()
        _build_locks.clear()
        _stats.update(hits=0, misses=0, evictions=0, last_miss_ts=0.0)


# ---------------------------------------------------------------------------
# Device-resident datasets
# ---------------------------------------------------------------------------
#
# The epoch-scan fast path wants the whole dataset in HBM. Device
# copies are cached ON the (host-side, LRU-cached) Dataset object, so
# their lifetime follows the dataset cache's: trials of one job reuse
# one upload, and eviction of the host dataset frees the device
# arrays. NOTE this only amortizes when callers pass the SAME Dataset
# object across trials — JaxModel guarantees it for identity
# preprocess (see _prepared_dataset); a knob-dependent custom
# preprocess re-uploads per call by design.

_DEVICE_DATASET_MAX_MB_ENV = "RAFIKI_DEVICE_DATASET_MAX_MB"
_DEVICE_DATASET_MAX_MB_DEFAULT = 2048


def device_dataset_cap_bytes() -> int:
    import os

    return int(float(os.environ.get(_DEVICE_DATASET_MAX_MB_ENV,
                                    _DEVICE_DATASET_MAX_MB_DEFAULT)) * 1e6)


def _default_device_key():
    dev = getattr(jax.config, "jax_default_device", None)
    return dev if dev is not None else jax.devices()[0]


def get_device_dataset(dataset) -> Tuple[jax.Array, jax.Array]:
    """The dataset's (x, y) as device arrays, cached per target device.

    setdefault keeps concurrent first-touchers (worker threads on
    different devices sharing one LRU-cached dataset) from replacing
    each other's cache dict; a same-device double upload is a benign
    last-writer-wins."""
    cache = dataset.__dict__.setdefault("_device_arrays", {})
    key = _default_device_key()
    if key not in cache:
        cache[key] = (jnp.asarray(dataset.x), jnp.asarray(dataset.y))
    return cache[key]


# ---------------------------------------------------------------------------
# TrainLoop: per-trial state driving a (possibly shared) Program
# ---------------------------------------------------------------------------


class TrainLoop:
    """Drives epochs of jit'd steps over a Dataset for one trial.

    Parameters
    ----------
    init_fn: rng -> params
    apply_fn: (params, batch) -> logits
    loss_fn: (params, batch, rng[, hyper]) -> (loss, metrics dict)
    optimizer: optax transform. With ``hyper`` containing "lr" this
        must be lr-free (default: ``optax.scale_by_adam()``); without
        hyper it is a complete optimizer (default: adam(1e-3)).
    mesh: optional dp Mesh (within-trial data parallelism). With a mesh
        of k devices the global batch is sharded k ways; gradients are
        all-reduced over ICI by XLA (from sharding annotations).
    hyper: optional dict of dynamic f32 scalars carried in the state
        ("lr", "warmup", "dropout", ...). These are traced, so trials
        differing only in them share one compiled program.
    program_key: optional hashable. When given, the compiled Program is
        fetched from / stored in the process-wide cache under
        (program_key, mesh) — the compile-amortization path.
    initial_state: optional full (params, opt_state, step, rng, hyper)
        tuple to adopt INSTEAD of running init — the detached-member
        path: a trial evicted from a pack mid-sweep continues (or just
        evaluates/serves) through an ordinary serial loop holding the
        state sliced out of the stacked pack.
    """

    def __init__(self, init_fn, apply_fn, loss_fn, optimizer=None,
                 mesh: Optional[Mesh] = None, seed: int = 0,
                 hyper: Optional[Dict[str, float]] = None,
                 program_key: Optional[Hashable] = None,
                 initial_state=None):
        dynamic_lr = hyper is not None and "lr" in hyper
        if optimizer is None:
            optimizer = optax.scale_by_adam() if dynamic_lr else optax.adam(1e-3)

        def build() -> Program:
            return Program(init_fn, apply_fn, loss_fn, optimizer,
                           _ShardingPlan.build(mesh), dynamic_lr=dynamic_lr)

        if program_key is not None:
            self._perf_key = (program_key, mesh_cache_key(mesh), dynamic_lr)
            self.program = get_program(self._perf_key, build)
        else:
            self._perf_key = ("serial", "anon", id(self))
            self.program = build()
        self.plan = self.program.plan
        self.apply_fn = apply_fn
        self.optimizer = self.program.optimizer
        # Numerics health plane (docs/health.md): consumes the in-graph
        # sentinel scalars at each epoch boundary; serial loops fail
        # fast (DivergenceError) on divergence.
        self.health = HealthMonitor(str(self._perf_key))
        # Back-compat aliases (bench/tests poke the private names).
        self._train_step = self.program.train_step
        self._eval_step = self.program.eval_step
        self._predict = self.program.predict

        if initial_state is not None:
            self.state = self.plan.put_state(initial_state)
            return
        hyper_dev = {k: jnp.float32(v) for k, v in (hyper or {}).items()}
        rng = jax.random.PRNGKey(seed)
        rng, init_rng = jax.random.split(rng)
        params, opt_state = self.program.init(init_rng)
        self.state = self.plan.put_state(
            (params, opt_state, jnp.zeros((), jnp.int32), rng, hyper_dev))

    @property
    def params(self):
        return self.state[0]

    @params.setter
    def params(self, params):
        _, opt_state, step, rng, hyper = self.state
        self.state = (self.plan.put_state(params), opt_state, step, rng, hyper)

    @property
    def hyper(self) -> Dict[str, jax.Array]:
        return self.state[4]

    def _fits_device_fast_path(self, dataset) -> bool:
        """Single-device x/y datasets small enough to live in HBM run
        as one lax.scan per epoch over a device-resident copy."""
        return (self.plan.mesh is None
                and getattr(dataset, "mask", None) is None
                and dataset.x.nbytes + dataset.y.nbytes <= device_dataset_cap_bytes())

    def run_epoch(self, dataset, batch_size: int, epoch_seed: int,
                  on_metrics: Optional[Callable[[int, Dict[str, float]], None]] = None) -> Dict[str, float]:
        if dataset.size < batch_size:
            raise ValueError(
                f"Dataset has {dataset.size} examples < batch_size={batch_size}; "
                f"the epoch would run zero steps")
        if self.plan.mesh is not None:
            # Chaos site for collective streams: every epoch of a dp
            # (possibly multi-process) run passes through here, so a
            # kill keyed to a follower process lands while its peers
            # are inside (or about to enter) the epoch's all-reduces —
            # the distributed-training failure mode the scheduler's
            # whole-group teardown exists for. Keyed by process index
            # AND worker id (the id carries the -rN restart suffix, so
            # `unless=-r` scopes a kill to the first incarnation).
            import os as _os

            from rafiki_tpu import chaos as _chaos

            _chaos.hook("collective.step",
                        key=f"p{jax.process_index()}:"
                            f"{_os.environ.get('RAFIKI_WORKER_ID', '')}")
        fast = on_metrics is None and self._fits_device_fast_path(dataset)
        # Pre-epoch host snapshot for the replay capsule: the epoch
        # program donates its input buffers, so the "state before the
        # bad epoch" must be banked BEFORE dispatch — and before the
        # timer, so the copy never pollutes step_s or the perf
        # sentinel's step-time distribution. No-op when capsules are
        # off, and skipped on the python path (no index matrix there,
        # so no replayable capsule to bank state for).
        snap = self.health.snapshot_state(self.state) if fast else None
        t_epoch = time.monotonic()
        # Chaos site INSIDE the timed region (unlike collective.step
        # above): an injected delay here inflates the measured epoch
        # wall, which is exactly what the perf sentinel's anomaly
        # detector watches — perf_smoke.py drives it through this site.
        from rafiki_tpu import chaos as _chaos

        _chaos.hook("train.epoch", key=str(self._perf_key))
        n_steps = dataset.size // batch_size
        poison = self._chaos_poison(n_steps)
        if fast:
            X, Y = get_device_dataset(dataset)
            perm = np.random.default_rng(epoch_seed).permutation(dataset.size)
            idx = perm[: n_steps * batch_size].reshape(
                n_steps, batch_size).astype(np.int32)
            if not getattr(self, "_warm", False):
                from rafiki_tpu.obs.perf import profiler as _profiler

                _profiler.capture_cost(self._perf_key,
                                       self.program.train_epoch,
                                       self.state, X, Y, idx, poison)
            self.state, metrics = self.program.train_epoch(
                self.state, X, Y, idx, poison)
            out = {k: float(v) for k, v in metrics.items()}
            self._record_epoch(t_epoch, feed_s=0.0)
            self._health_check(out, t_epoch, epoch_seed, idx, poison, snap)
            return out
        count = 0
        metrics = None
        feed_s = 0.0
        health_steps = []
        # One-slot prefetch (double buffering): batch i+1's host→device
        # put is issued right after step i is DISPATCHED — jit dispatch
        # is async, so the transfer overlaps the device step instead of
        # serializing with it (train.host_feed_s stops adding to
        # train.step_s on datasets that miss the device-resident path).
        batches = dataset.batches(batch_size, shuffle=True, seed=epoch_seed,
                                  drop_remainder=True)

        def put_next():
            nonlocal feed_s
            batch = next(batches, None)
            if batch is None:
                return None
            batch.pop("valid", None)
            t_feed = time.monotonic()
            dev = self.plan.put_batch(batch)
            # lint: disable=RF007 — feed_s accumulator for the ledger split
            feed_s += time.monotonic() - t_feed
            return dev

        dev_batch = put_next()
        if dev_batch is not None and not getattr(self, "_warm", False):
            from rafiki_tpu.obs.perf import profiler as _profiler

            _profiler.capture_cost(self._perf_key, self._train_step,
                                   self.state, dev_batch)
        while dev_batch is not None:
            if poison is not None and count < n_steps:
                pz = jnp.float32(poison[count])
                if self.plan.mesh is not None:
                    # The dp batch sharding is a rank-≥1 prefix; ship the
                    # step multiplier as a batch-length column it can
                    # shard (train_step reads one element back out).
                    pz = jnp.full((batch_size,), pz, jnp.float32)
                dev_batch = dict(dev_batch, _health_poison=pz)
            self.state, metrics = self._train_step(self.state, dev_batch)
            # Device scalars appended as-is: the per-step health series
            # syncs to the host ONCE, at the epoch-boundary reduction.
            health_steps.append({k: v for k, v in metrics.items()
                                 if k.startswith(_sentinel.PREFIX)})
            dev_batch = put_next()  # overlaps the in-flight step
            if on_metrics is not None and (count % 50 == 0):
                on_metrics(count, {k: float(v) for k, v in metrics.items()
                                   if not k.startswith(_sentinel.PREFIX)})
            count += 1
        # Final-step metrics are the epoch result (one host sync per epoch).
        out = {k: float(v) for k, v in metrics.items()
               if not k.startswith(_sentinel.PREFIX)} if count else {}
        self._record_epoch(t_epoch, feed_s)
        if count:
            series = {k: jnp.stack([h[k] for h in health_steps])
                      for k in health_steps[0]}
            out.update({k: float(v) for k, v
                        in _sentinel.reduce_epoch(series).items()})
            # No index matrix on this path -> detection and containment
            # only; the monitor skips the replay capsule.
            self._health_check(out, t_epoch, epoch_seed, None, poison, None)
        return out

    def _chaos_poison(self, n_steps: int) -> np.ndarray:
        """Chaos site ``train.nan``: when an active plane arms it for
        this loop's key, corrupt ONE step's gradients (step
        ``n_steps // 2``) via a per-step poison multiplier column
        (docs/chaos.md). The column is ALWAYS present (all-ones when
        quiet): multiplying grads by a runtime operand changes XLA's
        fusion of the surrounding reductions, so a poison-free trace
        would NOT be bit-identical to the 1.0-multiplier trace. One
        uniform trace keeps clean epochs, faulted-run survivors, and
        capsule replays all in the same program — the bit-parity the
        health plane's replay verification depends on."""
        from rafiki_tpu import chaos as _chaos

        poison = np.ones(n_steps, np.float32)
        if (_chaos.active() is not None
                and _chaos.hook("train.nan",
                                key=str(self._perf_key)) is not None):
            poison[n_steps // 2] = np.nan
        return poison

    def _health_check(self, out: Dict[str, float], t0: float,
                      epoch_seed: int, idx, poison, snapshot) -> None:
        """Epoch-boundary health gate: strip the sentinel keys from the
        caller-visible metric dict (the JaxModel/logger contract
        predates the health plane) and fail the trial fast on a
        divergence verdict."""
        health = {k: out.pop(k) for k in list(out)
                  if k.startswith(_sentinel.PREFIX)}
        verdict = self.health.observe(health, t0=t0, epoch_seed=epoch_seed,
                                      idx=idx, poison=poison,
                                      snapshot=snapshot)
        if verdict is not None:
            raise DivergenceError(verdict)

    def _record_epoch(self, t0: float, feed_s: float) -> None:
        """Compile-vs-step-vs-feed attribution at epoch granularity: the
        first epoch of a TrainLoop pays the XLA compile (or the program-
        cache hit), so its wall-clock lands in a separate histogram
        instead of polluting the steady-state distribution.

        The same split feeds the goodput ledger (docs/observability.md):
        a cold epoch's non-feed wall is billed as compile (it contains
        the program build), warm epochs as productive step time."""
        from rafiki_tpu.obs.ledger import ledger

        # lint: disable=RF007 — epoch wall split into ledger buckets
        dt = time.monotonic() - t0
        cold = not getattr(self, "_warm", False)
        self._warm = True
        telemetry.observe("train.cold_epoch_s" if cold else "train.epoch_s", dt)
        if feed_s > 0.0:
            telemetry.inc("train.host_feed_s", feed_s)
            ledger.add("feed_s", feed_s)
        telemetry.inc("train.step_s", max(dt - feed_s, 0.0))
        ledger.add("compile_s" if cold else "step_s", max(dt - feed_s, 0.0))
        # Perf sentinel: step sampling + EWMA/MAD anomaly detection per
        # program, and an SLO evaluation tick (both cheap when idle).
        from rafiki_tpu.obs.perf import profiler, slo

        profiler.note_epoch(self._perf_key, dt, feed_s=feed_s, cold=cold)
        slo.maybe_tick()

    def evaluate(self, dataset, batch_size: int) -> float:
        total_correct = jnp.zeros((), jnp.int32)
        total = jnp.zeros((), jnp.int32)
        start = 0
        if self._fits_device_fast_path(dataset) and dataset.size >= batch_size:
            # Full batches in one device-side scan; the remainder falls
            # through to the per-batch path below.
            X, Y = get_device_dataset(dataset)
            n_steps = dataset.size // batch_size
            idx = np.arange(n_steps * batch_size, dtype=np.int32).reshape(
                n_steps, batch_size)
            c, n = self.program.eval_epoch(self.state[0], X, Y, idx)
            total_correct, total = total_correct + c, total + n
            start = n_steps * batch_size
        # (correct, valid) accumulate as device scalars; the adds
        # dispatch asynchronously and the host syncs ONCE at the end
        # (a per-batch int() sync would serialize host<->device).
        for batch in dataset.batches(batch_size, shuffle=False, drop_remainder=False,
                                     start=start):
            dev_batch = self.plan.put_batch(batch)
            c, n = self._eval_step(self.state[0], dev_batch)
            total_correct = total_correct + c
            total = total + n
        return int(total_correct) / max(int(total), 1)

    def predict_proba(self, x: np.ndarray, batch_size: int, extra: Optional[Batch] = None) -> np.ndarray:
        """Forward a query array; pads to full batches, returns (N, ..., C) probs."""
        n = x.shape[0]
        outs = []
        for start in range(0, n, batch_size):
            chunk = x[start : start + batch_size]
            pad = batch_size - chunk.shape[0]
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, axis=0)])
            batch = {"x": chunk}
            if extra:
                batch.update(extra)
            probs = np.asarray(self._predict(self.state[0], self.plan.put_batch(batch)))
            outs.append(probs[: batch_size - pad] if pad else probs)
        return np.concatenate(outs) if outs else np.zeros((0,))


# ---------------------------------------------------------------------------
# Trial packing: k same-program trials vectorized into one XLA program
# ---------------------------------------------------------------------------
#
# The program cache makes back-to-back same-shape trials compile-free,
# but one Rafiki-scale trial stream nowhere near saturates a chip's
# MXU. PackedProgram vmaps the SAME per-trial step closures over a
# leading trial axis: k learning rates, warmups, dropouts and rng
# streams advance in lockstep inside one jit'd (donated) program, and
# the pack shares one device-resident dataset upload. Per-trial
# identity is preserved exactly — trial i's params, rng chain and
# shuffle order match what a serial TrainLoop(seed_i) would produce —
# so scores are comparable to serial runs within numeric tolerance.
#
# Packing composes with the program cache, not with the dp mesh:
# a packed trial is single-device by construction (the trial axis IS
# the parallelism), and multihost SPMD groups must keep packing off
# (docs/trial_packing.md).


class PackedProgram:
    """The compiled half of a k-trial pack: vmapped, jit'd steps.

    Safe to share (via the process-wide program cache) across packs
    whose traced computation AND pack width k are identical; per-pack
    state lives in :class:`PackedTrainLoop`.
    """

    def __init__(self, init_fn, apply_fn, loss_fn: LossFn,
                 optimizer: optax.GradientTransformation, k: int,
                 dynamic_lr: bool = True):
        if k < 1:
            raise ValueError(f"pack width k={k} must be >= 1")
        self.k = k
        self.plan = _ShardingPlan.build(None)  # packing is single-device
        self.optimizer = optimizer
        self.dynamic_lr = dynamic_lr
        self.apply_fn = apply_fn
        train_step, eval_step, predict, init_all = _make_step_fns(
            init_fn, apply_fn, loss_fn, optimizer, dynamic_lr)

        # Trial axis 0 everywhere in the carried state; eval/predict
        # share one batch across trials (in_axes=(0, None)) while the
        # train step feeds each trial ITS OWN batch so per-trial
        # shuffle order matches a serial run.
        v_train = jax.vmap(train_step)
        v_eval = jax.vmap(eval_step, in_axes=(0, None))
        v_predict = jax.vmap(predict, in_axes=(0, None))
        v_init = jax.vmap(init_all)

        def packed_train_epoch(state, X, Y, idx, poison=None):
            # idx: (n_steps, k, batch) int32 — per-trial permutations.
            # poison: optional (n_steps, k) chaos train.nan multipliers;
            # vmap hands each member its own column, so one sick member
            # cannot perturb its pack-mates (ones-column = bit-exact).
            def body(st, xs):
                ib, pz = xs
                batch = {"x": jnp.take(X, ib, axis=0),
                         "y": jnp.take(Y, ib, axis=0)}
                if pz is not None:
                    batch["_health_poison"] = pz
                return v_train(st, batch)

            state, ms = jax.lax.scan(body, state, (idx, poison))
            # Final-step metrics per trial: each value is (k,); the
            # health series reduces per member on-device.
            rest, health = _sentinel.split(ms)
            out = {key: v[-1] for key, v in rest.items()}
            out.update(_sentinel.reduce_epoch(health))
            return state, out

        def packed_eval_epoch(params, X, Y, idx):
            # idx: (n_steps, batch) — eval order is shared (no shuffle).
            def body(carry, ib):
                batch = {"x": jnp.take(X, ib, axis=0),
                         "y": jnp.take(Y, ib, axis=0)}
                c, n = v_eval(params, batch)
                return (carry[0] + c, carry[1] + n), None

            zero = jnp.zeros((k,), jnp.int32)
            (c, n), _ = jax.lax.scan(body, (zero, zero), idx)
            return c, n

        self.train_step = jax.jit(v_train, donate_argnums=(0,))
        self.eval_step = jax.jit(v_eval)
        self.predict = jax.jit(v_predict)
        self.init = jax.jit(v_init)
        self.train_epoch = jax.jit(packed_train_epoch, donate_argnums=(0,))
        self.eval_epoch = jax.jit(packed_eval_epoch)


def packed_program_key(program_key: Hashable, k: int, dynamic_lr: bool) -> Hashable:
    """Cache key for a PackedProgram. Structurally distinct from the
    unpacked key form ``(program_key, mesh_key, dynamic_lr)`` — the
    leading tag guarantees packed and unpacked programs never collide
    in the process-wide cache even for identical base keys."""
    return ("packed", int(k), program_key, bool(dynamic_lr))


class PackedTrainLoop:
    """Per-pack state driving a (possibly cached) PackedProgram.

    Parameters mirror :class:`TrainLoop`, pluralized: ``seeds`` is the
    k per-trial init seeds; ``hypers`` the k per-trial dynamic-scalar
    dicts (identical key sets — a structural requirement, since the
    hyper dict's keys are part of the trace). Trial i of the pack is
    bit-for-bit the same *computation* as ``TrainLoop(seed=seeds[i],
    hyper=hypers[i])`` — only batched.
    """

    def __init__(self, init_fn, apply_fn, loss_fn, optimizer=None,
                 seeds: Optional[list] = None,
                 hypers: Optional[list] = None,
                 program_key: Optional[Hashable] = None,
                 packing_key: Optional[str] = None):
        if not seeds:
            raise ValueError("PackedTrainLoop needs at least one seed")
        # The repr of the members' shared Model.packing_key — stamped
        # onto every perf/step record so the train twin can bucket
        # step-time calibration per (packing_key, k) (docs/twin.md).
        self.packing_key = packing_key
        self.k = len(seeds)
        hypers = hypers if hypers is not None else [{} for _ in seeds]
        if len(hypers) != self.k:
            raise ValueError(f"{len(hypers)} hyper dicts for {self.k} seeds")
        keysets = {tuple(sorted(h)) for h in hypers}
        if len(keysets) != 1:
            raise ValueError(
                f"pack members carry different hyper keys {sorted(keysets)}; "
                f"the hyper dict's key set is part of the traced program")
        dynamic_lr = "lr" in hypers[0]
        if optimizer is None:
            optimizer = optax.scale_by_adam() if dynamic_lr else optax.adam(1e-3)
        # The build inputs outlive __init__: evict/admit change the pack
        # width k, and width is part of the packed program key, so every
        # re-pack fetches (or builds) the program at the new width.
        self._fns = (init_fn, apply_fn, loss_fn, optimizer)
        self._program_key = program_key
        self._dynamic_lr = dynamic_lr
        self._set_program()
        # Per-member numerics health (docs/health.md): a pack never
        # raises on divergence — run_epoch stashes per-member verdicts
        # on ``last_verdicts`` and the pack driver (train_packed)
        # evicts only the sick member.
        self.health = HealthMonitor(str(self._perf_key), k=self.k)
        self.last_verdicts: list = [None] * self.k

        # Per-trial rng derivation matches TrainLoop exactly: key(seed)
        # split once; row 0 carries on as the step rng, row 1 seeds init.
        keys = jnp.stack([jax.random.PRNGKey(int(s)) for s in seeds])
        split = jax.vmap(jax.random.split)(keys)  # (k, 2, key)
        rngs, init_rngs = split[:, 0], split[:, 1]
        params, opt_state = self.program.init(init_rngs)
        hyper_dev = {name: jnp.asarray([float(h[name]) for h in hypers],
                                       jnp.float32)
                     for name in hypers[0]}
        self.state = (params, opt_state, jnp.zeros((self.k,), jnp.int32),
                      rngs, hyper_dev)

    def _set_program(self) -> None:
        """(Re)fetch the PackedProgram at the CURRENT width self.k —
        the packed cache key includes k, so a width change after
        evict/admit compiles (once, then cached) a new program while
        per-trial math stays bit-identical (vmap width never enters the
        per-trial computation)."""
        init_fn, apply_fn, loss_fn, optimizer = self._fns
        k, dynamic_lr = self.k, self._dynamic_lr

        def build() -> PackedProgram:
            return PackedProgram(init_fn, apply_fn, loss_fn, optimizer, k,
                                 dynamic_lr=dynamic_lr)

        if self._program_key is not None:
            self._perf_key = packed_program_key(self._program_key, k, dynamic_lr)
            self.program = get_program(self._perf_key, build)
        else:
            self._perf_key = ("packed", "anon", id(self), k)
            self.program = build()
        self.plan = self.program.plan
        self.optimizer = self.program.optimizer

    # -- elastic membership (docs/mesh_sweep.md) -----------------------------

    def evict(self, i: int):
        """Slice member ``i`` out of the stacked state and narrow the
        pack to k-1. Returns the evicted member's serial-shaped state
        (leading trial axis removed) — exactly what a serial
        ``TrainLoop`` carrying that trial would hold, so the caller can
        adopt it via ``TrainLoop(initial_state=...)`` or checkpoint it.

        Used for straggler eviction (a member's early-stop fires epochs
        before its pack-mates) and for re-packing after a lost chip.
        """
        if not (0 <= i < self.k):
            raise IndexError(f"evict {i} out of pack of {self.k}")
        if self.k == 1:
            raise ValueError("cannot evict the last pack member")
        evicted = jax.tree.map(lambda a: a[i], self.state)
        self.state = jax.tree.map(
            lambda a: jnp.concatenate([a[:i], a[i + 1:]], axis=0), self.state)
        self.k -= 1
        self._set_program()
        self.health.evict_member(i)
        if i < len(self.last_verdicts):
            self.last_verdicts.pop(i)
        telemetry.inc("trial_pack.evictions")
        return evicted

    def admit(self, seed: int, hyper: Dict[str, float]) -> int:
        """Backfill one slot: append a fresh member initialized exactly
        as a serial ``TrainLoop(seed=seed, hyper=hyper)`` would be and
        widen the pack to k+1. Returns the new member's slot index.

        The hyper key set must match the pack's (it is part of the
        traced state structure).
        """
        have = tuple(sorted(self.state[4]))
        want = tuple(sorted(hyper))
        if have != want:
            raise ValueError(
                f"backfill hyper keys {want} != pack hyper keys {have}")
        keys = jnp.stack([jax.random.PRNGKey(int(seed))])
        split = jax.vmap(jax.random.split)(keys)
        rngs, init_rngs = split[:, 0], split[:, 1]
        params, opt_state = self.program.init(init_rngs)
        member = (params, opt_state, jnp.zeros((1,), jnp.int32), rngs,
                  {name: jnp.asarray([float(hyper[name])], jnp.float32)
                   for name in hyper})
        self.state = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=0), self.state, member)
        self.k += 1
        self._set_program()
        self.health.admit_member()
        self.last_verdicts.append(None)
        telemetry.inc("trial_pack.backfills")
        return self.k - 1

    # -- per-trial views -----------------------------------------------------

    def trial_params(self, i: int):
        """Trial i's parameter pytree (device slices of the stacked leaves)."""
        return jax.tree.map(lambda a: a[i], self.state[0])

    def trial_state(self, i: int):
        """Trial i's full (params, opt_state, step, rng, hyper) state,
        shaped exactly like a serial TrainLoop's."""
        return jax.tree.map(lambda a: a[i], self.state)

    def slice(self, i: int) -> "PackedSliceLoop":
        return PackedSliceLoop(self, i)

    # -- epochs --------------------------------------------------------------

    def _fits_device_fast_path(self, dataset) -> bool:
        return (getattr(dataset, "mask", None) is None
                and dataset.x.nbytes + dataset.y.nbytes <= device_dataset_cap_bytes())

    def run_epoch(self, dataset, batch_size: int, epoch_seeds) -> list:
        """One epoch for every trial in the pack; ``epoch_seeds`` is the
        k per-trial shuffle seeds (serial parity: ``seed_i + epoch``).
        Returns a list of k per-trial final-step metric dicts."""
        if len(epoch_seeds) != self.k:
            raise ValueError(f"{len(epoch_seeds)} epoch seeds for pack of {self.k}")
        if dataset.size < batch_size:
            raise ValueError(
                f"Dataset has {dataset.size} examples < batch_size={batch_size}; "
                f"the epoch would run zero steps")
        # Pre-epoch stacked-state snapshot for replay capsules (sliced
        # per sick member only on trip); banked before the timer so the
        # copy never pollutes step_s. See TrainLoop.run_epoch.
        snap = self.health.snapshot_state(self.state)
        t_epoch = time.monotonic()
        # Same in-timed-region chaos site as the serial loop: injected
        # delays here are visible to the anomaly detector.
        from rafiki_tpu import chaos as _chaos

        _chaos.hook("train.epoch", key=str(self._perf_key))
        n_steps = dataset.size // batch_size
        # (n_steps, k, batch): step-major so lax.scan walks steps while
        # each trial keeps its own serial-identical permutation.
        idx = np.stack([
            np.random.default_rng(int(s)).permutation(dataset.size)
            [: n_steps * batch_size].reshape(n_steps, batch_size)
            for s in epoch_seeds], axis=1).astype(np.int32)
        poison = self._chaos_poison(n_steps)
        if self._fits_device_fast_path(dataset):
            X, Y = get_device_dataset(dataset)
            if not getattr(self, "_warm", False):
                from rafiki_tpu.obs.perf import profiler as _profiler

                _profiler.capture_cost(self._perf_key,
                                       self.program.train_epoch,
                                       self.state, X, Y, idx, poison,
                                       kind="packed", k=self.k)
            self.state, metrics = self.program.train_epoch(
                self.state, X, Y, idx, poison)
            self._record_epoch(t_epoch)
            host = {key: np.asarray(jax.device_get(v)) for key, v in metrics.items()}
            rows = [{key: float(v[i]) for key, v in host.items()}
                    for i in range(self.k)]
            return self._health_check(rows, t_epoch, epoch_seeds, idx,
                                      poison, snap)
        metrics = None
        health_steps = []
        for t in range(n_steps):
            ib = idx[t]  # (k, batch)
            batch = {"x": jnp.asarray(dataset.x[ib]),
                     "y": jnp.asarray(dataset.y[ib])}
            if poison is not None:
                batch["_health_poison"] = jnp.asarray(poison[t])
            self.state, metrics = self.program.train_step(self.state, batch)
            # (k,) device vectors appended as-is — the health series
            # syncs once, at the epoch-boundary reduction below.
            health_steps.append({k: v for k, v in metrics.items()
                                 if k.startswith(_sentinel.PREFIX)})
        self._record_epoch(t_epoch)
        reduced = _sentinel.reduce_epoch(
            {k: jnp.stack([h[k] for h in health_steps])
             for k in health_steps[0]})
        host = {key: np.asarray(jax.device_get(v))
                for key, v in metrics.items()
                if not key.startswith(_sentinel.PREFIX)}
        host.update({key: np.asarray(jax.device_get(v))
                     for key, v in reduced.items()})
        rows = [{key: float(v[i]) for key, v in host.items()}
                for i in range(self.k)]
        return self._health_check(rows, t_epoch, epoch_seeds, idx,
                                  poison, snap)

    def _chaos_poison(self, n_steps: int) -> np.ndarray:
        """Per-member ``train.nan`` poison plane: each live member is a
        distinct hook key (``<perf_key>@m<i>`` — ``@`` because the spec
        grammar reserves ``:``), so a chaos spec's ``match=@m2`` selects
        WHICH pack member diverges. The matrix is ALWAYS present
        (all-ones when quiet) for the same single-trace reason as the
        serial column — see :meth:`TrainLoop._chaos_poison`. Members
        whose column stays all-ones are bit-unaffected (the multiply is
        exact and the trace is uniform) — the isolation the
        nan-trial-contained scenario pins."""
        from rafiki_tpu import chaos as _chaos

        poison = np.ones((n_steps, self.k), np.float32)
        if _chaos.active() is not None:
            hit = [i for i in range(self.k)
                   if _chaos.hook("train.nan",
                                  key=f"{self._perf_key}@m{i}") is not None]
            poison[n_steps // 2, hit] = np.nan
        return poison

    def _health_check(self, rows: list, t0: float, epoch_seeds, idx,
                      poison, snapshot) -> list:
        """Epoch-boundary health gate, pack flavor: strip the sentinel
        keys from the per-member metric rows and stash one
        Optional[verdict] per live slot on ``last_verdicts``. A pack
        never raises — survivors must keep training; the pack driver
        evicts sick members (docs/health.md)."""
        health_rows = [{k: v for k, v in r.items()
                        if k.startswith(_sentinel.PREFIX)} for r in rows]
        clean = [{k: v for k, v in r.items()
                  if not k.startswith(_sentinel.PREFIX)} for r in rows]
        self.last_verdicts = self.health.observe_pack(
            health_rows, t0=t0, epoch_seeds=epoch_seeds, idx=idx,
            poison=poison, snapshot=snapshot)
        return clean

    def _record_epoch(self, t0: float) -> None:
        from rafiki_tpu.obs.ledger import ledger

        # lint: disable=RF007 — epoch wall split into ledger buckets
        dt = time.monotonic() - t0
        cold = not getattr(self, "_warm", False)
        self._warm = True
        telemetry.observe("train.packed_cold_epoch_s" if cold
                          else "train.packed_epoch_s", dt)
        # Goodput ledger: same convention as the serial loop — the cold
        # (compile-paying) epoch is overhead, warm epochs are productive.
        ledger.add("compile_s" if cold else "step_s", dt)
        from rafiki_tpu.obs.perf import profiler, slo

        profiler.note_epoch(self._perf_key, dt, cold=cold,
                            kind="packed", k=self.k,
                            packing_key=self.packing_key)
        slo.maybe_tick()

    def evaluate(self, dataset, batch_size: int) -> np.ndarray:
        """(k,) per-trial accuracies over one shared eval pass: the
        batch stream is uploaded/gathered ONCE and every trial's params
        score it inside one vmapped program."""
        total_correct = jnp.zeros((self.k,), jnp.int32)
        total = jnp.zeros((self.k,), jnp.int32)
        start = 0
        if self._fits_device_fast_path(dataset) and dataset.size >= batch_size:
            X, Y = get_device_dataset(dataset)
            n_steps = dataset.size // batch_size
            idx = np.arange(n_steps * batch_size, dtype=np.int32).reshape(
                n_steps, batch_size)
            c, n = self.program.eval_epoch(self.state[0], X, Y, idx)
            total_correct, total = total_correct + c, total + n
            start = n_steps * batch_size
        for batch in dataset.batches(batch_size, shuffle=False,
                                     drop_remainder=False, start=start):
            dev_batch = self.plan.put_batch(batch)
            c, n = self.program.eval_step(self.state[0], dev_batch)
            total_correct = total_correct + c
            total = total + n
        c = np.asarray(jax.device_get(total_correct), dtype=np.float64)
        n = np.asarray(jax.device_get(total), dtype=np.float64)
        return c / np.maximum(n, 1.0)


class PackedSliceLoop:
    """A per-trial, TrainLoop-shaped view over a PackedTrainLoop.

    Exposes exactly the surface JaxModel touches after training
    (``params``/``state``/``evaluate``/``predict_proba``), so a model
    trained inside a pack dumps, scores and serves through the same
    code paths as a serially-trained one. Mutating entry points
    (run_epoch) are deliberately absent: per-trial training continues
    only through the pack.
    """

    def __init__(self, packed: PackedTrainLoop, index: int):
        if not (0 <= index < packed.k):
            raise IndexError(f"slice {index} out of pack of {packed.k}")
        self.packed = packed
        self.index = index
        self.plan = packed.plan

    @property
    def params(self):
        return self.packed.trial_params(self.index)

    @property
    def state(self):
        return self.packed.trial_state(self.index)

    def evaluate(self, dataset, batch_size: int) -> float:
        # The packed evaluator scores all k trials in one pass; callers
        # wanting every score should use PackedTrainLoop.evaluate once
        # instead of k slice evaluates (the jit cache makes the repeat
        # calls cheap, not free).
        return float(self.packed.evaluate(dataset, batch_size)[self.index])

    def predict_proba(self, x: np.ndarray, batch_size: int,
                      extra: Optional[Batch] = None) -> np.ndarray:
        n = x.shape[0]
        outs = []
        for start in range(0, n, batch_size):
            chunk = x[start : start + batch_size]
            pad = batch_size - chunk.shape[0]
            if pad:
                chunk = np.concatenate([chunk, np.repeat(chunk[-1:], pad, axis=0)])
            batch = {"x": chunk}
            if extra:
                batch.update(extra)
            probs = np.asarray(
                self.packed.program.predict(self.packed.state[0],
                                            self.plan.put_batch(batch))[self.index])
            outs.append(probs[: batch_size - pad] if pad else probs)
        return np.concatenate(outs) if outs else np.zeros((0,))


# ---------------------------------------------------------------------------
# Standalone builders (legacy surface; Program is the primary API)
# ---------------------------------------------------------------------------


def make_train_step(loss_fn: LossFn, optimizer: optax.GradientTransformation,
                    plan: _ShardingPlan, dynamic_lr: bool = False):
    """Build a donated, jit'd SGD step.

    NOTE (contract change vs round 1): the carried state is now the
    5-tuple (params, opt_state, step, rng, hyper) — ``hyper`` may be
    an empty dict when no dynamic hyperparameters are used.
    """
    prog = Program(lambda rng: None, lambda p, b: None, loss_fn, optimizer,
                   plan, dynamic_lr=dynamic_lr)
    return prog.train_step


def make_eval_step(apply_fn, plan: _ShardingPlan):
    """Jit'd eval step returning (#correct, #valid) device scalars."""
    prog = Program(lambda rng: None, apply_fn,
                   lambda p, b, r, h: (jnp.float32(0.0), {}),
                   optax.identity(), plan, dynamic_lr=False)
    return prog.eval_step


def make_predict_fn(apply_fn, plan: _ShardingPlan):
    """Jit'd forward returning probabilities."""
    prog = Program(lambda rng: None, apply_fn,
                   lambda p, b, r, h: (jnp.float32(0.0), {}),
                   optax.identity(), plan, dynamic_lr=False)
    return prog.predict
