"""rafiki_tpu: a TPU-native distributed AutoML framework.

A ground-up JAX/XLA re-design of the capabilities of Rafiki (reference:
wanliuhuo/rafiki, a fork of nginyc/rafiki, VLDB 2018 — see SURVEY.md):
an AutoML service where a Bayesian *advisor* proposes hyperparameter
("knob") configurations, parallel *train workers* run one trial per TPU
chip (with optional within-trial data parallelism over ICI), a *meta
store* persists trials and parameters, and a *predictor* serves the
top-k trials behind a sharded batched ensemble forward pass.

Layer map (bottom → top), mirroring SURVEY.md §1:
  store/      — meta store (sqlite3) + params store  [ref: rafiki/db/]
  model/      — model contract, knobs, datasets, dev harness [ref: rafiki/model/]
  ops/        — jit'd train/eval/predict step factories (JAX compute path)
  parallel/   — meshes, data-parallel training, ensemble sharding
  advisor/    — ask/tell HPO engines (random, GP-EI)  [ref: rafiki/advisor/]
  worker/     — train + inference workers             [ref: rafiki/worker/]
  scheduler/  — one-trial-per-chip schedulers         [ref: Docker Swarm + services_manager]
  bus/        — query/prediction bus                  [ref: rafiki/cache/ (Redis)]
  predictor/  — ensemble predictor frontend           [ref: rafiki/predictor/]
  admin/      — control plane + REST                  [ref: rafiki/admin/]
  client/     — client SDK                            [ref: rafiki/client/]
  utils/      — auth (JWT), logging, misc
"""

__version__ = "0.1.0"
