"""The bus: per-worker query queues + per-query prediction slots.

Interface (mirrors the reference's Cache verbs, SURVEY.md §2):
  add_worker(job_id, worker_id)          — register a live worker
  get_workers(job_id)                    — running-worker set
  remove_worker(job_id, worker_id)
  add_query(worker_id, query_id, query)  — predictor → worker fan-out
  pop_queries(worker_id, max_n, timeout) — worker batch pull
  put_prediction(query_id, worker_id, prediction)
  get_predictions(query_id, n, timeout)  — predictor gather-wait
"""

from __future__ import annotations

import queue
import threading
import time
from collections import defaultdict, deque
from typing import Any, Dict, List, Optional, Tuple


class InProcBus:
    _EXPIRED_CAP = 4096  # remembered timed-out query ids (leak guard)

    def __init__(self):
        # Queues exist exactly while their worker is registered:
        # created in add_worker, destroyed in remove_worker, and
        # add_query drops (rather than resurrects) queries to dead
        # workers — otherwise repeated inference-job cycles would leak
        # one queue per retired worker id.
        self._queues: Dict[str, queue.Queue] = {}
        self._preds: Dict[str, list] = {}
        self._pred_cv = threading.Condition()
        self._workers: Dict[str, set] = defaultdict(set)
        self._expired: "deque[str]" = deque(maxlen=self._EXPIRED_CAP)
        self._expired_set: set = set()
        self._lock = threading.Lock()

    # -- worker registry -----------------------------------------------------

    def add_worker(self, job_id: str, worker_id: str) -> None:
        with self._lock:
            self._workers[job_id].add(worker_id)
            self._queues.setdefault(worker_id, queue.Queue())

    def remove_worker(self, job_id: str, worker_id: str) -> None:
        with self._lock:
            self._workers[job_id].discard(worker_id)
            self._queues.pop(worker_id, None)

    def get_workers(self, job_id: str) -> List[str]:
        with self._lock:
            return sorted(self._workers[job_id])

    # -- queries -------------------------------------------------------------

    def add_query(self, worker_id: str, query_id: str, query: Any) -> None:
        with self._lock:
            q = self._queues.get(worker_id)
        if q is not None:  # dead worker → drop; the gather just sees n-1
            q.put((query_id, query))

    def pop_queries(self, worker_id: str, max_n: int = 64,
                    timeout: float = 0.1) -> List[Tuple[str, Any]]:
        """Block up to ``timeout`` for the first query, then drain up to
        max_n without blocking — natural micro-batching for the device."""
        with self._lock:
            q = self._queues.get(worker_id)
        if q is None:  # not registered (stopped): nothing to serve
            time.sleep(min(timeout, 0.05))
            return []
        out: List[Tuple[str, Any]] = []
        try:
            out.append(q.get(timeout=timeout))
        except queue.Empty:
            return out
        while len(out) < max_n:
            try:
                out.append(q.get_nowait())
            except queue.Empty:
                break
        return out

    # -- predictions ---------------------------------------------------------

    def put_prediction(self, query_id: str, worker_id: str, prediction: Any) -> None:
        with self._pred_cv:
            if query_id in self._expired_set:
                return  # late answer to a timed-out query: drop, don't leak
            self._preds.setdefault(query_id, []).append((worker_id, prediction))
            self._pred_cv.notify_all()

    def get_predictions(self, query_id: str, n: int,
                        timeout: float = 10.0) -> List[Tuple[str, Any]]:
        """Wait until n predictions arrived (or timeout); pops the slot.
        After this returns, late answers for query_id are discarded."""
        deadline = time.monotonic() + timeout
        with self._pred_cv:
            while len(self._preds.get(query_id, [])) < n:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._pred_cv.wait(remaining)
            if len(self._expired) == self._expired.maxlen:
                self._expired_set.discard(self._expired[0])
            self._expired.append(query_id)
            self._expired_set.add(query_id)
            return self._preds.pop(query_id, [])


def make_mp_bus(manager=None):
    """A multiprocessing-shared bus with the same interface.

    Built on a ``multiprocessing.Manager`` so predictor and inference
    workers can run as separate processes on the TPU host — the
    deployment shape the reference achieves with Redis.
    """
    import multiprocessing as mp

    # spawn, not fork: JAX is multithreaded and fork() can deadlock.
    manager = manager or mp.get_context("spawn").Manager()
    return _MpBus(manager)


class _MpBus:
    def __init__(self, manager):
        self._manager = manager
        self._queues = manager.dict()   # worker_id -> manager.Queue
        self._preds = manager.dict()    # query_id -> manager.list
        self._workers = manager.dict()  # job_id -> manager.list
        self._expired = manager.dict()  # gathered/timed-out query ids
        self._lock = manager.Lock()

    def _q(self, worker_id: str):
        with self._lock:
            q = self._queues.get(worker_id)
            if q is None:
                q = self._manager.Queue()
                self._queues[worker_id] = q
        return q

    def add_worker(self, job_id, worker_id):
        with self._lock:
            ws = self._workers.get(job_id)
            if ws is None:
                ws = self._manager.list()
                self._workers[job_id] = ws
            if worker_id not in list(ws):
                ws.append(worker_id)

    def remove_worker(self, job_id, worker_id):
        with self._lock:
            ws = self._workers.get(job_id)
            if ws is not None and worker_id in list(ws):
                ws.remove(worker_id)

    def get_workers(self, job_id):
        ws = self._workers.get(job_id)
        return sorted(list(ws)) if ws is not None else []

    def add_query(self, worker_id, query_id, query):
        self._q(worker_id).put((query_id, query))

    def pop_queries(self, worker_id, max_n=64, timeout=0.1):
        import queue as q_mod

        q = self._q(worker_id)
        out = []
        try:
            out.append(q.get(timeout=timeout))
        except q_mod.Empty:
            return out
        while len(out) < max_n:
            try:
                out.append(q.get_nowait())
            except q_mod.Empty:
                break
        return out

    def put_prediction(self, query_id, worker_id, prediction):
        with self._lock:
            if query_id in self._expired:
                return  # late answer to a timed-out query: drop, don't leak
            preds = self._preds.get(query_id)
            if preds is None:
                preds = self._manager.list()
                self._preds[query_id] = preds
            preds.append((worker_id, prediction))

    def get_predictions(self, query_id, n, timeout=10.0):
        deadline = time.monotonic() + timeout
        while True:
            preds = self._preds.get(query_id)
            if preds is not None and len(preds) >= n:
                break
            if time.monotonic() >= deadline:
                break
            time.sleep(0.005)
        with self._lock:
            preds = self._preds.pop(query_id, None)
            self._expired[query_id] = True
            if len(self._expired) > 4096:
                self._expired.clear()  # coarse cap; stale ids just re-leak one slot
        return list(preds) if preds is not None else []
